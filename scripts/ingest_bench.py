#!/usr/bin/env python
"""Out-of-core ingest benchmark: two-round (streaming) loading of a
multi-GB synthetic TSV with bounded memory.

The reference's precedent is two-round loading + PipelineReader
(dataset_loader.cpp:170-185, utils/pipeline_reader.h): stream the file
twice instead of materializing text + parsed floats.  This script
measures our equivalent at real scale and reports ONE JSON line:

  {"bytes": ..., "rows": ..., "wall_s": ..., "mb_per_s": ...,
   "max_rss_mb": ..., "import_rss_mb": ...}

Usage:
  python scripts/ingest_bench.py --mb 150          # quick
  python scripts/ingest_bench.py --gb 5            # the VERDICT-scale run
  python scripts/ingest_bench.py --mb 150 --one-round   # comparison

The synthetic file tiles a ~4 MB block of random rows (content variety
only matters for bin finding, which samples anyway); generation is
IO-bound and the file is cached in .bench_cache/ by size."""

import argparse
import json
import os
import resource
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
CACHE = os.path.join(REPO, ".bench_cache")
N_FEAT = 28

# ingest is host-only; keep the remote TPU tunnel (and its RSS/latency
# noise) out of the measurement — sitecustomize pins JAX_PLATFORMS, so
# flip via jax.config before any backend init
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def ensure_file(target_bytes: int) -> str:
    os.makedirs(CACHE, exist_ok=True)
    path = os.path.join(CACHE, "ingest_%d.tsv" % target_bytes)
    if os.path.exists(path) and os.path.getsize(path) >= target_bytes:
        return path
    rng = np.random.RandomState(0)
    rows = 20000
    x = rng.randn(rows, N_FEAT).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    block = "\n".join(
        "\t".join([str(y[i])] + ["%.4f" % v for v in x[i]])
        for i in range(rows)) + "\n"
    block_b = block.encode()
    with open(path, "wb") as f:
        written = 0
        while written < target_bytes:
            f.write(block_b)
            written += len(block_b)
    return path


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=float, default=0)
    ap.add_argument("--gb", type=float, default=0)
    ap.add_argument("--one-round", action="store_true")
    ap.add_argument("--shards", default="",
                    help="out-of-core mode: ingest into this shard "
                         "directory (lightgbm_tpu/ingest) instead of "
                         "loading an in-memory Dataset")
    ap.add_argument("--budget-mb", type=int, default=0,
                    help="ingest_memory_budget_mb for --shards")
    ap.add_argument("--workers", type=int, default=1,
                    help="ingest_workers for --shards (1 = inline, "
                         "so --trace-peak sees every allocation)")
    ap.add_argument("--trace-peak", action="store_true",
                    help="tracemalloc the load and report peak_py_mb: the "
                         "loader's OWN allocation high-water (numpy buffers "
                         "register with tracemalloc), immune to the "
                         "allocator-arena / suite-load noise that makes an "
                         "OS-RSS assertion flaky.  Off by default — tracing "
                         "slows the throughput numbers.")
    args = ap.parse_args()
    target = int(args.gb * (1 << 30) + args.mb * (1 << 20)) or (150 << 20)
    path = ensure_file(target)

    import_rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.io.dataset import load_dataset
    import_rss = max(import_rss,
                     resource.getrusage(resource.RUSAGE_SELF).ru_maxrss)

    params = {
        "is_save_binary_file": "false",
        "use_two_round_loading": "false" if args.one_round else "true"}
    if args.shards:
        params["ingest_workers"] = str(args.workers)
        if args.budget_mb:
            params["ingest_memory_budget_mb"] = str(args.budget_mb)
    cfg = Config.from_params(params)
    if args.trace_peak:
        import tracemalloc
        tracemalloc.start()
    t0 = time.time()
    if args.shards:
        from lightgbm_tpu.ingest.writer import ingest
        rows = ingest([path], args.shards, cfg).num_rows
        mode = "ingest_shards"
    else:
        rows = load_dataset(path, cfg).num_data
        mode = "one_round" if args.one_round else "two_round"
    wall = time.time() - t0
    size = os.path.getsize(path)
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    rss = max(rss, resource.getrusage(resource.RUSAGE_CHILDREN).ru_maxrss)
    rec = {
        "bytes": size, "rows": rows,
        "wall_s": round(wall, 2),
        "mb_per_s": round(size / (1 << 20) / wall, 2),
        "max_rss_mb": round(rss / 1024, 1),
        "import_rss_mb": round(import_rss / 1024, 1),
        "mode": mode,
    }
    if args.trace_peak:
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        rec["peak_py_mb"] = round(peak / (1 << 20), 1)
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
