#!/usr/bin/env python
"""Per-phase timing + MFU accounting for the fused training iteration
(VERDICT r1 #5).

Measures, on the current default JAX backend at the bench shape
(N x F, num_leaves=63, max_bin=255 by default):

  - matmul_peak_tflops: empirical best-case f32 MXU throughput on this
    chip (8k^3 dense matmul) — the utilization denominator, so no
    hardware spec sheet is assumed.
  - hist_sweep_ms / hist_tflops / hist_mfu: one full-row Pallas radix
    histogram sweep; FLOPs counted as the ACTUAL MXU work (including the
    off-diagonal waste blocks) and as USEFUL FLOPs (diagonal only, 1/4),
    giving both machine utilization and algorithmic efficiency.
  - xla_hist_ms: the one-hot matmul oracle (ops/histogram.py) at the
    same shape — quantifies what the radix kernel buys at F=28 and
    F=512.
  - phase split of one boosting iteration: gradients / tree growth
    (histograms+scan+partition) / score+valid updates + packing, from
    nested timed jits; plus the fused single-dispatch iteration they
    compose into.

Prints ONE JSON line.  Run with BENCH_ROWS / PROFILE_FEATS to vary the
shape; results are recorded in BASELINE.md.
"""

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the environment pins JAX_PLATFORMS to the TPU tunnel at interpreter
# start; PROFILE_DEVICE=cpu flips the platform the supported way (before
# backend init), like the CLI's device_type=cpu
if os.environ.get("PROFILE_DEVICE"):
    import jax as _jax
    _jax.config.update("jax_platforms", os.environ["PROFILE_DEVICE"])

N_ROWS = int(os.environ.get("BENCH_ROWS", 1_000_000))
MAX_BIN = 255
NUM_LEAVES = 63


def _force(out):
    """Full completion barrier that works through the remote TPU tunnel:
    block_until_ready alone has been observed returning early there, so
    read one scalar back to the host."""
    import jax
    import jax.numpy as jnp
    jax.block_until_ready(out)
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(leaf).astype(jnp.float32))


def timed(fn, *args, reps=10):
    """Per-call device time through a HIGH-LATENCY tunnel: the ~200 ms
    host<->device round trip dwarfs sub-ms kernels, so measure one call
    (T1 = rtt + t) and a chain of `reps` calls with a single readback
    (TK = rtt + reps*t; same-stream calls serialize on device) and take
    the slope (TK - T1) / (reps - 1)."""
    out = fn(*args)
    _force(out)
    t0 = time.time()
    out = fn(*args)
    _force(out)
    t1 = time.time() - t0
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args)
    _force(out)
    tk = time.time() - t0
    return max((tk - t1) / (reps - 1), 1e-9)


def main() -> int:
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops import hist_pallas as hp
    from lightgbm_tpu.ops.histogram import leaf_histogram, make_gvals

    backend = jax.default_backend()
    rng = np.random.RandomState(0)
    res = {"backend": backend, "rows": N_ROWS}

    # empirical matmul peaks: utilization denominators.  f32 dots run the
    # MXU in multiple passes; bf16 is the single-pass peak, which is the
    # right ceiling for the histogram kernel's one-hot dots (XLA may run
    # them at bf16-class rates since one-hots are exactly representable)
    k = 4096 if backend != "tpu" else 8192
    a = jnp.asarray(rng.randn(k, k), dtype=jnp.float32)
    mm = jax.jit(lambda x: x @ x)
    mm_s = timed(mm, a, reps=5)
    res["matmul_peak_f32_tflops"] = round(2 * k**3 / mm_s / 1e12, 2)
    ab = a.astype(jnp.bfloat16)
    mmb = jax.jit(lambda x: jax.lax.dot(x, x,
                                        preferred_element_type=jnp.float32))
    mmb_s = timed(mmb, ab, reps=5)
    res["matmul_peak_bf16_tflops"] = round(2 * k**3 / mmb_s / 1e12, 2)
    peak_tflops = max(2 * k**3 / mm_s, 2 * k**3 / mmb_s) / 1e12

    for f in (28, 512):
        n = N_ROWS if f == 28 else max(N_ROWS // 8, 1 << 17)
        n = (n // hp.PALLAS_ROW_BLOCK) * hp.PALLAS_ROW_BLOCK
        bins = jnp.asarray(rng.randint(0, MAX_BIN, size=(f, n)),
                           dtype=jnp.uint8)
        grad = jnp.asarray(rng.randn(n), dtype=jnp.float32)
        hess = jnp.ones(n, dtype=jnp.float32)
        gh2 = hp.make_gh2(grad, hess)
        mask = jnp.ones(n, dtype=bool)

        pallas_fn = jax.jit(lambda b, g, m: hp.leaf_histogram_pallas(
            b, g, m, max_bin=MAX_BIN))
        p_s = timed(pallas_fn, bins, gh2, mask, reps=200)

        # actual MXU FLOPs: per grid step, ceil(fb/4) block-diagonal
        # [96, blk] x [blk, 128] matmuls over every row block
        fb = hp._feat_block(f)
        n_mm = -(-fb // hp.MM_FEATS) * -(-f // fb)
        flops = 2 * hp.M_ROWS * hp.N_COLS * n * n_mm
        useful = flops / (hp.MM_FEATS ** 2) * hp.MM_FEATS  # diagonal 1/4
        key = "F%d" % f
        res[key] = {
            "rows": n,
            "pallas_sweep_ms": round(p_s * 1e3, 3),
            "actual_tflops": round(flops / p_s / 1e12, 2),
            "mxu_utilization": round(flops / p_s / 1e12 / peak_tflops, 3),
            "useful_tflops": round(useful / p_s / 1e12, 2),
            "hbm_gb_per_s": round((f * n + 12 * n) / p_s / 1e9, 1),
        }

        gvals = make_gvals(grad, hess, mask, jnp.float32)
        xla_fn = jax.jit(lambda b, g: leaf_histogram(b, g, max_bin=MAX_BIN))
        try:
            x_s = timed(xla_fn, bins, gvals, reps=20)
            res[key]["xla_onehot_ms"] = round(x_s * 1e3, 3)
            res[key]["pallas_speedup_vs_xla"] = round(x_s / p_s, 2)
        except Exception as e:  # OOM at F=512 x 1M is expected on CPU
            res[key]["xla_onehot_ms"] = None
            res[key]["xla_error"] = str(e)[:80]

    # ---- phase split of one boosting iteration at the bench shape ----
    from lightgbm_tpu.config import Config
    from lightgbm_tpu.models.gbdt import create_boosting, _make_fused_step
    from lightgbm_tpu.objectives import create_objective
    from lightgbm_tpu.ops.grow import grow_tree
    from lightgbm_tpu.ops.split import SplitParams
    import bench

    x, y = bench.make_data()
    cfg = Config.from_params(bench._params())
    ds = bench.build_dataset(cfg, x, y)
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    booster = create_boosting(cfg, ds, obj)
    booster.train_one_iter(None, None, False)   # compile + warm state
    jax.block_until_ready(booster.scores)

    grad_fn = jax.jit(obj.make_grad_fn())
    t_grad = timed(grad_fn, booster.scores[0], obj.grad_state(), reps=100)

    grow_kw = dict(max_leaves=NUM_LEAVES, max_bin=booster.max_bin,
                   params=booster.params, max_depth=cfg.max_depth,
                   hist_impl=booster.hist_impl,
                   hist_slots=booster.hist_slots)
    g, h = grad_fn(booster.scores[0], obj.grad_state())
    bag = jnp.ones(booster.n_pad, dtype=bool)
    fmask = jnp.ones(ds.num_features, dtype=bool)
    grow_fn = jax.jit(lambda *a: grow_tree(*a, **grow_kw))
    t_grow = timed(grow_fn, booster.bins_dev, g.astype(booster.dtype),
                   h.astype(booster.dtype), bag, fmask, reps=5)

    fused = _make_fused_step(obj.make_grad_fn(), grow_kw,
                             booster.shrinkage_rate, booster.dtype)

    def fused_once(scores, bag, fmask, bins, gstate):
        return fused(scores, [], bag, fmask, bins, (), gstate)

    # donated buffers chain naturally (out feeds the next call): time a
    # 1-call and a reps-call chain, one readback each, take the slope
    s = jnp.array(booster.scores)
    out = fused_once(s, bag, fmask, booster.bins_dev, obj.grad_state())
    _force(out)
    t0 = time.time()
    out = fused_once(out[0], bag, fmask, booster.bins_dev,
                     obj.grad_state())
    _force(out)
    t1 = time.time() - t0
    reps = 10
    t0 = time.time()
    for _ in range(reps):
        out = fused_once(out[0], bag, fmask, booster.bins_dev,
                         obj.grad_state())
    _force(out)
    t_fused = max((time.time() - t0 - t1) / (reps - 1), 1e-9)

    res["phase_ms"] = {
        "gradients": round(t_grad * 1e3, 2),
        "grow_tree_hist_scan_partition": round(t_grow * 1e3, 2),
        "fused_full_iteration": round(t_fused * 1e3, 2),
        "score_update_pack_overhead": round((t_fused - t_grow - t_grad)
                                            * 1e3, 2),
    }
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
