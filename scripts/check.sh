#!/usr/bin/env bash
# scripts/check.sh — THE single pre-merge check entry point.
#
#   1. scripts/lint.sh        graftlint + graftcheck + typegate (always;
#                             stdlib-only), ruff/mypy when installed,
#                             baseline-gated (analysis/baseline.json)
#   2. repo-is-clean pytest gates:
#        tests/test_graftlint.py             rule power + repo clean sweep
#        tests/test_graftcheck.py            call graph + contract rules
#        tests/test_graftsync.py             SPMD collective-sequence +
#                                            lock-order rules (GC009-12),
#                                            runtime collective tracer,
#                                            2-process static-vs-runtime
#                                            cross-check (slow-marked leg)
#        tests/test_graftcheck_mutations.py  seeded-violation harness:
#                                            every contract class catches
#                                            its bug class, clean tree
#                                            stays clean
#   3. scripts/chaos_smoke.sh (when jax imports): kill-resume round
#      trip byte-identity, corrupt-snapshot skip, serving overload
#      shedding, degraded-mode fallback — the fast cousin of the
#      slow-marked tests/test_chaos.py suite
#   4. scripts/serve_smoke.sh (when jax imports): serve round trip +
#      reload byte parity, then the multi-process front-end leg —
#      4 SO_REUSEPORT workers, SIGKILL-under-load respawn, per-worker
#      liveness on /metrics
#   5. scripts/ingest_smoke.sh (when jax imports): out-of-core ingest
#      SIGKILL + resume byte identity, shard-fed vs text training and
#      predict byte parity
#   6. scripts/refresh_smoke.sh (when jax imports): continuous
#      train->deploy — ingest -> warm-start retrain -> shadow-eval ->
#      promote with byte-compares vs task=predict, plus the SIGKILL-at-
#      deploy.push chaos leg (champion keeps serving byte-identically,
#      the rerun converges and promotes)
#
# Exit codes:
#   0  everything that ran is clean
#   1  findings / test failures
#   2  a tool crashed (treat as failure, not as clean)
#
# Full tier-1 (slow, needs jax) stays `python -m pytest tests/ -m "not
# slow"` — this script is the fast gate that runs everywhere, including
# jax-free lanes.

set -u
cd "$(dirname "$0")/.."

rc=0

bash scripts/lint.sh
l=$?
if [ "$l" -ge 2 ]; then
    echo "check.sh: lint.sh crashed (exit $l)" >&2
    exit 2
fi
[ "$l" -ne 0 ] && rc=1

echo "== repo-is-clean pytest gates (graftlint + graftcheck + mutations) =="
if command -v python >/dev/null 2>&1 && python -c "import pytest" 2>/dev/null; then
    python -m pytest tests/test_graftlint.py tests/test_graftcheck.py \
        tests/test_graftsync.py tests/test_graftcheck_mutations.py \
        -q -p no:cacheprovider
    p=$?
    if [ "$p" -ge 2 ]; then
        echo "check.sh: pytest crashed (exit $p)" >&2
        exit 2
    fi
    [ "$p" -ne 0 ] && rc=1
else
    echo "== pytest: not installed — SKIPPED (lint.sh covered the stdlib gates) =="
fi

echo "== chaos smoke (kill-resume + overload + degraded mode) =="
if python -c "import jax" 2>/dev/null; then
    bash scripts/chaos_smoke.sh
    c=$?
    [ "$c" -ne 0 ] && rc=1
    echo "== serve smoke (round trip + reload + multi-process front-end) =="
    bash scripts/serve_smoke.sh
    s=$?
    [ "$s" -ne 0 ] && rc=1
    echo "== ingest smoke (kill-resume byte identity + shard-fed train parity) =="
    bash scripts/ingest_smoke.sh
    g=$?
    [ "$g" -ne 0 ] && rc=1
    echo "== refresh smoke (warm-start retrain + shadow-eval promote + kill-at-push chaos) =="
    bash scripts/refresh_smoke.sh
    r=$?
    [ "$r" -ne 0 ] && rc=1
else
    echo "== jax not importable — chaos_smoke + serve_smoke + ingest_smoke + refresh_smoke SKIPPED (jax-free lane) =="
fi

if [ "$rc" -eq 0 ]; then
    echo "check.sh: clean"
else
    echo "check.sh: FINDINGS (exit 1)" >&2
fi
exit $rc
