#!/usr/bin/env python
"""Measure the primitives for leaf-proportional histogram sweeps (r3).

The round-2 negative result (BASELINE.md "hist_compact") ruled out
per-split XLA gather compaction.  The remaining design (VERDICT r2 #1)
is an ORDERED PARTITION: stable-sort rows by leaf at a few scheduled
points per tree, after which each leaf occupies a contiguous range and a
sweep touches only its blocks.  Whether that wins is decided by:

  t_rep   = argsort(leaf [N] i32) + take(bins [F,N] u8) + take(gh2)
  t_sweep(k) = ranged Pallas sweep over k of nblocks row blocks
               (inactive grid steps revisit the last block: no DMA,
               no matmul -- cost is the grid-step overhead)

This script times both on the attached TPU with the tunnel-safe slope
protocol (see scripts/phase_profile.py docstring).  Prints one JSON line.
"""

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

if os.environ.get("PROFILE_DEVICE"):
    import jax as _jax
    _jax.config.update("jax_platforms", os.environ["PROFILE_DEVICE"])

N = int(os.environ.get("BENCH_ROWS", 1 << 20))
F = int(os.environ.get("PROFILE_FEATS", 28))
MAX_BIN = 255


def _force(out):
    import jax
    import jax.numpy as jnp
    jax.block_until_ready(out)
    leaf = jax.tree_util.tree_leaves(out)[0]
    float(jnp.sum(leaf).astype(jnp.float32))


def timed(fn, *args, reps=10):
    out = fn(*args)
    _force(out)
    t0 = time.time()
    out = fn(*args)
    _force(out)
    t1 = time.time() - t0
    t0 = time.time()
    for _ in range(reps):
        out = fn(*args)
    _force(out)
    tk = time.time() - t0
    return max((tk - t1) / (reps - 1), 1e-9)


def main() -> int:
    import jax
    import jax.numpy as jnp

    from lightgbm_tpu.ops import hist_pallas as hp

    backend = jax.default_backend()
    rng = np.random.RandomState(0)
    res = {"backend": backend, "rows": N, "feats": F}
    n = (N // hp.PALLAS_ROW_BLOCK) * hp.PALLAS_ROW_BLOCK
    nblocks = n // hp.PALLAS_ROW_BLOCK

    bins = jnp.asarray(rng.randint(0, MAX_BIN, size=(F, n)), dtype=jnp.uint8)
    grad = jnp.asarray(rng.randn(n), dtype=jnp.float32)
    hess = jnp.asarray(rng.rand(n), dtype=jnp.float32)
    gh2 = jax.jit(hp.make_gh2)(grad, hess)
    leaf = jnp.asarray(rng.randint(0, 64, size=n), dtype=jnp.int32)
    interp = backend == "cpu"

    # 1) full masked sweep (the r2 baseline)
    full = jax.jit(lambda b, g, l: hp.leaf_histogram_masked(
        b, g, l, jnp.int32(3), max_bin=MAX_BIN, interpret=interp))
    res["full_sweep_ms"] = round(timed(full, bins, gh2, leaf) * 1e3, 3)

    # 2) ranged sweep at several active-block counts
    if hasattr(hp, "leaf_histogram_ranged"):
        for k in (nblocks, 16, 8, 1):
            fn = jax.jit(lambda b, g, l, k=k: hp.leaf_histogram_ranged(
                b, g, l, jnp.int32(3), jnp.int32(0), jnp.int32(k),
                max_bin=MAX_BIN, interpret=interp))
            res["ranged_%d_ms" % k] = round(timed(fn, bins, gh2, leaf) * 1e3,
                                            3)

    # 3) reorder primitives
    srt = jax.jit(lambda x: jnp.argsort(x, stable=True))
    res["argsort_ms"] = round(timed(srt, leaf) * 1e3, 3)
    perm = srt(leaf)

    tk_u8 = jax.jit(lambda b, p: jnp.take(b, p, axis=1))
    res["take_bins_u8_ms"] = round(timed(tk_u8, bins, perm) * 1e3, 3)
    tk_f32 = jax.jit(lambda g, p: jnp.take(g, p, axis=1))
    res["take_gh2_f32_ms"] = round(timed(tk_f32, gh2, perm) * 1e3, 3)
    tk_i32 = jax.jit(lambda l, p: jnp.take(l, p))
    res["take_leaf_i32_ms"] = round(timed(tk_i32, leaf, perm) * 1e3, 3)

    # sort-pairs alternative to argsort+takes: one lax.sort moving all
    # payloads (stable; leaf key ascending)
    def sort_all(l, b, g):
        ops = (l,) + tuple(b[i] for i in range(F)) + (g[0], g[1])
        out = jax.lax.sort(ops, num_keys=1, is_stable=True)
        return out[0], jnp.stack(out[1:1 + F]), jnp.stack(out[1 + F:])
    res["sort_pairs_ms"] = round(timed(jax.jit(sort_all), leaf, bins, gh2)
                                 * 1e3, 3)

    # scatter build of an inverse permutation
    sc = jax.jit(lambda d: jnp.zeros(n, jnp.int32).at[d].set(
        jnp.arange(n, dtype=jnp.int32)))
    res["scatter_i32_ms"] = round(timed(sc, perm) * 1e3, 3)

    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
