#!/usr/bin/env bash
# scripts/ingest_smoke.sh — fast out-of-core ingest round trip:
#
#   1. SIGKILL a CLI ingest at the ingest.shard_write seam (fault
#      injection), resume it, and byte-compare every shard + the
#      manifest against an uninterrupted ingest
#   2. train from the shard directory and from the text file —
#      model bytes must be IDENTICAL
#   3. task=predict with both models — output bytes must be IDENTICAL
#
# Nonzero exit on any mismatch.  The slow-marked cousins
# (tests/test_ingest_scale.py, tests/test_chaos.py) prove the same
# properties at scale; this is the pre-merge smoke.

set -u
cd "$(dirname "$0")/.."

TMP=$(mktemp -d)
trap 'rm -rf "$TMP"' EXIT
export JAX_PLATFORMS=cpu
unset LGBM_TPU_FAULTS 2>/dev/null || true

PY=python
DATA="$TMP/train.tsv"
$PY - "$DATA" <<'EOF'
import sys
import numpy as np
rng = np.random.RandomState(3)
n = 400
x = rng.randn(n, 6)
y = (x[:, 0] + 0.5 * x[:, 1] * x[:, 2] > 0).astype(int)
with open(sys.argv[1], "w") as f:
    for i in range(n):
        f.write("%d\t" % y[i] + "\t".join("%.6g" % v for v in x[i]) + "\n")
EOF

INGEST_ARGS="task=ingest data=$DATA ingest_workers=1 ingest_shard_rows=64"

echo "== ingest_smoke: clean ingest =="
$PY -m lightgbm_tpu $INGEST_ARGS "ingest_dir=$TMP/clean" \
    > "$TMP/log_clean.txt" 2>&1 || {
    echo "ingest_smoke: clean ingest FAILED" >&2
    cat "$TMP/log_clean.txt" >&2
    exit 1
}

echo "== ingest_smoke: SIGKILL at shard 3, then resume =="
LGBM_TPU_FAULTS="ingest.shard_write@3=kill" \
    $PY -m lightgbm_tpu $INGEST_ARGS "ingest_dir=$TMP/killed" \
    > "$TMP/log_kill.txt" 2>&1
rc=$?
if [ "$rc" -ne 137 ] && [ "$rc" -ne 265 ]; then
    echo "ingest_smoke: expected SIGKILL (137), got rc=$rc" >&2
    cat "$TMP/log_kill.txt" >&2
    exit 1
fi
if [ -f "$TMP/killed/manifest.json" ]; then
    echo "ingest_smoke: killed ingest left a COMMITTED manifest" >&2
    exit 1
fi
$PY -m lightgbm_tpu $INGEST_ARGS "ingest_dir=$TMP/killed" \
    > "$TMP/log_resume.txt" 2>&1 || {
    echo "ingest_smoke: resume FAILED" >&2
    cat "$TMP/log_resume.txt" >&2
    exit 1
}
grep -q "Resuming killed ingest" "$TMP/log_resume.txt" || {
    echo "ingest_smoke: resume did not take the resume path" >&2
    cat "$TMP/log_resume.txt" >&2
    exit 1
}
for f in "$TMP/clean"/shard_* "$TMP/clean/manifest.json"; do
    b="$TMP/killed/$(basename "$f")"
    cmp -s "$f" "$b" || {
        echo "ingest_smoke: $(basename "$f") differs after resume" >&2
        exit 1
    }
done

echo "== ingest_smoke: shard-fed vs text training byte parity =="
TRAIN_ARGS="task=train num_iterations=6 num_leaves=7 min_data_in_leaf=5 \
 min_sum_hessian_in_leaf=1 metric= bagging_fraction=0.8 bagging_freq=2 \
 feature_fraction=0.9 is_save_binary_file=false"
$PY -m lightgbm_tpu $TRAIN_ARGS "data=$DATA" \
    "output_model=$TMP/model_text.txt" > "$TMP/log_t1.txt" 2>&1 || {
    echo "ingest_smoke: text-path training FAILED" >&2
    cat "$TMP/log_t1.txt" >&2
    exit 1
}
$PY -m lightgbm_tpu $TRAIN_ARGS "data=$TMP/killed" \
    "output_model=$TMP/model_shards.txt" > "$TMP/log_t2.txt" 2>&1 || {
    echo "ingest_smoke: shard-fed training FAILED" >&2
    cat "$TMP/log_t2.txt" >&2
    exit 1
}
cmp -s "$TMP/model_text.txt" "$TMP/model_shards.txt" || {
    echo "ingest_smoke: shard-fed model differs from text-path model" >&2
    diff <(head -5 "$TMP/model_text.txt") \
         <(head -5 "$TMP/model_shards.txt") >&2 || true
    exit 1
}

echo "== ingest_smoke: predict byte parity =="
for m in model_text model_shards; do
    $PY -m lightgbm_tpu task=predict "data=$DATA" \
        "input_model=$TMP/$m.txt" "output_result=$TMP/$m.pred" \
        > "$TMP/log_p_$m.txt" 2>&1 || {
        echo "ingest_smoke: predict with $m FAILED" >&2
        cat "$TMP/log_p_$m.txt" >&2
        exit 1
    }
done
cmp -s "$TMP/model_text.pred" "$TMP/model_shards.pred" || {
    echo "ingest_smoke: predictions differ between models" >&2
    exit 1
}

echo "ingest_smoke: PASS (kill-resume byte identity, shard-fed train parity, predict parity)"
exit 0
