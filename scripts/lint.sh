#!/usr/bin/env bash
# scripts/lint.sh — static-analysis entry point for CI and humans.
# (scripts/check.sh wraps this plus the repo-clean pytest gates.)
#
#   graftlint + graftcheck + typegate   always (stdlib-only,
#       python -m lightgbm_tpu.analysis, gated against the checked-in
#       analysis/baseline.json so only NEW findings fail)
#   ruff                   when installed ([tool.ruff] in pyproject.toml)
#   mypy --strict gate     when installed ([tool.mypy] in pyproject.toml)
#
# Tools missing from the environment are reported as SKIPPED and do not
# fail the run (the containers bake no ruff/mypy; the stdlib gates cover
# the invariants regardless).
#
# Exit codes (CI gates on these):
#   0  everything that ran is clean
#   1  findings (lint violations, stale/bare suppressions, typing gaps)
#   2  internal error (a tool crashed — treat as failure, not as clean)

set -u
cd "$(dirname "$0")/.."

rc=0

echo "== graftlint + graftcheck + typing gate (python -m lightgbm_tpu.analysis) =="
python -m lightgbm_tpu.analysis --baseline lightgbm_tpu/analysis/baseline.json
g=$?
if [ "$g" -ge 2 ]; then
    echo "lint.sh: graftlint crashed (exit $g)" >&2
    exit 2
fi
[ "$g" -ne 0 ] && rc=1

if command -v ruff >/dev/null 2>&1; then
    echo "== ruff check lightgbm_tpu =="
    ruff check lightgbm_tpu
    r=$?
    if [ "$r" -ge 2 ]; then
        echo "lint.sh: ruff crashed (exit $r)" >&2
        exit 2
    fi
    [ "$r" -ne 0 ] && rc=1
else
    echo "== ruff: not installed — SKIPPED (config lives in [tool.ruff]) =="
fi

if command -v mypy >/dev/null 2>&1; then
    echo "== mypy --strict gate (config.py, api.py, serving/) =="
    mypy --config-file pyproject.toml
    m=$?
    if [ "$m" -ge 2 ]; then
        echo "lint.sh: mypy crashed (exit $m)" >&2
        exit 2
    fi
    [ "$m" -ne 0 ] && rc=1
else
    echo "== mypy: not installed — SKIPPED (config lives in [tool.mypy];" \
         "the typegate above enforces the annotation floor) =="
fi

if [ "$rc" -eq 0 ]; then
    echo "lint.sh: clean"
else
    echo "lint.sh: FINDINGS (exit 1)" >&2
fi
exit $rc
