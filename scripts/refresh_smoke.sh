#!/bin/bash
# refresh_smoke.sh — end-to-end smoke of continuous train->deploy
# (lightgbm_tpu/refresh/), the fast cousin of the slow-marked
# tests/test_refresh.py leg:
#
#   1. train a champion on a slice, serve it, and capture the
#      task=predict bytes for the held-out rows;
#   2. drop fresh data and run ONE refresh cycle with the CHAOS kill
#      armed at deploy.push@1: the agent ingests the drop
#      (refresh_ingest=true -> task=ingest shard pass), warm-start
#      retrains from the champion (init_model continued training over
#      the shard directory), then dies the instant it would push —
#      the fleet must still answer BYTE-identically to the champion;
#   3. rerun the agent clean: the interrupted cycle replays
#      deterministically (ingest -> retrain -> push -> shadow-eval ->
#      promote), and the served bytes flip to exactly what
#      task=predict writes under the promoted challenger.
#
# Exits nonzero on any mismatch.  Stdlib-only clients (no curl).
#
# Usage: scripts/refresh_smoke.sh      (from the repo root or anywhere)

set -u
here="$(CDPATH= cd -- "$(dirname -- "$0")/.." && pwd)"
PY="${PYTHON:-python3}"
export PYTHONPATH="$here${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
# jaxlib 0.4.36's persistent compilation cache corrupts the heap on the
# CPU backend (see tests/conftest.py); smoke runs don't need
# cold-compile amortization.
export LGBM_TPU_NO_COMPILE_CACHE="${LGBM_TPU_NO_COMPILE_CACHE:-1}"

work="$(mktemp -d)"
server_pid=""
cleanup() {
    [ -n "$server_pid" ] && kill -9 "$server_pid" 2>/dev/null
    rm -rf "$work"
}
trap cleanup EXIT

die() { echo "refresh_smoke: FAIL: $*" >&2; exit 1; }

# -- fixture: base slice, drop batch, held-out eval rows ---------------
"$PY" - "$work" <<'EOF' || die "fixture generation"
import os, sys, numpy as np
work = sys.argv[1]
rng = np.random.RandomState(11)
n = 900
x = rng.randn(n, 6)
y = (x[:, 0] + 0.5 * x[:, 1] * x[:, 2] > 0).astype(int)
def dump(path, a, b):
    with open(path, "w") as f:
        for i in range(a, b):
            f.write("%d\t" % y[i]
                    + "\t".join("%.6g" % v for v in x[i]) + "\n")
dump(work + "/base.tsv", 0, 200)
os.makedirs(work + "/drop")
dump(work + "/drop/batch1.tsv", 200, 700)
dump(work + "/eval.tsv", 700, 900)
EOF

targs="objective=binary num_leaves=7 max_bin=63 min_data_in_leaf=20 metric= verbose=0"

# -- champion + its expected predict bytes -----------------------------
"$PY" -m lightgbm_tpu task=train "data=$work/base.tsv" \
    "output_model=$work/champion.txt" num_iterations=5 $targs \
    || die "champion training"
"$PY" -m lightgbm_tpu task=predict "data=$work/eval.tsv" \
    "input_model=$work/champion.txt" \
    "output_result=$work/want_champ.txt" verbose=0 \
    || die "task=predict (champion)"

# -- serve the champion ------------------------------------------------
port="$("$PY" -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1",0)); print(s.getsockname()[1]); s.close()')"
"$PY" -m lightgbm_tpu task=serve "input_model=$work/champion.txt" \
    "serve_port=$port" serve_batch_timeout_ms=1 serve_backend=native \
    > "$work/server.log" 2>&1 &
server_pid=$!

"$PY" - "$port" <<'EOF' || { cat "$work/server.log" >&2; die "server did not come up"; }
import sys, time, urllib.request
port = sys.argv[1]
deadline = time.time() + 120
while time.time() < deadline:
    try:
        urllib.request.urlopen("http://127.0.0.1:%s/healthz" % port,
                               timeout=2).read()
        sys.exit(0)
    except OSError:
        time.sleep(0.2)
sys.exit(1)
EOF

agent_args="task=refresh refresh_drop_dir=$work/drop \
refresh_serve_url=http://127.0.0.1:$port \
refresh_eval_data=$work/eval.tsv input_model=$work/champion.txt \
refresh_ingest=true refresh_max_cycles=1 refresh_period_s=0 \
refresh_poll_s=0.1 refresh_deadline_s=240 refresh_rounds=10 \
refresh_status_port=-1 $targs verbose=1"

# -- chaos leg: SIGKILL the agent the instant it would push ------------
LGBM_TPU_FAULTS="deploy.push@1=kill" \
    "$PY" -m lightgbm_tpu $agent_args > "$work/agent_kill.log" 2>&1
rc=$?
[ "$rc" -eq 137 ] || [ "$rc" -eq 265 ] \
    || { cat "$work/agent_kill.log" >&2; \
         die "expected the injected SIGKILL (exit $rc)"; }

"$PY" - "$port" "$work" champ <<'EOF' || { cat "$work/server.log" >&2; die "champion byte-compare after the killed refresh"; }
import sys, urllib.request
port, work, tag = sys.argv[1], sys.argv[2], sys.argv[3]
body = open(work + "/eval.tsv", "rb").read()
req = urllib.request.Request("http://127.0.0.1:%s/predict" % port,
                             data=body,
                             headers={"Content-Type": "text/plain"})
got = urllib.request.urlopen(req, timeout=120).read()
want = open(work + "/want_%s.txt" % tag, "rb").read()
assert got == want, "served bytes diverged from task=predict (%s)" % tag
EOF

# -- rerun converges: ingest -> retrain -> eval -> promote -------------
"$PY" -m lightgbm_tpu $agent_args > "$work/agent_ok.log" 2>&1 \
    || { cat "$work/agent_ok.log" >&2; die "refresh rerun"; }
grep -q "refresh cycle 0: promoted" "$work/agent_ok.log" \
    || { cat "$work/agent_ok.log" >&2; die "rerun did not promote"; }

chall="$work/drop/.refresh/challenger_0000.txt"
[ -f "$chall" ] || die "challenger model missing"
"$PY" -m lightgbm_tpu task=predict "data=$work/eval.tsv" \
    "input_model=$chall" "output_result=$work/want_chall.txt" \
    verbose=0 || die "task=predict (challenger)"

"$PY" - "$port" "$work" chall <<'EOF' || { cat "$work/server.log" >&2; die "challenger byte-compare after promotion"; }
import sys, urllib.request
port, work, tag = sys.argv[1], sys.argv[2], sys.argv[3]
body = open(work + "/eval.tsv", "rb").read()
req = urllib.request.Request("http://127.0.0.1:%s/predict" % port,
                             data=body,
                             headers={"Content-Type": "text/plain"})
got = urllib.request.urlopen(req, timeout=120).read()
want = open(work + "/want_%s.txt" % tag, "rb").read()
assert got == want, "served bytes diverged from task=predict (%s)" % tag
EOF

kill -TERM "$server_pid" 2>/dev/null
wait "$server_pid" 2>/dev/null
server_pid=""

echo "refresh_smoke: PASS (kill at deploy.push left the champion serving byte-identically; rerun promoted the challenger)"
