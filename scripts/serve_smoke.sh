#!/bin/bash
# serve_smoke.sh — end-to-end smoke of the task=serve subsystem:
# start the server, round-trip one predict (bytes must equal
# task=predict's), scrape /metrics, hot-swap via /reload (bytes must
# equal task=predict under the NEW model), then SIGTERM-drain.
# Then the multi-process leg (serving/frontend.py): start 4
# SO_REUSEPORT workers, byte-compare responses vs task=predict,
# SIGKILL one worker UNDER LOAD and assert the fleet keeps answering
# + the supervisor respawns the slot, scrape per-worker liveness from
# /metrics, then SIGTERM-drain the whole front-end.
# Exits nonzero on any mismatch.  Stdlib-only clients (no curl).
#
# Usage: scripts/serve_smoke.sh        (from the repo root or anywhere)

set -u
here="$(CDPATH= cd -- "$(dirname -- "$0")/.." && pwd)"
PY="${PYTHON:-python3}"
export PYTHONPATH="$here${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
# jaxlib 0.4.36's persistent compilation cache corrupts the heap on the
# CPU backend (see tests/conftest.py); smoke runs don't need
# cold-compile amortization.
export LGBM_TPU_NO_COMPILE_CACHE="${LGBM_TPU_NO_COMPILE_CACHE:-1}"

work="$(mktemp -d)"
server_pid=""
fe_pid=""
cleanup() {
    [ -n "$server_pid" ] && kill -9 "$server_pid" 2>/dev/null
    if [ -n "$fe_pid" ]; then
        # the front-end supervisor fans SIGTERM out to its workers;
        # give it a moment, then hard-kill the process group
        kill -TERM "$fe_pid" 2>/dev/null
        sleep 1
        kill -9 "$fe_pid" 2>/dev/null
    fi
    rm -rf "$work"
}
trap cleanup EXIT

die() { echo "serve_smoke: FAIL: $*" >&2; exit 1; }

# -- fixture: two tiny models + a request body -------------------------
"$PY" - "$work" <<'EOF' || die "fixture generation"
import sys, numpy as np
work = sys.argv[1]
model = """gbdt
num_class=1
label_index=0
max_feature_idx=3
sigmoid=1
objective=binary

Tree=0
num_leaves=3
split_feature=0 2
split_gain=1 0.5
threshold=0.5 -0.25
left_child=1 -2
right_child=-1 -3
leaf_parent=0 1 1
leaf_value=0.2 -0.13 0.34
internal_value=0 0.1

feature importance:
"""
open(work + "/model_a.txt", "w").write(model)
open(work + "/model_b.txt", "w").write(
    model.replace("leaf_value=0.2 -0.13 0.34",
                  "leaf_value=0.7 -0.6 0.5"))
rng = np.random.RandomState(0)
with open(work + "/data.tsv", "w") as f:
    for row in rng.randn(25, 4):
        f.write("0\t" + "\t".join("%.6g" % v for v in row) + "\n")
EOF

# -- expected bytes via the batch path ---------------------------------
for m in a b; do
    "$PY" -m lightgbm_tpu task=predict "data=$work/data.tsv" \
        "input_model=$work/model_$m.txt" \
        "output_result=$work/want_$m.txt" verbose=0 \
        || die "task=predict ($m)"
done

# -- start the server --------------------------------------------------
port="$("$PY" -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1",0)); print(s.getsockname()[1]); s.close()')"
"$PY" -m lightgbm_tpu task=serve "input_model=$work/model_a.txt" \
    "serve_port=$port" serve_batch_timeout_ms=1 \
    > "$work/server.log" 2>&1 &
server_pid=$!

"$PY" - "$port" <<'EOF' || { cat "$work/server.log" >&2; die "server did not come up"; }
import sys, time, urllib.request
port = sys.argv[1]
deadline = time.time() + 120
while time.time() < deadline:
    try:
        urllib.request.urlopen("http://127.0.0.1:%s/healthz" % port,
                               timeout=2).read()
        sys.exit(0)
    except OSError:
        time.sleep(0.2)
sys.exit(1)
EOF

# -- predict round trip + /metrics + /reload ---------------------------
"$PY" - "$port" "$work" <<'EOF' || { cat "$work/server.log" >&2; exit 1; }
import json, sys, urllib.request
port, work = sys.argv[1], sys.argv[2]
base = "http://127.0.0.1:%s" % port

def post(path, data, ctype="text/plain"):
    req = urllib.request.Request(base + path, data=data,
                                 headers={"Content-Type": ctype})
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.read()

def fail(msg):
    sys.stderr.write("serve_smoke: FAIL: %s\n" % msg)
    sys.exit(1)

body = open(work + "/data.tsv", "rb").read()
want_a = open(work + "/want_a.txt", "rb").read()
want_b = open(work + "/want_b.txt", "rb").read()

got = post("/predict", body)
if got != want_a:
    fail("served bytes differ from task=predict (model A)")

metrics = urllib.request.urlopen(base + "/metrics", timeout=60).read().decode()
for needle in ("lgbm_serve_rows_total 25",
               'lgbm_serve_requests_total{endpoint="/predict",code="200"} 1',
               "lgbm_serve_batches_total",
               "lgbm_serve_request_latency_seconds_count"):
    if needle not in metrics:
        fail("metrics scrape missing %r" % needle)

# -- single-row low-latency lane: byte-compare vs task=predict ---------
# a 1-row request routes through the synchronous fast lane (the 25-row
# body above exceeded the lane bound and batched); its bytes must be
# the matching line of task=predict's output
import time as _time
one = body.split(b"\n", 1)[0] + b"\n"
want_one = want_a.split(b"\n", 1)[0] + b"\n"
t0 = _time.monotonic()
got_one = post("/predict", one)
lat_ms = (_time.monotonic() - t0) * 1e3
if got_one != want_one:
    fail("fast-lane single-row bytes differ from task=predict")
metrics = urllib.request.urlopen(base + "/metrics", timeout=60).read().decode()
for needle in ('lgbm_serve_lane_requests_total{lane="fast"} 1',
               'lgbm_serve_lane_requests_total{lane="batch"} 1',
               "lgbm_serve_batcher_queue_depth 0",
               'lgbm_serve_lane_latency_seconds_count{lane="fast"} 1'):
    if needle not in metrics:
        fail("lane metrics scrape missing %r" % needle)
print("serve_smoke: fast-lane single row OK (%.2f ms)" % lat_ms)

info = json.loads(post("/reload",
                       json.dumps({"model": work + "/model_b.txt"}).encode(),
                       "application/json"))
if info.get("source") != work + "/model_b.txt":
    fail("reload did not report the new model: %r" % info)

got = post("/predict", body)
if got != want_b:
    fail("post-reload bytes differ from task=predict (model B)")
if got == want_a:
    fail("reload did not change predictions")

health = json.loads(urllib.request.urlopen(base + "/healthz",
                                           timeout=60).read())
if health.get("status") != "ok":
    fail("healthz not ok after reload: %r" % health)

# -- reload FAILURE: structured error, counted, old forest keeps serving
import urllib.error
try:
    post("/reload", json.dumps({"model": work + "/no_such_model.txt"}).encode(),
         "application/json")
    fail("reload of a missing model did not error")
except urllib.error.HTTPError as e:
    if e.code != 400:
        fail("reload failure status %d, want 400" % e.code)
    doc = json.loads(e.read())
    if not doc.get("error") or not doc.get("message"):
        fail("reload failure body not structured: %r" % doc)
metrics = urllib.request.urlopen(base + "/metrics", timeout=60).read().decode()
if "lgbm_serve_reload_failures_total 1" not in metrics:
    fail("lgbm_serve_reload_failures_total not incremented")
got = post("/predict", body)
if got != want_b:
    fail("old forest not serving after failed reload")
print("serve_smoke: predict + metrics + reload + reload-failure OK")
EOF
rc=$?
[ "$rc" -eq 0 ] || die "round trip (rc=$rc)"

# -- graceful drain ----------------------------------------------------
kill -TERM "$server_pid"
for _ in $(seq 1 100); do
    kill -0 "$server_pid" 2>/dev/null || break
    sleep 0.1
done
if kill -0 "$server_pid" 2>/dev/null; then
    die "server did not drain within 10s of SIGTERM"
fi
wait "$server_pid"
rc=$?
server_pid=""
[ "$rc" -eq 0 ] || die "server exited nonzero on SIGTERM drain (rc=$rc)"

# -- multi-process front-end leg (serving/frontend.py) -----------------
fe_port="$("$PY" -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1",0)); print(s.getsockname()[1]); s.close()')"
"$PY" -m lightgbm_tpu task=serve "input_model=$work/model_a.txt" \
    "serve_port=$fe_port" serve_workers=4 serve_batch_timeout_ms=1 \
    > "$work/frontend.log" 2>&1 &
fe_pid=$!

"$PY" - "$fe_port" <<'EOF' || { cat "$work/frontend.log" >&2; die "front-end did not come up"; }
import sys, time, urllib.request
port = sys.argv[1]
deadline = time.time() + 180
while time.time() < deadline:
    try:
        urllib.request.urlopen("http://127.0.0.1:%s/healthz" % port,
                               timeout=2).read()
        sys.exit(0)
    except OSError:
        time.sleep(0.2)
sys.exit(1)
EOF

"$PY" - "$fe_port" "$work" <<'EOF' || { tail -40 "$work/frontend.log" >&2; exit 1; }
import json, os, signal, sys, threading, time, urllib.request
port, work = sys.argv[1], sys.argv[2]
base = "http://127.0.0.1:%s" % port

def fail(msg):
    sys.stderr.write("serve_smoke: FAIL(frontend): %s\n" % msg)
    sys.exit(1)

def post_predict(body, timeout=60):
    req = urllib.request.Request(base + "/predict", data=body,
                                 headers={"Content-Type": "text/plain"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.read()

body = open(work + "/data.tsv", "rb").read()
want = open(work + "/want_a.txt", "rb").read()

# every connection may land on a different worker (SO_REUSEPORT picks
# per connection): bytes must match task=predict on all of them
for _ in range(8):
    if post_predict(body) != want:
        fail("front-end bytes differ from task=predict")

# discover the worker pids through repeated /healthz scrapes (each
# scrape is a fresh connection, so the kernel rotates us around the
# fleet) — all 4 should answer eventually
def scrape_pids(need, deadline_s=60):
    pids, deadline = {}, time.time() + deadline_s
    while len(pids) < need and time.time() < deadline:
        doc = json.loads(urllib.request.urlopen(
            base + "/healthz", timeout=10).read())
        w = doc.get("worker")
        if not w:
            fail("healthz has no worker identity: %r" % doc)
        pids[int(w["pid"])] = int(w["index"])
    return pids

pids = scrape_pids(4)
if len(pids) < 2:
    fail("only saw %d distinct worker pids via /healthz" % len(pids))

# per-worker liveness on /metrics
metrics = urllib.request.urlopen(base + "/metrics", timeout=30).read().decode()
if 'lgbm_serve_worker{index="' not in metrics:
    fail("metrics scrape missing lgbm_serve_worker liveness series")

# SIGKILL one worker UNDER LOAD: the fleet must keep answering
# byte-identically (only the victim's own connections may error) and
# the supervisor must respawn the slot
stop = threading.Event()
errors = []
def hammer():
    while not stop.is_set():
        try:
            if post_predict(body, timeout=30) != want:
                errors.append("bytes diverged under kill load")
                return
        except OSError:
            pass   # the killed worker's own connection: allowed
ts = [threading.Thread(target=hammer) for _ in range(4)]
for t in ts:
    t.start()
victim = sorted(pids)[0]
time.sleep(0.3)
os.kill(victim, signal.SIGKILL)
time.sleep(1.0)
stop.set()
for t in ts:
    t.join()
if errors:
    fail(errors[0])
# fleet still answers, and a NEW pid appears (the respawned slot)
if post_predict(body) != want:
    fail("front-end bytes differ after worker SIGKILL")
deadline = time.time() + 120
respawned = False
while time.time() < deadline:
    seen = scrape_pids(4, deadline_s=10)
    if victim in seen:
        seen.pop(victim)   # stale scrape raced the kill
    if any(p not in pids for p in seen):
        respawned = True
        break
    time.sleep(0.5)
if not respawned:
    fail("no respawned worker pid appeared within 120s of SIGKILL")
print("serve_smoke: front-end predict + kill-respawn + liveness OK")
EOF
rc=$?
[ "$rc" -eq 0 ] || die "front-end leg (rc=$rc)"

# -- front-end graceful drain ------------------------------------------
kill -TERM "$fe_pid"
for _ in $(seq 1 300); do
    kill -0 "$fe_pid" 2>/dev/null || break
    sleep 0.1
done
if kill -0 "$fe_pid" 2>/dev/null; then
    die "front-end did not drain within 30s of SIGTERM"
fi
wait "$fe_pid"
rc=$?
fe_pid=""
[ "$rc" -eq 0 ] || die "front-end exited nonzero on SIGTERM drain (rc=$rc)"

echo "serve_smoke: PASS"
