#!/bin/bash
# chaos_smoke.sh — end-to-end smoke of the fault-tolerance subsystem
# (lightgbm_tpu/resilience/), the fast cousin of the slow-marked
# tests/test_chaos.py suite:
#
#   1. kill-resume round trip: train, SIGKILL the process at a seeded
#      mid-run iteration via the fault-injection harness, restart with
#      resume=auto — the final model must be BYTE-identical to the
#      uninterrupted run's;
#   2. corrupt-snapshot skip: truncate the newest snapshot, resume must
#      reject it by name, fall back to the previous one, and still
#      finish byte-identical;
#   3. serving overload: with a tiny in-flight budget and concurrent
#      clients, shed requests get a fast 503 + Retry-After while every
#      accepted response carries exactly the task=predict bytes;
#   4. degraded mode: injected device-dispatch failures flip /healthz
#      to "degraded" with the JAX-free native fallback still serving
#      byte-correct answers.
#
# Exits nonzero on any mismatch.  Stdlib-only clients (no curl).
#
# Usage: scripts/chaos_smoke.sh        (from the repo root or anywhere)

set -u
here="$(CDPATH= cd -- "$(dirname -- "$0")/.." && pwd)"
PY="${PYTHON:-python3}"
export PYTHONPATH="$here${PYTHONPATH:+:$PYTHONPATH}"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
# jaxlib 0.4.36's persistent compilation cache corrupts the heap on the
# CPU backend (see tests/conftest.py — root-caused by bisection there);
# a corrupted training subprocess changes the trajectory mid-run and
# aborts at teardown, which this smoke would misreport as a resume
# defect.  Smoke runs don't need cold-compile amortization.
export LGBM_TPU_NO_COMPILE_CACHE="${LGBM_TPU_NO_COMPILE_CACHE:-1}"

work="$(mktemp -d)"
server_pid=""
cleanup() {
    [ -n "$server_pid" ] && kill -9 "$server_pid" 2>/dev/null
    rm -rf "$work"
}
trap cleanup EXIT

die() { echo "chaos_smoke: FAIL: $*" >&2; exit 1; }

# -- fixture -----------------------------------------------------------
"$PY" - "$work" <<'EOF' || die "fixture generation"
import sys, numpy as np
work = sys.argv[1]
rng = np.random.RandomState(7)
x = rng.randn(400, 6)
y = (x[:, 0] + 0.5 * x[:, 1] * x[:, 2] > 0).astype(int)
with open(work + "/train.tsv", "w") as f:
    for i in range(400):
        f.write("%d\t" % y[i] + "\t".join("%.6g" % v for v in x[i]) + "\n")
EOF

train_args="task=train data=$work/train.tsv objective=binary \
num_iterations=15 num_leaves=7 max_bin=63 min_data_in_leaf=20 metric= verbose=1"

# -- 1. kill-resume round trip -----------------------------------------
"$PY" -m lightgbm_tpu $train_args "output_model=$work/base.txt" \
    > "$work/base.log" 2>&1 || { cat "$work/base.log" >&2; die "base run"; }

chaos_args="$train_args output_model=$work/chaos.txt \
snapshot_period=3 snapshot_dir=$work/snaps resume=auto"
LGBM_TPU_FAULTS="flush.device_get@8=kill" \
    "$PY" -m lightgbm_tpu $chaos_args > "$work/kill.log" 2>&1
rc=$?
[ "$rc" -eq 137 ] || { cat "$work/kill.log" >&2; die "expected SIGKILL (137), got rc=$rc"; }
[ -e "$work/chaos.txt" ] && die "killed run committed a model file"

"$PY" -m lightgbm_tpu $chaos_args > "$work/resume.log" 2>&1 \
    || { cat "$work/resume.log" >&2; die "resume run"; }
grep -q "Resumed from snapshot" "$work/resume.log" \
    || die "resume run did not resume from a snapshot"
cmp -s "$work/base.txt" "$work/chaos.txt" \
    || die "kill-resume model differs from the uninterrupted run"
echo "chaos_smoke: kill-resume round trip byte-identical"

# -- 2. corrupt-snapshot skip ------------------------------------------
rm -f "$work/chaos.txt"
newest="$(ls "$work/snaps" | sort | tail -1)"
"$PY" - "$work/snaps/$newest" <<'EOF'
import sys
p = sys.argv[1]
raw = open(p, "rb").read()
open(p, "wb").write(raw[:len(raw)//2])   # truncate: mid-write crash shape
EOF
"$PY" -m lightgbm_tpu $chaos_args > "$work/resume2.log" 2>&1 \
    || { cat "$work/resume2.log" >&2; die "resume past corrupt snapshot"; }
grep -q "Skipping snapshot .*$newest" "$work/resume2.log" \
    || die "corrupt snapshot $newest not rejected by name"
cmp -s "$work/base.txt" "$work/chaos.txt" \
    || die "corrupt-skip resume model differs from the uninterrupted run"
echo "chaos_smoke: corrupt snapshot skipped, resume byte-identical"

# -- serving fixture: expected predict bytes ---------------------------
"$PY" -m lightgbm_tpu task=predict "data=$work/train.tsv" \
    "input_model=$work/base.txt" "output_result=$work/want.txt" verbose=0 \
    || die "task=predict"

start_server() {   # $1 extra params   $2 env fault spec
    port="$("$PY" -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1",0)); print(s.getsockname()[1]); s.close()')"
    LGBM_TPU_FAULTS="$2" "$PY" -m lightgbm_tpu task=serve \
        "input_model=$work/base.txt" "serve_port=$port" \
        serve_batch_timeout_ms=5 $1 > "$work/server.log" 2>&1 &
    server_pid=$!
    "$PY" - "$port" <<'EOF' || { cat "$work/server.log" >&2; die "server did not come up"; }
import sys, time, urllib.request
deadline = time.time() + 120
while time.time() < deadline:
    try:
        urllib.request.urlopen("http://127.0.0.1:%s/healthz" % sys.argv[1],
                               timeout=2).read()
        sys.exit(0)
    except OSError:
        time.sleep(0.2)
sys.exit(1)
EOF
}

stop_server() {
    kill -9 "$server_pid" 2>/dev/null
    wait "$server_pid" 2>/dev/null
    server_pid=""
}

# -- 3. overload: fast 503 + Retry-After, accepted bytes exact ---------
start_server "serve_max_inflight_rows=500" ""
"$PY" - "$port" "$work" <<'EOF' || { cat "$work/server.log" >&2; die "overload probe"; }
import json, sys, threading, urllib.error, urllib.request
port, work = sys.argv[1], sys.argv[2]
base = "http://127.0.0.1:%s" % port
body = open(work + "/train.tsv", "rb").read()
want = open(work + "/want.txt", "rb").read()
results = []
lock = threading.Lock()

def client():
    req = urllib.request.Request(base + "/predict", data=body)
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            out = (r.status, r.read(), dict(r.headers))
    except urllib.error.HTTPError as e:
        out = (e.code, e.read(), dict(e.headers))
    with lock:
        results.append(out)

threads = [threading.Thread(target=client) for _ in range(8)]
for t in threads: t.start()
for t in threads: t.join(120)

def fail(msg):
    sys.stderr.write("chaos_smoke: FAIL: %s\n" % msg)
    sys.exit(1)

if len(results) != 8:
    fail("a client hung under overload")
ok = shed = 0
for st, got, hdrs in results:
    if st == 200:
        ok += 1
        if got != want:
            fail("accepted request under overload returned bad bytes")
    elif st == 503:
        shed += 1
        if "Retry-After" not in hdrs:
            fail("503 without Retry-After")
        doc = json.loads(got)
        if not doc.get("error"):
            fail("503 body not structured: %r" % doc)
    else:
        fail("unexpected status %d" % st)
if not ok:
    fail("overload shed every request (budget admits an idle server)")
if not shed:
    fail("overload shed nothing (8 x 400 rows vs budget 500)")
print("chaos_smoke: overload shed %d/8, served %d/8 byte-exact" % (shed, ok))
EOF
rc=$?
stop_server
[ "$rc" -eq 0 ] || exit 1

# -- 4. degraded mode: breaker flips to the native fallback ------------
# serve_max_batch_rows=64 pins the warm-up to 3 row buckets = 3
# serve.dispatch hits, so the @4+ schedule spares startup and fails
# every post-warm device dispatch
start_server "serve_breaker_threshold=2 serve_backend=jax serve_max_batch_rows=64" \
    "serve.dispatch@4+=raise:injected device failure"
"$PY" - "$port" "$work" <<'EOF' || { cat "$work/server.log" >&2; die "degraded probe"; }
import json, sys, urllib.request
port, work = sys.argv[1], sys.argv[2]
base = "http://127.0.0.1:%s" % port
body = open(work + "/train.tsv", "rb").read()
want = open(work + "/want.txt", "rb").read()

def fail(msg):
    sys.stderr.write("chaos_smoke: FAIL: %s\n" % msg)
    sys.exit(1)

def post(path, data):
    req = urllib.request.Request(base + path, data=data)
    with urllib.request.urlopen(req, timeout=60) as r:
        return r.status, r.read()

# warm-up crossed serve.dispatch 3x (3 row buckets); hits 4+ fail, so
# both requests below fail on-device and are answered on the host path
for i in range(2):
    st, got = post("/predict", body)
    if st != 200 or got != want:
        fail("request %d during device failure: status %d or bad bytes" % (i, st))
health = json.loads(urllib.request.urlopen(base + "/healthz", timeout=60).read())
if health.get("status") != "degraded":
    fail("healthz not degraded after repeated dispatch failures: %r" % health)
metrics = urllib.request.urlopen(base + "/metrics", timeout=60).read().decode()
if "lgbm_serve_degraded 1" not in metrics:
    fail("lgbm_serve_degraded gauge not set")
st, got = post("/predict", body)
if st != 200 or got != want:
    fail("degraded-mode serving returned bad bytes")
print("chaos_smoke: degraded mode serves byte-exact on the native fallback")
EOF
rc=$?
stop_server
[ "$rc" -eq 0 ] || exit 1

echo "chaos_smoke: PASS"
