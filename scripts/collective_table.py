#!/usr/bin/env python
"""Per-split ICI collective-byte accounting at 8/64/256 virtual devices
(VERDICT r2 #7): compiles the data-parallel grower under hist_agg=psum,
hist_agg=scatter (owner-computes ReduceScatter protocol) and
tree_learner=voting, and sums the collective output bytes in the
OPTIMIZED HLO — the same methodology as
tests/test_parallel.py::test_scatter_halves_collective_bytes, not a
hand-derived formula.

Each device count needs its own process (the virtual CPU device count is
fixed at backend init), so the script re-execs itself per row.  Prints a
markdown table + one JSON line; results go into BASELINE.md.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

F = 28
MAX_BIN = 256
LEAVES = 63


def measure(ndev: int) -> dict:
    import jax
    # lock the backend to THIS process's forced device count BEFORE the
    # tests import below pulls in conftest (which appends its own
    # 8-device XLA flag — harmless once the backend exists)
    assert len(jax.devices()) == ndev, jax.devices()
    import jax.numpy as jnp
    import numpy as np
    from lightgbm_tpu.ops.split import SplitParams
    from lightgbm_tpu.parallel.mesh import ShardedGrower, make_mesh
    sys.path.insert(0, os.path.join(REPO, "tests"))
    from test_parallel import _collective_bytes

    params = SplitParams(5, 1e-3, 0.0, 0.0, 0.0)
    n = 64 * ndev
    rng = np.random.RandomState(0)
    bins_t = rng.randint(0, MAX_BIN, size=(F, n)).astype(np.uint8)
    res = {}
    for mode, kw in (("psum", dict(hist_agg="psum")),
                     ("scatter", dict(hist_agg="scatter")),
                     ("voting", dict(voting_top_k=8))):
        mesh = make_mesh(ndev)
        g = ShardedGrower(mesh, max_leaves=LEAVES, max_bin=MAX_BIN,
                          params=params, **kw)
        args = (g.shard_bins(bins_t),
                g.shard_rows(rng.randn(n), n),
                g.shard_rows(rng.rand(n) + 0.5, n),
                g.shard_rows(np.ones(n, dtype=bool), n),
                jnp.ones(F, dtype=bool))
        text = g._grow.lower(*args).compile().as_text()
        total, per_op = _collective_bytes(text)
        res[mode] = {"bytes": total, "per_op": per_op}
    # feature-parallel: per-split traffic is the candidate all-gather +
    # the owner's packed [N/8] go_right broadcast (VERDICT r3 weak #4 —
    # was a [N] i32 psum, 32x heavier)
    from lightgbm_tpu.parallel.mesh import (FEATURE_AXIS,
                                            FeatureShardedGrower)
    mesh = make_mesh(ndev, FEATURE_AXIS)
    g = FeatureShardedGrower(mesh, max_leaves=LEAVES, max_bin=MAX_BIN,
                             params=params)
    fpad = g.padded_features(F)
    bins_p = np.pad(bins_t, ((0, fpad - F), (0, 0)))
    fmask = np.pad(np.ones(F, dtype=bool), (0, fpad - F))
    args = (g.shard_bins(bins_p),
            g.shard_rows(rng.randn(n), n),
            g.shard_rows(rng.rand(n) + 0.5, n),
            g.shard_rows(np.ones(n, dtype=bool), n),
            g._put_feature_sharded(fmask))
    text = g._grow.lower(*args).compile().as_text()
    total, per_op = _collective_bytes(text)
    res["feature"] = {"bytes": total, "per_op": per_op}
    return res


def main() -> int:
    if len(sys.argv) > 1:           # child: one device count
        ndev = int(sys.argv[1])
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=%d" % ndev)
        import jax
        jax.config.update("jax_platforms", "cpu")
        print(json.dumps({"ndev": ndev, **measure(ndev)}))
        return 0

    rows = []
    for ndev in (8, 64, 256):
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), str(ndev)],
            capture_output=True, text=True, timeout=3600,
            env={k: v for k, v in os.environ.items()
                 if k not in ("XLA_FLAGS", "JAX_PLATFORMS")})
        if out.returncode != 0:
            sys.stderr.write(out.stdout + out.stderr)
            return 1
        rows.append(json.loads(out.stdout.strip().splitlines()[-1]))

    print("| devices | psum MB | scatter MB | voting MB | feature MB "
          "| scatter/psum |")
    print("|---|---|---|---|---|---|")
    for r in rows:
        p, s, v, fe = (r[m]["bytes"] / 1e6
                       for m in ("psum", "scatter", "voting", "feature"))
        print("| %d | %.2f | %.2f | %.2f | %.2f | %.2f |"
              % (r["ndev"], p, s, v, fe, s / p))
    print(json.dumps(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
