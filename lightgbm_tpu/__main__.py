"""CLI entry: python -m lightgbm_tpu key=value ..."""

__jax_free__ = True
import sys

from .cli import main

sys.exit(main())
