"""CLI entry: python -m lightgbm_tpu key=value ..."""
import sys

from .cli import main

sys.exit(main())
