"""Python API — the embedding surface replacing the reference C API.

Operation-for-operation equivalent of src/c_api.cpp / include/LightGBM/
c_api.h, exposed the way a Python framework should be (objects, numpy /
scipy matrices) instead of C handles:

  reference c_api.h                      here
  -------------------------------------  --------------------------------
  LGBM_CreateDatasetFromFile (:58)       Dataset(path, ...)
  LGBM_CreateDatasetFromBinaryFile(:72)  Dataset.load_binary(path)
  LGBM_CreateDatasetFromMat (:117)       Dataset(ndarray, ...)
  LGBM_CreateDatasetFromCSR (:86)        Dataset(csr_matrix, ...)
  LGBM_CreateDatasetFromCSC (:103)       Dataset(csc_matrix, ...)
  LGBM_DatasetSaveBinary (:140)          Dataset.save_binary(path)
  LGBM_DatasetSetField (:152)            Dataset.set_field / set_label ...
  LGBM_DatasetGetField (:166)            Dataset.get_field
  LGBM_DatasetGetNumData/Feature (:178)  Dataset.num_data / num_feature
  LGBM_BoosterCreate (:198)              Booster(params, train_set)
  LGBM_BoosterCreateFromModelfile(:209)  Booster(model_file=...)
  LGBM_BoosterAddValidData (:228)        Booster.add_valid
  LGBM_BoosterUpdateOneIter (:247)       Booster.update()
  LGBM_BoosterUpdateOneIterCustom(:259)  Booster.update(fobj=...)
  LGBM_BoosterEval (:285)                Booster.eval / eval_train/valid
  LGBM_BoosterPredict* (:313-368)        Booster.predict(raw_score=...,
                                           pred_leaf=...)
  LGBM_BoosterSaveModel (:383)           Booster.save_model
  (sample-then-push construction mirrors c_api.cpp:185-231; validation
   bin alignment via `reference=` mirrors c_api.cpp:158-183)

plus a `train()` convenience driver (the Application train loop,
src/application/application.cpp:218-236, incl. early stopping).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .config import Config, apply_aliases
from .io.binning import BinMapper, K_ZERO_THRESHOLD, find_bin
from .io import dataset as io_dataset
from .metrics import create_metrics
from .models.gbdt import GBDT, create_boosting
from .objectives import create_objective
from .utils import log
from .utils.mt19937 import Mt19937Random

ArrayLike = Union[np.ndarray, "scipy.sparse.spmatrix", str]  # noqa: F821


def _to_config(params: Optional[Dict]) -> Config:
    p = {str(k): str(v) for k, v in (params or {}).items()}
    return Config.from_params(apply_aliases(p))


def _is_sparse(data: Any) -> bool:
    try:
        import scipy.sparse as sp
        return sp.issparse(data)
    except ImportError:
        return False


def _as_dense(data: Any) -> np.ndarray:
    """Accept ndarray / scipy CSR / CSC (the reference's 4 matrix adapters,
    c_api.cpp:589-770); densify sparse — only used where a dense matrix is
    genuinely needed (prediction); INGEST of sparse input is O(nnz)
    (Dataset._construct_from_sparse)."""
    if _is_sparse(data):
        return np.asarray(data.todense(), dtype=np.float64)
    arr = np.asarray(data, dtype=np.float64)
    if arr.ndim != 2:
        raise ValueError("data must be 2-dimensional, got shape %r"
                         % (arr.shape,))
    return arr


class Dataset:
    """Binned training data (reference DatasetHandle).

    data: 2-D numpy array [N, F], scipy sparse matrix, or a text-file path
    (CSV/TSV/LibSVM, auto-detected like src/io/parser.cpp:72-144).
    reference: align bins to another Dataset's mappers (validation data),
    like LGBM_CreateDatasetFromFile's reference argument.
    group: per-query row counts (the .query file convention,
    src/io/metadata.cpp:252-327) or per-row query ids.
    """

    def __init__(self, data: ArrayLike, label: Any = None,
                 params: Optional[Dict] = None,
                 reference: Optional["Dataset"] = None,
                 weight: Any = None, group: Any = None,
                 init_score: Any = None,
                 feature_names: Optional[Sequence[str]] = None,
                 free_raw_data: bool = True):
        self.params = dict(params or {})
        self.config = _to_config(params)
        self._reference = reference
        self._inner: Optional[io_dataset.Dataset] = None
        self._raw = data
        self._label = label
        self._weight = weight
        self._group = group
        self._init_score = init_score
        self._feature_names = list(feature_names) if feature_names else None
        self.free_raw_data = free_raw_data
        if isinstance(data, str):
            self._construct_from_file(data)
        elif _is_sparse(data):
            self._construct_from_sparse(data)
        else:
            self._construct_from_matrix(_as_dense(data))

    # -- construction --------------------------------------------------
    def _construct_from_file(self, path: str) -> None:
        ref = self._reference.inner if self._reference is not None else None
        self._inner = io_dataset.load_dataset(path, self.config,
                                              reference=ref)
        self._apply_field_overrides()

    def _construct_from_matrix(self, mat: np.ndarray) -> None:
        n, ncols = mat.shape
        if self._label is None:
            log.warning("Dataset created without a label")
            self._label = np.zeros(n, dtype=np.float32)
        label = np.asarray(self._label, dtype=np.float32).reshape(n)

        if self._reference is not None:
            refin = self._reference.inner
            ds = io_dataset.Dataset(
                bins=np.zeros((refin.num_features, n),
                              dtype=refin.bin_dtype),
                bin_mappers=refin.bin_mappers,
                used_feature_map=refin.used_feature_map,
                real_feature_index=refin.real_feature_index,
                num_total_features=refin.num_total_features,
                feature_names=refin.feature_names,
                metadata=io_dataset.Metadata(label=label))
            ds.bins = ds.bin_feature_values(mat)
            self._inner = ds
            self._apply_field_overrides()
            return

        cfg = self.config
        # sample-then-push construction (c_api.cpp:185-231 ->
        # DatasetLoader::CostructFromSampleData, dataset_loader.cpp:408-453)
        # with the reference's OWN mt19937 Random::Sample — knife-edge
        # values must bin identically to the C API (VERDICT r3 missing #2)
        sample_cnt = min(cfg.bin_construct_sample_cnt, n)
        if sample_cnt < n:
            idx = Mt19937Random(cfg.data_random_seed).sample(n, sample_cnt)
            sample = mat[np.asarray(idx, dtype=np.int64)]
        else:
            sample = mat

        mappers_all: List[Optional[BinMapper]] = [
            find_bin(sample[:, j], sample.shape[0], cfg.max_bin)
            for j in range(ncols)]
        (used_feature_map, bin_mappers, real_index, names,
         dtype) = self._filter_mappers(mappers_all, ncols)
        bins = np.zeros((len(bin_mappers), n), dtype=dtype)
        for inner, real in enumerate(real_index):
            bins[inner] = bin_mappers[inner].value_to_bin(
                mat[:, real]).astype(dtype)

        self._finish_inner(bins, bin_mappers, used_feature_map,
                           real_index, ncols, names, label)

    def _construct_from_sparse(self, sp_mat: Any) -> None:
        """CSR/CSC input binned in O(nnz + F*N) memory without ever
        materializing the dense float matrix (VERDICT r3 missing #1; the
        reference builds Datasets straight from its sparse adapters,
        c_api.cpp:589-770): bin sampling slices sampled rows from CSR,
        per-feature binning slices columns from CSC, and the training
        representation is the usual [F, N] uint8 matrix whose absent
        entries take the value-0 default bin (dense_bin.hpp:19-24).
        Results are identical to the densified path."""
        n, ncols = sp_mat.shape
        if self._label is None:
            log.warning("Dataset created without a label")
            self._label = np.zeros(n, dtype=np.float32)
        label = np.asarray(self._label, dtype=np.float32).reshape(n)
        csc = sp_mat.tocsc()
        cfg = self.config

        def col_bins(mapper: BinMapper, real: int, dtype: type,
                     out_n: int, indptr: np.ndarray,
                     indices: np.ndarray,
                     data: np.ndarray) -> np.ndarray:
            zb = mapper.value_to_bin(np.zeros(1))[0]
            row = np.full(out_n, zb, dtype=dtype)
            if real >= len(indptr) - 1:
                # feature column absent from this matrix: every row at
                # the value-0 default bin, like the dense path's zeros
                # column (io/dataset.py bin_feature_values)
                return row
            s, e = indptr[real], indptr[real + 1]
            if e > s:
                v = data[s:e]
                # adapter zero rule (1e-15, c_api.cpp RowPairFunction*);
                # explicitly stored NaN stays and clips to the last bin,
                # exactly like the densified path's value_to_bin
                keep = (np.abs(v) > K_ZERO_THRESHOLD) | np.isnan(v)
                if keep.any():
                    row[indices[s:e][keep]] = \
                        mapper.value_to_bin(v[keep]).astype(dtype)
            return row

        if self._reference is not None:
            refin = self._reference.inner
            bins = np.zeros((refin.num_features, n), dtype=refin.bin_dtype)
            for inner, real in enumerate(refin.real_feature_index):
                bins[inner] = col_bins(
                    refin.bin_mappers[inner], int(real),
                    refin.bin_dtype, n, csc.indptr, csc.indices,
                    csc.data)
            self._finish_inner(bins, refin.bin_mappers,
                               refin.used_feature_map,
                               refin.real_feature_index,
                               refin.num_total_features,
                               refin.feature_names, label)
            return

        sample_cnt = min(cfg.bin_construct_sample_cnt, n)
        if sample_cnt < n:
            idx = Mt19937Random(cfg.data_random_seed).sample(n, sample_cnt)
            sub_csc = sp_mat.tocsr()[np.asarray(idx, np.int64)].tocsc()
        else:
            sub_csc = csc
        mappers_all: List[Optional[BinMapper]] = []
        for j in range(ncols):
            vals = sub_csc.data[sub_csc.indptr[j]:sub_csc.indptr[j + 1]]
            vals = vals[np.abs(vals) > K_ZERO_THRESHOLD]
            # find_bin takes the NONZERO sample values + the total count
            # (zeros implied), exactly the reference's sample_values
            mappers_all.append(
                find_bin(np.asarray(vals, dtype=np.float64),
                         min(sample_cnt, n), cfg.max_bin))
        (used_feature_map, bin_mappers, real_index, names,
         dtype) = self._filter_mappers(mappers_all, ncols)
        bins = np.zeros((len(bin_mappers), n), dtype=dtype)
        for inner, real in enumerate(real_index):
            bins[inner] = col_bins(bin_mappers[inner], real, dtype, n,
                                   csc.indptr, csc.indices, csc.data)
        self._finish_inner(bins, bin_mappers, used_feature_map,
                           real_index, ncols, names, label)

    def _filter_mappers(
            self, mappers_all: List[Optional[BinMapper]], ncols: int
    ) -> Tuple[np.ndarray, List[BinMapper], List[int], List[str], type]:
        """Drop trivial (single-value) features, like the reference's
        used-feature map construction (dataset_loader.cpp:600-640)."""
        used_feature_map = np.full(ncols, -1, dtype=np.int32)
        bin_mappers: List[BinMapper] = []
        real_index: List[int] = []
        names = (self._feature_names
                 or ["Column_%d" % i for i in range(ncols)])
        for j, m in enumerate(mappers_all):
            if m.is_trivial:
                log.warning("Ignoring feature %s, only has one value"
                            % names[j])
                continue
            used_feature_map[j] = len(bin_mappers)
            bin_mappers.append(m)
            real_index.append(j)
        if not bin_mappers:
            log.fatal("No usable features in data")
        max_bin_used = max(m.num_bin for m in bin_mappers)
        dtype = np.uint8 if max_bin_used <= 256 else np.uint16
        return used_feature_map, bin_mappers, real_index, names, dtype

    def _finish_inner(self, bins: np.ndarray,
                      bin_mappers: Sequence[BinMapper],
                      used_feature_map: np.ndarray,
                      real_index: Sequence[int], ncols: int,
                      names: Sequence[str], label: np.ndarray) -> None:
        self._inner = io_dataset.Dataset(
            bins=bins, bin_mappers=list(bin_mappers),
            used_feature_map=np.asarray(used_feature_map, dtype=np.int32),
            real_feature_index=np.asarray(real_index, dtype=np.int32),
            num_total_features=ncols, feature_names=list(names),
            metadata=io_dataset.Metadata(label=label))
        self._apply_field_overrides()

    def _apply_field_overrides(self) -> None:
        if self._weight is not None:
            self.set_weight(self._weight)
        if self._group is not None:
            self.set_group(self._group)
        if self._init_score is not None:
            self.set_init_score(self._init_score)
        if self.free_raw_data and not isinstance(self._raw, str):
            # free_raw_data drops raw MATRICES (the memory the flag is
            # about); a file path is identity, not data — keeping it
            # lets init_model continued training re-read the rows
            self._raw = None

    # -- fields (LGBM_DatasetSet/GetField, c_api.cpp:357-391) ----------
    @property
    def inner(self) -> io_dataset.Dataset:
        return self._inner

    def set_field(self, name: str, data: Any) -> None:
        md = self._inner.metadata
        if name == "label":
            md.label = np.asarray(data, dtype=np.float32).reshape(-1)
        elif name == "weight":
            md.weights = (None if data is None else
                          np.asarray(data, dtype=np.float32).reshape(-1))
            md.finish_queries()
        elif name == "init_score":
            md.init_score = (None if data is None else
                             np.asarray(data, dtype=np.float64).reshape(-1))
        elif name == "group" or name == "query":
            if data is None:
                md.query_boundaries = None
                return
            g = np.asarray(data, dtype=np.int64).reshape(-1)
            if g.sum() == self.num_data():
                # per-query counts (the .query-file convention; checked
                # first so group=[1]*N means N singleton queries)
                md.query_boundaries = np.concatenate(
                    [[0], np.cumsum(g)]).astype(np.int32)
            elif len(g) == self.num_data():
                # per-row query ids -> boundaries (metadata.cpp:66-92)
                change = np.nonzero(np.diff(g))[0] + 1
                md.query_boundaries = np.concatenate(
                    [[0], change, [len(g)]]).astype(np.int32)
            else:
                log.fatal("group must be per-query counts summing to "
                          "num_data or per-row query ids of length "
                          "num_data")
            md.finish_queries()
        else:
            log.fatal("Unknown dataset field %s" % name)

    def get_field(self, name: str) -> Optional[np.ndarray]:
        md = self._inner.metadata
        if name == "label":
            return md.label
        if name == "weight":
            return md.weights
        if name == "init_score":
            return md.init_score
        if name == "group" or name == "query":
            return md.query_boundaries
        log.fatal("Unknown dataset field %s" % name)

    def set_label(self, label: Any) -> None:
        self.set_field("label", label)

    def set_weight(self, weight: Any) -> None:
        self.set_field("weight", weight)

    def set_group(self, group: Any) -> None:
        self.set_field("group", group)

    def set_init_score(self, init_score: Any) -> None:
        self.set_field("init_score", init_score)

    def get_label(self) -> np.ndarray:
        return self.get_field("label")

    # -- info ----------------------------------------------------------
    def num_data(self) -> int:
        return self._inner.num_data

    def num_feature(self) -> int:
        return self._inner.num_features

    @property
    def feature_name(self) -> List[str]:
        return list(self._inner.feature_names)

    # -- binary round-trip (LGBM_DatasetSaveBinary, c_api.cpp:343-355) -
    def save_binary(self, path: str) -> None:
        io_dataset._save_binary(self._inner, path)

    @classmethod
    def load_binary(cls, path: str,
                    params: Optional[Dict] = None) -> "Dataset":
        out = cls.__new__(cls)
        out.params = dict(params or {})
        out.config = _to_config(params)
        out._reference = None
        out._raw = None
        out._label = out._weight = out._group = out._init_score = None
        out._feature_names = None
        out.free_raw_data = True
        out._inner = io_dataset._load_binary(path)
        return out


class Booster:
    """Boosting session over a Dataset (reference Booster, c_api.cpp:24-148).

    Exactly one of train_set / model_file / model_str must be given.
    """

    def __init__(self, params: Optional[Dict] = None,
                 train_set: Optional[Dataset] = None,
                 model_file: Optional[str] = None,
                 model_str: Optional[str] = None):
        self.params = dict(params or {})
        if sum(x is not None
               for x in (train_set, model_file, model_str)) != 1:
            raise ValueError("need exactly one of train_set / model_file"
                             " / model_str")
        if train_set is not None:
            self.config = _to_config(self.params)
            self.train_set = train_set
            objective = create_objective(self.config)
            objective.init(train_set.inner.metadata, train_set.num_data())
            self._train_metrics = []
            for m in create_metrics(self.config):
                m.init("training", train_set.inner.metadata,
                       train_set.num_data())
                self._train_metrics.append(m)
            self._gbdt = create_boosting(self.config, train_set.inner,
                                         objective, self._train_metrics)
            self._valid_names: List[str] = []
        else:
            text = model_str
            if model_file is not None:
                with open(model_file) as f:
                    text = f.read()
            first_line = text.lstrip().split("\n", 1)[0].strip()
            p = dict(self.params)
            p.setdefault("boosting_type",
                         "dart" if first_line == "dart" else "gbdt")
            self.config = _to_config(p)
            self.train_set = None
            self._gbdt = GBDT(self.config, None, None)
            self._gbdt.load_model_from_string(text)
            self._train_metrics = []
            self._valid_names = []
        if self.config.faults:
            # deterministic fault injection: the API path honors the
            # same `faults` config key as cli.Application (config wins
            # over the LGBM_TPU_FAULTS environment schedule)
            from .resilience.faults import configure
            configure(self.config.faults)

    # -- training ------------------------------------------------------
    def add_valid(self, data: Dataset, name: str) -> None:
        """LGBM_BoosterAddValidData (c_api.cpp:430-437)."""
        metrics = []
        for m in create_metrics(self.config):
            m.init(name, data.inner.metadata, data.num_data())
            metrics.append(m)
        self._gbdt.add_valid_data(data.inner, metrics)
        self._valid_names.append(name)

    def update(self, fobj: Optional[Callable] = None) -> bool:
        """One boosting iteration; returns True when training should stop
        (no further splits / early stop).  fobj(score, train_inner) ->
        (grad, hess) is the custom-objective path
        (LGBM_BoosterUpdateOneIterCustom, c_api.cpp:455-467); score has
        shape [N] (or [K, N] multiclass), gradients laid out the same."""
        if self.train_set is None:
            raise RuntimeError("Booster was loaded from a model file;"
                               " no training data")
        if fobj is None:
            return self._gbdt.train_one_iter(None, None, False)
        score = np.asarray(self._gbdt._training_score())
        grad, hess = fobj(score, self.train_set)
        grad = np.asarray(grad, dtype=np.float32)
        hess = np.asarray(hess, dtype=np.float32)
        return self._gbdt.train_one_iter(grad, hess, False)

    @property
    def current_iteration(self) -> int:
        return self._gbdt.iter

    def num_model_per_iteration(self) -> int:
        return self._gbdt.num_class

    # -- eval (LGBM_BoosterEval / GetEvalNames, c_api.cpp:469-527) ------
    def eval_train(self) -> List[tuple]:
        return self._eval_at(0, "training")

    def eval_valid(self, idx: int = 0) -> List[tuple]:
        name = (self._valid_names[idx]
                if idx < len(self._valid_names) else "valid_%d" % idx)
        return self._eval_at(idx + 1, name)

    def _eval_at(self, data_idx: int, name: str) -> List[tuple]:
        vals = self._gbdt.get_eval_at(data_idx)
        metrics = (self._train_metrics if data_idx == 0
                   else self._gbdt.valid_metrics[data_idx - 1])
        out = []
        i = 0
        for m in metrics:
            for mname in m.names:
                out.append((name, mname, float(vals[i]),
                            m.factor_to_bigger_better > 0))
                i += 1
        return out

    # -- prediction (LGBM_BoosterPredictForMat etc.) --------------------
    def predict(self, data: Any, raw_score: bool = False,
                pred_leaf: bool = False,
                num_iteration: int = -1) -> np.ndarray:
        if _is_sparse(data):
            return self._predict_sparse(data, raw_score, pred_leaf,
                                        num_iteration)
        mat = _as_dense(data)
        saved = self._gbdt.num_used_model
        if num_iteration > 0:    # <= 0 means all iterations (c_api.h:313)
            self._gbdt.set_num_used_model(
                num_iteration * self._gbdt.num_class)
        try:
            if pred_leaf:
                return self._gbdt.predict_leaf_index(mat)
            if raw_score:
                out = self._gbdt.predict_raw(mat)
            else:
                out = self._gbdt.predict(mat)
        finally:
            self._gbdt.num_used_model = saved
        return out[0] if out.shape[0] == 1 else out.T

    # bound on the dense chunk buffer used by sparse prediction:
    # 4M doubles (~32 MB), split across however many rows fit (the
    # predict pipeline makes a handful of same-size transients per
    # chunk, so peak is a small multiple of this)
    _SPARSE_PREDICT_BUDGET = 1 << 22

    def _predict_sparse(self, data: Any, raw_score: bool,
                        pred_leaf: bool,
                        num_iteration: int) -> np.ndarray:
        """O(nnz) CSR/CSC prediction (VERDICT r4 #4; reference
        LGBM_BoosterPredictForCSR/CSC, c_api.cpp:529-556 with the row
        adapters :589-700): the matrix is never densified — rows stream
        through a bounded [chunk, F] buffer where only PRESENT entries
        are filled (absent features read 0.0, the reference's sparse
        convention), so peak memory is O(nnz + chunk*F) regardless of
        the matrix shape.  Output is identical to the densified path."""
        csr = data.tocsr()      # CSC converts in O(nnz)
        n, f = csr.shape
        chunk = max(1, min(GBDT.PREDICT_CHUNK,
                           self._SPARSE_PREDICT_BUDGET // max(f, 1)))
        outs = []
        block = np.zeros((min(chunk, n), f), dtype=np.float64)
        for a in range(0, n, chunk):
            m = min(chunk, n - a)
            sub = csr[a:a + m]
            blk = block[:m]
            blk[:] = 0.0
            rows = np.repeat(np.arange(m), np.diff(sub.indptr))
            blk[rows, sub.indices] = sub.data
            # every per-chunk result concatenates on its ROW axis:
            # binary/regression -> [m], multiclass -> [m, K] (already
            # transposed by predict), pred_leaf -> [m, T]
            outs.append(self.predict(blk, raw_score, pred_leaf,
                                     num_iteration))
        if not outs:
            # 0-row matrices produce mode-SHAPED empty output, exactly
            # like the dense path: [0] binary/regression, [0, K]
            # multiclass, [0, T] pred_leaf — callers indexing the class
            # axis must not see a sparse/dense shape mismatch
            return self.predict(np.zeros((0, f)), raw_score, pred_leaf,
                                num_iteration)
        return np.concatenate(outs, axis=0)

    # -- model io (LGBM_BoosterSaveModel / LoadModelFromString) ---------
    def save_checkpoint(self, path: str) -> None:
        """Exact-state trainer snapshot (model + scores + RNG streams);
        load_checkpoint resumes training bit-for-bit.  Superset of the
        reference, whose resume re-boosts from predicted init scores."""
        self._gbdt.save_checkpoint(path)

    def load_checkpoint(self, path: str) -> None:
        """Restore a save_checkpoint snapshot into a Booster built with
        the same params and datasets."""
        self._gbdt.load_checkpoint(path)

    def save_model(self, path: str, num_iteration: int = -1) -> None:
        # the GBDT save path is incremental (per-iteration append,
        # gbdt.cpp:351-400); reset its cursor for a standalone full save
        if self._gbdt._model_file is not None:
            self._gbdt._model_file.close()
            self._gbdt._model_file = None
        self._gbdt.saved_upto = -1
        self._gbdt.save_model_to_file(num_iteration, True, path)

    def model_to_string(self, num_iteration: int = -1) -> str:
        import tempfile
        import os as _os
        fd, tmp = tempfile.mkstemp(suffix=".txt")
        _os.close(fd)
        try:
            self.save_model(tmp, num_iteration)
            with open(tmp) as f:
                return f.read()
        finally:
            _os.unlink(tmp)

    def feature_importance(self) -> Dict[str, int]:
        """Split-count importances (GBDT::FeatureImportance,
        gbdt.cpp:458-485)."""
        td = self._gbdt.train_data
        names = (td.feature_names if td is not None else None)
        counts: Dict[str, int] = {}
        for tree in self._gbdt.models:
            for fi in tree.split_feature_real[:tree.num_leaves - 1]:
                name = (names[fi] if names and fi < len(names)
                        else "Column_%d" % fi)
                counts[name] = counts.get(name, 0) + 1
        return dict(sorted(counts.items(), key=lambda kv: -kv[1]))


def _seed_init_scores(old: Booster, ds: Dataset) -> None:
    """Install the old model's raw predictions over `ds`'s rows as its
    init_score — the reference's continued-training pass (re-boost from
    predicted scores, application.cpp:106-180 / predictor.hpp), shared
    semantics with cli.Application._set_init_scores.  Needs the
    dataset's raw features: matrices keep them with
    free_raw_data=False, file-backed datasets keep the path."""
    inner = ds.inner
    raw = ds._raw
    gb = old._gbdt
    if raw is None:
        log.fatal("init_model continued training needs the dataset's "
                  "raw features to predict init scores — construct the "
                  "Dataset with free_raw_data=False (matrix/sparse "
                  "input) or from a file path")
    if isinstance(raw, str):
        from .io.parser import parse_file_lines
        with open(raw) as f:
            lines = [ln for ln in f.read().splitlines() if ln]
        if ds.config.has_header:
            lines = lines[1:]
        # dense width fixed to the OLD model's schema (predictor.hpp)
        w = max(gb.max_feature_idx + 2, inner.label_idx + 1)
        _, feats, _ = parse_file_lines(lines, inner.label_idx,
                                       dense_cols=w)
        scores = gb.predict_raw(feats)                     # [K, N]
    elif _is_sparse(raw):
        out = old.predict(raw, raw_score=True)   # [N] or [N, K]
        scores = out.T if getattr(out, "ndim", 1) == 2 else out
    else:
        scores = gb.predict_raw(_as_dense(raw))            # [K, N]
    # class-major flat layout, like metadata init-score files
    ds.set_init_score(np.asarray(scores).reshape(-1))


def _as_old_booster(init_model: Union[str, Booster],
                    params: Dict) -> Booster:
    if isinstance(init_model, Booster):
        return init_model
    text = str(init_model)
    if "\n" in text:
        # a multi-line string IS the model text (model_to_string
        # output), not a path — open() on it would raise ENOENT/
        # ENAMETOOLONG instead of loading the model
        return Booster(params=dict(params), model_str=text)
    return Booster(params=dict(params), model_file=text)


def train(params: Dict, train_set: Dataset, num_boost_round: int = 100,
          valid_sets: Sequence[Dataset] = (),
          valid_names: Optional[Sequence[str]] = None,
          fobj: Optional[Callable] = None,
          early_stopping_rounds: Optional[int] = None,
          verbose_eval: Union[bool, int] = True,
          init_model: Optional[Union[str, Booster]] = None) -> Booster:
    """Train-loop driver (Application::Train, application.cpp:218-236).

    init_model warm-starts training two ways, routed on the file's
    actual format:

      * a CHECKPOINT archive (Booster.save_checkpoint): bit-exact
        continuation — the restored state continues to num_boost_round
        TOTAL rounds, byte-identical to an uninterrupted run of the
        same length (the resume=auto mechanism, resilience/snapshot);
        the checkpoint must have been written under this config and
        dataset (fingerprint-checked).
      * a model TEXT file / Booster / model string: the reference's
        continued-training semantics (re-boost from predicted init
        scores) — num_boost_round NEW trees are grown on top and the
        saved model contains old + new trees.  Works across datasets
        (the refresh pipeline's incremental-boosting path); see
        PARITY.md §5 for the deliberate divergence from a from-scratch
        run.
    """
    p = dict(params)
    if early_stopping_rounds is not None:
        p["early_stopping_round"] = early_stopping_rounds
    # size per-iteration device state (e.g. the DART tree bank) for the
    # actual round count; training is still driven by the loop below
    if not any(k in p for k in ("num_iterations", "num_iteration",
                                "num_tree", "num_trees", "num_round",
                                "num_rounds")):
        p["num_iterations"] = num_boost_round
    init_ckpt: Optional[str] = None
    old_booster: Optional[Booster] = None
    if init_model is not None:
        from .resilience.snapshot import is_checkpoint_file
        if isinstance(init_model, str) \
                and is_checkpoint_file(init_model):
            init_ckpt = init_model
        else:
            # init scores must be installed BEFORE Booster construction:
            # the objective reads metadata.init_score at init time
            old_booster = _as_old_booster(init_model, params)
            _seed_init_scores(old_booster, train_set)
            for vs in valid_sets:
                _seed_init_scores(old_booster, vs)
    booster = Booster(p, train_set=train_set)
    if old_booster is not None:
        # carry the already-trained trees so saved models hold the full
        # ensemble (cli.init_train's continued-training block)
        gb = booster._gbdt
        gb.models = list(old_booster._gbdt.models)
        gb.num_used_model = len(gb.models) // gb.num_class
    names = list(valid_names or
                 ["valid_%d" % i for i in range(len(valid_sets))])
    for ds, name in zip(valid_sets, names):
        booster.add_valid(ds, name)
    freq = (1 if verbose_eval is True
            else 0 if verbose_eval is False else int(verbose_eval))
    # metric printing + early stopping ride GBDT::OutputMetric
    # (gbdt.cpp:231-267); metric_freq controls the print cadence
    gbdt = booster._gbdt
    gbdt.config.metric_freq = freq if freq > 0 else (1 << 30)
    early = gbdt.early_stopping_round > 0
    is_eval = freq > 0 or early
    # crash-safe snapshots + auto-resume (resilience/snapshot.py): the
    # API loop honors the same snapshot_period / snapshot_dir / resume
    # keys as cli.train, riding save_checkpoint's bit-exact state
    from .resilience.snapshot import SnapshotManager
    # cap = the LOOP's bound, not config num_iterations: a snapshot
    # past num_boost_round would skip the loop and return extra trees
    snaps = SnapshotManager.from_config(gbdt.config,
                                        max_iteration=num_boost_round)
    if init_ckpt is not None:
        # bit-exact warm start: the loaded checkpoint IS the resume
        # mechanism (fingerprint-checked against this config/dataset).
        # A newer snapshot from THIS run's snapshot_dir still wins
        # below — the warm-start checkpoint is the base, not the tip.
        booster.load_checkpoint(init_ckpt)
        if gbdt.iter > num_boost_round:
            log.fatal("init_model=%s holds %d iterations, beyond "
                      "num_boost_round=%d — the model would silently "
                      "contain more rounds than requested"
                      % (init_ckpt, int(gbdt.iter), num_boost_round))
    if snaps is not None:
        snaps.maybe_resume(gbdt)
    done = int(gbdt.iter)
    stop = False
    while done < num_boost_round and not stop:
        if fobj is not None:
            # custom gradients stay per-iteration (their evolution is
            # host-driven, outside the scanned segment)
            stop = booster.update(fobj=fobj)
            done += 1
            if not stop and is_eval:
                stop = gbdt.eval_and_check_early_stopping()
        else:
            # iteration-batched segments (config.iter_batch): K
            # iterations per device dispatch, eval/flush only at
            # segment boundaries — bit-parity with the K=1 loop
            stop, k = gbdt.train_segment(num_boost_round - done,
                                         is_eval=is_eval)
            done += k
        if snaps is not None and snaps.due(done):
            snaps.write(gbdt)
    return booster
