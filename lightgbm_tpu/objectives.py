"""Objective functions: gradients/hessians of the training loss.

Formula-parity ports (float32 math, like the reference's score_t=float):
  - regression L2: reference src/objective/regression_objective.hpp:24-39
  - binary logloss: reference src/objective/binary_objective.hpp:23-86
  - multiclass softmax: reference src/objective/multiclass_objective.hpp:22-73
  - lambdarank NDCG: reference src/objective/rank_objective.hpp:41-192,
    including the 1M-entry sigmoid lookup table (same table, same index
    math) so gradient values match the reference bit-for-bit on identical
    scores.

Elementwise objectives are jitted jnp; lambdarank is vectorized numpy over
padded per-query blocks (scores are pulled to host once per iteration — the
per-query pairwise O(L^2) work is tiny relative to tree growth).
"""

from __future__ import annotations

from typing import Optional

from .utils.compile_cache import enable_compilation_cache

enable_compilation_cache()   # before any jit traces (was a package-import side effect)

import jax
import jax.numpy as jnp
import numpy as np

from . import native
from .analysis.contracts import contract
from .config import Config
from .io.dataset import Metadata
from .utils import log

K_EPSILON = 1e-15
K_MIN_SCORE = -np.inf


class Objective:
    # True when get_gradients is pure jax over captured device arrays and
    # may be traced inside a fused training step (models/gbdt.py)
    jax_traceable = False
    # True when grad_state can follow a row reordering (the ordered-
    # partition mode, models/gbdt.py) via make_permute_fn.  The default
    # permute treats every leaf as per-row on its last axis; objectives
    # whose state carries row INDICES (lambdarank's doc_idx) override
    # make_permute_fn to remap them instead.
    row_permutable = False
    # True when the data-parallel fused step can shard grad_state along
    # the data axis (models/gbdt.py _make_fused_step_sharded).  Two ways
    # to qualify: every leaf is per-row on its LAST axis (the default
    # sharding; regression/binary/multiclass), or the objective provides
    # its own query-granular layout + sharded state via shard_layout /
    # build_sharded_state (lambdarank's device path: the [Q, Lmax]
    # query-block state shards along Q with shard-local row indices).
    row_shardable = False
    name = "none"
    num_class = 1

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.num_data = num_data
        self.n_pad = num_data

    def pad_to(self, n_pad: int) -> None:
        """Extend label-derived device arrays to a padded row count so
        gradients can be computed directly on padded/sharded score arrays
        (padded rows produce values that are masked out of histograms by
        bag_mask and harmless in score updates)."""
        self.n_pad = n_pad

    @staticmethod
    def _pad(arr, n_pad, value=0.0):
        if arr is None or arr.shape[-1] >= n_pad:
            return arr
        pad = [(0, 0)] * (arr.ndim - 1) + [(0, n_pad - arr.shape[-1])]
        return jnp.pad(arr, pad, constant_values=value)

    def get_gradients(self, score):
        raise NotImplementedError

    # -- fused-step surface (models/gbdt.py) ---------------------------
    # The fused training step passes label-derived arrays as jit
    # ARGUMENTS (grad_state) to a pure gradient function (make_grad_fn),
    # so the compiled executable carries no embedded label constants and
    # one executable is shared by every booster whose fused_key matches.
    def fused_key(self):
        """Hashable key fully identifying the gradient computation, or
        None when this objective cannot be traced in the fused step."""
        return None

    def grad_state(self):
        """Pytree of device arrays consumed by make_grad_fn's function."""
        raise NotImplementedError

    def make_grad_fn(self):
        """-> pure fn (score, grad_state) -> (grad, hess).  Two
        objectives with equal fused_key must return functions that trace
        identically."""
        raise NotImplementedError

    @contract.traced_pure
    def make_permute_fn(self):
        """-> pure fn (grad_state, rel) -> grad_state permuted to the
        new row order (new position j holds old row rel[j]).  Traced
        inside the fused reorder step (models/gbdt.py), so two
        objectives with equal fused_key must return functions that trace
        identically.  Default: every state leaf is per-row on its last
        axis (regression/binary/multiclass).

        This is also the bag-compaction gather hook: the in-bag-first
        arrangement (models/gbdt.py _arrange_for_bag) is a stable row
        permutation, so grad_state follows it through this same function
        — objectives whose state carries row indices (lambdarank's
        doc_idx) remap them here and need nothing extra for compaction."""
        def permute(gstate, rel):
            return jax.tree_util.tree_map(
                lambda a: jnp.take(a, rel, axis=-1), gstate)
        return permute

    def bag_rows_bound(self, bagging_fraction: float) -> int:
        """Deterministic upper bound on the in-bag ROW count of any
        single re-bagging draw at this fraction — the static size of the
        bag-compacted sweep window (models/gbdt.py).  Row-granular
        bagging draws exactly int(fraction * n) rows (gbdt.cpp:109-131),
        so the bound is exact; query-granular bagging (query_boundaries
        present, gbdt.cpp:133-160) draws int(nq * fraction) whole
        queries whose row total varies per draw — bounded by the sum of
        the largest that-many query lengths."""
        qb = getattr(self.metadata, "query_boundaries", None)
        if qb is None:
            return int(bagging_fraction * self.num_data)
        qb = np.asarray(qb, dtype=np.int64)
        qlen = np.sort(qb[1:] - qb[:-1])[::-1]
        bag_query_cnt = int(len(qlen) * bagging_fraction)
        return int(qlen[:bag_query_cnt].sum())

    # -- query-granular sharding surface (tree_learner=data) -----------
    # Objectives whose grad_state is NOT per-row on its last axis (the
    # lambdarank query blocks) implement these two hooks to still run
    # the fused shard_map step: shard_layout returns the row placement
    # (rows of one query stay on one shard), build_sharded_state the
    # matching shard-major gradient state + PartitionSpecs.
    def shard_layout(self, local_shards: int, row_unit: int, mh: bool):
        """RowShardLayout (parallel/mesh.py) for the data-parallel fused
        step, or None when the default contiguous row blocks work (every
        elementwise objective)."""
        return None

    def build_sharded_state(self, layout, sync=None):
        """-> (host_leaves, specs): numpy grad_state blocks laid out
        shard-major for `layout` plus one PartitionSpec per leaf.  Only
        called when shard_layout returned a layout."""
        raise NotImplementedError

    def convert_output(self, score: np.ndarray) -> np.ndarray:
        """Final transform for human-facing predictions."""
        return score


class RegressionL2(Objective):
    name = "regression"
    jax_traceable = True
    row_permutable = True
    row_shardable = True

    def __init__(self, config: Config):
        pass

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        self.label = jnp.asarray(metadata.label, dtype=jnp.float32)
        self.weights = (None if metadata.weights is None
                        else jnp.asarray(metadata.weights, dtype=jnp.float32))

    def pad_to(self, n_pad: int) -> None:
        super().pad_to(n_pad)
        self.label = self._pad(self.label, n_pad)
        self.weights = self._pad(self.weights, n_pad)

    def get_gradients(self, score):
        return self.make_grad_fn()(score, self.grad_state())

    def fused_key(self):
        return ("regression", self.weights is not None)

    def grad_state(self):
        return (self.label, self.weights)

    @staticmethod
    @contract.traced_pure
    def make_grad_fn():
        def grad_fn(score, state):
            label, weights = state
            score = score.astype(jnp.float32)
            grad = score - label
            hess = jnp.ones_like(grad)
            if weights is not None:
                grad = grad * weights
                hess = weights
            return grad, hess
        return grad_fn


class BinaryLogloss(Objective):
    name = "binary"
    jax_traceable = True
    row_permutable = True
    row_shardable = True

    def __init__(self, config: Config):
        self.sigmoid = np.float32(config.sigmoid)
        self.is_unbalance = config.is_unbalance
        if self.sigmoid <= 0:
            log.fatal("Sigmoid parameter %f should be greater than zero"
                      % self.sigmoid)

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        labels01 = metadata.label.astype(np.int32)
        cnt_pos = int((labels01 == 1).sum())
        cnt_neg = num_data - cnt_pos
        log.info("Number of postive: %d, number of negative: %d"
                 % (cnt_pos, cnt_neg))
        if cnt_pos == 0 or cnt_neg == 0:
            log.fatal("Training data only contains one class")
        w_pos, w_neg = 1.0, 1.0
        if self.is_unbalance:
            if cnt_pos > cnt_neg:
                w_neg = cnt_pos / cnt_neg
            else:
                w_pos = cnt_neg / cnt_pos
        sign = np.where(labels01 == 1, 1.0, -1.0).astype(np.float32)
        lw = np.where(labels01 == 1, w_pos, w_neg).astype(np.float32)
        if metadata.weights is not None:
            lw = lw * metadata.weights.astype(np.float32)
        self.sign = jnp.asarray(sign)
        self.label_weight = jnp.asarray(lw)

    def pad_to(self, n_pad: int) -> None:
        super().pad_to(n_pad)
        # sign 0 + weight 0 -> zero grad/hess for padded rows
        self.sign = self._pad(self.sign, n_pad)
        self.label_weight = self._pad(self.label_weight, n_pad)

    def get_gradients(self, score):
        return self.make_grad_fn()(score, self.grad_state())

    def fused_key(self):
        return ("binary", float(self.sigmoid))

    def grad_state(self):
        return (self.sign, self.label_weight)

    @contract.traced_pure
    def make_grad_fn(self):
        sig = jnp.float32(self.sigmoid)

        def grad_fn(score, state):
            sign, label_weight = state
            score = score.astype(jnp.float32)
            response = (-2.0 * sign * sig
                        / (1.0 + jnp.exp(2.0 * sign * sig * score)))
            abs_r = jnp.abs(response)
            grad = response * label_weight
            hess = abs_r * (2.0 * sig - abs_r) * label_weight
            return grad, hess
        return grad_fn

    def convert_output(self, score: np.ndarray) -> np.ndarray:
        return 1.0 / (1.0 + np.exp(-2.0 * float(self.sigmoid) * score))


class MulticlassSoftmax(Objective):
    name = "multiclass"
    # [K, N] gradients feed the MULTICLASS fused step
    # (gbdt._make_fused_step_multi): one dispatch grows all K
    # per-iteration trees via a class-wise lax.scan
    jax_traceable = True
    # onehot [K, N] / weights [N] both permute on their last axis, so
    # the shared-joint-order multiclass reorder may carry them
    row_permutable = True
    row_shardable = True

    def __init__(self, config: Config):
        self.num_class = config.num_class

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        li = metadata.label.astype(np.int32)
        if li.min() < 0 or li.max() >= self.num_class:
            log.fatal("Label must be in [0, %d)" % self.num_class)
        self.onehot = jnp.asarray(
            np.eye(self.num_class, dtype=np.float32)[li].T)  # [K, N]
        self.weights = (None if metadata.weights is None
                        else jnp.asarray(metadata.weights, dtype=jnp.float32))

    def pad_to(self, n_pad: int) -> None:
        super().pad_to(n_pad)
        self.onehot = self._pad(self.onehot, n_pad)
        self.weights = self._pad(self.weights, n_pad)

    def get_gradients(self, score):
        """score [K, N] -> grad/hess [K, N] (see make_grad_fn)."""
        return self.make_grad_fn()(score, self.grad_state())

    def fused_key(self):
        return ("multiclass", self.num_class, self.weights is not None)

    def grad_state(self):
        return (self.onehot, self.weights)

    @staticmethod
    @contract.traced_pure
    def make_grad_fn():
        def grad_fn(score, state):
            """score [K, N] -> grad/hess [K, N].

            The softmax itself runs in float64 with the result cast to
            float32, reproducing the reference's double-precision
            Common::Softmax rec[] with score_t p = (float)rec[k]
            (multiclass_objective.hpp:35-53, common.h:353-367) — under
            default x64-disabled JAX the cast is a no-op and everything
            stays f32."""
            onehot, weights = state
            score = score.astype(jnp.float32)
            # graftlint: disable=GL003 -- reference parity REQUIRES the
            # f64 softmax (double rec[] in common.h:353-367); with x64
            # off the astype is a no-op and the math stays f32
            p = jax.nn.softmax(score.astype(jnp.float64), axis=0) \
                .astype(jnp.float32)
            grad = p - onehot
            hess = 2.0 * p * (1.0 - p)
            if weights is not None:
                grad = grad * weights[None, :]
                hess = hess * weights[None, :]
            return grad, hess
        return grad_fn

    def convert_output(self, score: np.ndarray) -> np.ndarray:
        e = np.exp(score - score.max(axis=0, keepdims=True))
        return e / e.sum(axis=0, keepdims=True)


_SIGMOID_BINS = 1024 * 1024


class LambdarankNDCG(Objective):
    """LambdaRank with NDCG deltas (reference rank_objective.hpp:41-192).

    Two gradient paths, selected by ``rank_impl``:

    - ``device`` (default): the pairwise per-query computation expressed
      as jnp over padded ``[Q, Lmax]`` query blocks — scores never leave
      the device, the objective traces into the fused training step, and
      the O(L^2) pair tensors are bounded by scanning fixed-size query
      blocks.  Tie order under equal scores follows a STABLE descending
      sort (documented divergence from the reference's non-stable
      std::sort tie permutation; PARITY.md).
    - ``native``: the bit-parity C++ kernel (native/ingest.cpp) that
      reproduces the reference's libstdc++ sort permutation and
      sequential fp32 pair accumulation digit-for-digit — kept as the
      golden-parity oracle, with a vectorized numpy fallback.
    """

    name = "lambdarank"

    def __init__(self, config: Config):
        self.impl = getattr(config, "rank_impl", "device")
        self.jax_traceable = self.impl == "device"
        self.sigmoid = np.float32(config.sigmoid)
        if self.sigmoid <= 0:
            log.fatal("Sigmoid param %f should be greater than zero"
                      % self.sigmoid)
        self.label_gain = np.asarray(config.label_gain or default_label_gain(),
                                     dtype=np.float32)
        self.optimize_pos_at = config.max_position
        # discount table (reference src/metric/dcg_calculator.cpp:27-30)
        self.discount = (1.0 / np.log2(2.0 + np.arange(10000))).astype(np.float32)
        # sigmoid lookup table (reference rank_objective.hpp:175-189)
        self.min_in = np.float32(-50.0) / self.sigmoid / np.float32(2.0)
        self.max_in = -self.min_in
        self.idx_factor = np.float32(_SIGMOID_BINS / (self.max_in - self.min_in))
        ts = (np.arange(_SIGMOID_BINS, dtype=np.float32) / self.idx_factor
              + self.min_in)
        self.sigmoid_table = (
            np.float32(2.0) / (np.float32(1.0)
                               + np.exp(np.float32(2.0) * ts * self.sigmoid)))

    def init(self, metadata: Metadata, num_data: int) -> None:
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("Lambdarank tasks require query information")
        self.qb = metadata.query_boundaries
        # reference src/io/metadata.cpp CheckOrPartition: an undercounting
        # .query sidecar must fatal, not silently hand uncovered rows
        # query-0's gradients via the row_slot default of 0
        if int(self.qb[-1]) != num_data:
            log.fatal("Sum of query counts is not same with #data")
        label = metadata.label
        check_rank_label(label, len(self.label_gain))
        nq = len(self.qb) - 1
        inv = np.zeros(nq, dtype=np.float32)
        for q in range(nq):
            lab = label[self.qb[q]:self.qb[q + 1]]
            m = max_dcg_at_k(self.optimize_pos_at, lab, self.label_gain,
                             self.discount)
            inv[q] = 1.0 / m if m > 0 else m
        self.inverse_max_dcgs = inv
        self.weights = metadata.weights
        if self.impl == "device":
            # the [1, Lmax, Lmax] pair tensors (x ~6 f32 temporaries) grow
            # unbounded in Lmax even at q_block=1; a single 100k-doc query
            # would need tens of GB of HBM.  Past ~16k docs/query the
            # reference-order host path is the right tool.
            qb = np.asarray(self.qb, dtype=np.int64)
            lmax = int((qb[1:] - qb[:-1]).max()) if len(qb) > 1 else 1
            if lmax * lmax * 4 * 6 > (1 << 32):   # >4 GB of pair temps
                log.warning(
                    "Longest query has %d docs; pair tensors would not fit "
                    "in HBM. Falling back to rank_impl=native." % lmax)
                self.impl = "native"
                self.jax_traceable = False
        if self.impl == "device":
            self._build_device_state()
        # the device path's per-doc outputs map back to rows through the
        # per-row row_slot array (every other state leaf is row-POSITION
        # free), so the ordered-partition mode may permute rows: row_slot
        # rides along and doc_idx remaps through the inverse permutation
        # (make_permute_fn)
        self.row_permutable = self.impl == "device"
        # ... and the data-parallel fused step may shard it: rows shard
        # query-granularly (shard_layout below), each shard's query
        # blocks carry SHARD-LOCAL doc indices, and the same grad_fn /
        # permute_fn run unchanged per shard inside shard_map
        self.row_shardable = self.impl == "device"

    # -- device path ---------------------------------------------------
    def _build_device_state(self) -> None:
        """Pack queries into padded [nb, QB, Lmax] blocks for the jnp
        gradient path.  QB bounds the [QB, Lmax, Lmax] pair tensors that
        dominate memory (scanned block-by-block), so HBM use is
        ~O(QB * Lmax^2) regardless of query count."""
        qb = np.asarray(self.qb, dtype=np.int64)
        nq = len(qb) - 1
        qlen = (qb[1:] - qb[:-1]).astype(np.int64)
        lmax = max(1, int(qlen.max()) if nq else 1)
        # ~16M pair elements per scanned block (~64 MB of f32 temps)
        q_block = int(min(max(1, (1 << 24) // (lmax * lmax)), max(nq, 1)))
        nb = max(1, -(-nq // q_block))
        nq_pad = nb * q_block
        label = np.asarray(self.metadata.label)

        doc_idx = np.zeros((nq_pad, lmax), dtype=np.int32)
        lab = np.full((nq_pad, lmax), -1, dtype=np.int32)
        gain = np.zeros((nq_pad, lmax), dtype=np.float32)
        wts = np.ones((nq_pad, lmax), dtype=np.float32)
        inv = np.zeros(nq_pad, dtype=np.float32)
        inv[:nq] = self.inverse_max_dcgs
        ar = np.arange(lmax, dtype=np.int64)
        for q in range(nq):
            a, ln = int(qb[q]), int(qlen[q])
            idx = a + np.minimum(ar, max(ln - 1, 0))
            doc_idx[q] = idx
            lab[q, :ln] = label[a:a + ln].astype(np.int32)
            gain[q, :ln] = self.label_gain[lab[q, :ln]]
            if self.weights is not None:
                wts[q, :ln] = self.weights[a:a + ln]

        # row -> padded-slot map: every real row occupies exactly one
        # cell of the [nb*QB, Lmax] layout, so the per-doc outputs come
        # back via ONE gather instead of a scatter-add (TPU scatters
        # serialize; gathers of [N] from [Q*L] are cheap).  Padded rows
        # (pad_to) point at the DEAD slot — one extra zero cell appended
        # to the flat output in grad_fn — so the mapping carries no
        # positional assumption and survives row permutations.
        self._dead_slot = nq_pad * lmax
        row_slot = np.zeros(self.num_data, dtype=np.int32)
        for q in range(nq):
            a, ln = int(qb[q]), int(qlen[q])
            row_slot[a:a + ln] = q * lmax + np.arange(ln)

        shp = (nb, q_block)
        self._dev_state = (
            jnp.asarray(doc_idx.reshape(shp + (lmax,))),
            jnp.asarray(lab.reshape(shp + (lmax,))),
            jnp.asarray(gain.reshape(shp + (lmax,))),
            jnp.asarray(inv.reshape(shp)),
            jnp.asarray(wts.reshape(shp + (lmax,))),
            jnp.asarray(row_slot),
            jnp.asarray(self.discount),
        )
        self._dev_fn = jax.jit(self.make_grad_fn())

    def pad_to(self, n_pad: int) -> None:
        super().pad_to(n_pad)
        if self.impl != "device":
            return
        (di, lab, gain, inv, wts, row_slot, disc) = self._dev_state
        if row_slot.shape[0] < n_pad:
            dead = jnp.full((n_pad - row_slot.shape[0],), self._dead_slot,
                            dtype=jnp.int32)
            row_slot = jnp.concatenate([row_slot, dead])
            self._dev_state = (di, lab, gain, inv, wts, row_slot, disc)

    def fused_key(self):
        if self.impl != "device":
            return None
        return ("lambdarank", float(self.sigmoid))

    def grad_state(self):
        return self._dev_state

    @contract.traced_pure
    def make_permute_fn(self):
        """Row permutation support (ordered-partition mode): row_slot is
        per-row and rides the permutation; doc_idx holds row POSITIONS
        into the score vector, so it remaps through the inverse
        permutation.  Everything else (labels/gains/weights/inv_max_dcg/
        discount) is query-block state, independent of row order."""
        def permute(gstate, rel):
            di, lab, gain, inv, wts, row_slot, disc = gstate
            inv_rel = jnp.argsort(rel).astype(jnp.int32)
            return (inv_rel[di], lab, gain, inv, wts,
                    jnp.take(row_slot, rel), disc)
        return permute

    # -- query-granular sharding (tree_learner=data fused step) --------
    def shard_layout(self, local_shards: int, row_unit: int, mh: bool):
        """Rows shard on query boundaries: shard s's contiguous device
        block holds whole queries [bounds[s], bounds[s+1]) padded to a
        common capacity, the invariant that lets each shard compute its
        queries' pairwise lambdas from its OWN score block (reference
        rank training under data parallelism is likewise query-local —
        only histograms cross machines,
        data_parallel_tree_learner.cpp:124-187)."""
        if self.impl != "device":
            return None
        from .parallel.mesh import query_shard_layout
        sync = None
        if mh:
            from .parallel.dist import sync_max_ints
            sync = sync_max_ints
        return query_shard_layout(self.qb, local_shards, row_unit, sync)

    def build_sharded_state(self, layout, sync=None):
        """Shard-major [S*nb, QB, Lmax] query-block state for the fused
        shard_map step: the serial _build_device_state layout rebuilt
        per shard with SHARD-LOCAL doc indices (row positions inside the
        shard's own score block) and a per-shard row_slot / dead slot.
        Every shard gets identically-shaped blocks (SPMD); multi-host
        passes `sync` so lmax / queries-per-shard agree globally.
        make_grad_fn's function consumes this state unchanged inside
        shard_map — per-query lambdas are independent of the blocking,
        so gradients are bit-identical to the serial device path."""
        from jax.sharding import PartitionSpec as P

        from .parallel.mesh import DATA_AXIS

        qb = np.asarray(self.qb, dtype=np.int64)
        qlen = (qb[1:] - qb[:-1]).astype(np.int64)
        nq = len(qb) - 1
        lmax = max(1, int(qlen.max()) if nq else 1)
        bounds = layout.bounds
        nq_cap = max(1, int((bounds[1:] - bounds[:-1]).max()))
        if sync is not None:
            lmax, nq_cap = (int(v) for v in sync([lmax, nq_cap]))
        # same pair-tensor budget as the serial builder: ~16M pair
        # elements per scanned block
        q_block = int(min(max(1, (1 << 24) // (lmax * lmax)),
                          max(nq_cap, 1)))
        nb = max(1, -(-nq_cap // q_block))
        nq_pad = nb * q_block
        S = layout.local_shards
        label = np.asarray(self.metadata.label)

        doc_idx = np.zeros((S, nq_pad, lmax), dtype=np.int32)
        lab = np.full((S, nq_pad, lmax), -1, dtype=np.int32)
        gain = np.zeros((S, nq_pad, lmax), dtype=np.float32)
        wts = np.ones((S, nq_pad, lmax), dtype=np.float32)
        inv = np.zeros((S, nq_pad), dtype=np.float32)
        dead = nq_pad * lmax          # per-shard flat output size
        row_slot = np.full((S, layout.cap), dead, dtype=np.int32)
        ar = np.arange(lmax, dtype=np.int64)
        for s in range(S):
            base = int(qb[bounds[s]])
            for qi, q in enumerate(range(int(bounds[s]),
                                         int(bounds[s + 1]))):
                a, ln = int(qb[q]), int(qlen[q])
                doc_idx[s, qi] = (a - base) + np.minimum(ar,
                                                         max(ln - 1, 0))
                lab[s, qi, :ln] = label[a:a + ln].astype(np.int32)
                gain[s, qi, :ln] = self.label_gain[lab[s, qi, :ln]]
                if self.weights is not None:
                    wts[s, qi, :ln] = self.weights[a:a + ln]
                inv[s, qi] = self.inverse_max_dcgs[q]
                row_slot[s, a - base:a - base + ln] = (
                    qi * lmax + np.arange(ln, dtype=np.int64))

        shp = (S * nb, q_block)
        host = (doc_idx.reshape(shp + (lmax,)),
                lab.reshape(shp + (lmax,)),
                gain.reshape(shp + (lmax,)),
                inv.reshape(shp),
                wts.reshape(shp + (lmax,)),
                row_slot.reshape(-1),
                self.discount.copy())
        specs = (P(DATA_AXIS, None, None), P(DATA_AXIS, None, None),
                 P(DATA_AXIS, None, None), P(DATA_AXIS, None),
                 P(DATA_AXIS, None, None), P(DATA_AXIS), P())
        return host, specs

    @staticmethod
    def permute_sharded_state_host(host, layout, order_local):
        """Apply a checkpointed ordered-partition row order to the HOST
        sharded state (load_checkpoint restore): re-sorts are shard-
        local, so each shard's doc_idx remaps through the inverse of its
        own block of the order and row_slot rides the permutation —
        exactly make_permute_fn per shard, done in numpy before the
        device put."""
        di, lab, gain, inv, wts, row_slot, disc = host
        S, cap = layout.local_shards, layout.cap
        nb = di.shape[0] // S
        di = di.copy()
        row_slot = row_slot.reshape(S, cap).copy()
        ordl = np.asarray(order_local).reshape(S, cap)
        for s in range(S):
            rel = ordl[s] - s * cap
            inv_rel = np.argsort(rel).astype(np.int32)
            di[s * nb:(s + 1) * nb] = inv_rel[di[s * nb:(s + 1) * nb]]
            row_slot[s] = row_slot[s][rel]
        return (di, lab, gain, inv, wts, row_slot.reshape(-1), disc)

    @contract.traced_pure
    def make_grad_fn(self):
        sigmoid = float(self.sigmoid)

        def grad_fn(score, state):
            doc_idx, lab, gain, inv, wts, row_slot, disc_table = state
            score = score.astype(jnp.float32)
            n_disc = disc_table.shape[0]

            def block(_, xs):
                di, lb, gn, iv, wb = xs
                valid = lb >= 0
                s = score[di]                           # [QB, L]
                s_sort = jnp.where(valid, s, -jnp.inf)
                # stable descending sort: first-by-score, ties by index
                # (reference uses non-stable std::sort — PARITY.md)
                order = jnp.argsort(-s_sort, axis=-1)
                rank_of = jnp.argsort(order, axis=-1)
                dsc = disc_table[jnp.minimum(rank_of, n_disc - 1)]
                dsc = jnp.where(valid, dsc, 0.0)
                best = jnp.max(s_sort, axis=-1)
                worst = jnp.min(jnp.where(valid, s, jnp.inf), axis=-1)
                norm = (best != worst)[:, None, None]
                ds = s[:, :, None] - s[:, None, :]      # [QB, L, L]
                vp = ((lb[:, :, None] > lb[:, None, :])
                      & valid[:, :, None] & valid[:, None, :])
                delta = ((gn[:, :, None] - gn[:, None, :])
                         * jnp.abs(dsc[:, :, None] - dsc[:, None, :])
                         * iv[:, None, None])
                delta = jnp.where(
                    norm, delta / (jnp.float32(0.01) + jnp.abs(ds)), delta)
                # direct sigmoid: the reference's 1M-entry lookup table
                # (rank_objective.hpp:175-189) is a CPU-era optimization;
                # a random gather of [QB, L, L] indices serializes on TPU
                # while the VPU computes exp at full rate.  Values differ
                # from the table path only by its quantization (~2.5e-5).
                p_lam = (jnp.float32(2.0)
                         / (jnp.float32(1.0)
                            + jnp.exp(jnp.float32(2.0 * sigmoid) * ds)))
                p_hess = p_lam * (jnp.float32(2.0) - p_lam)
                p_lam = jnp.where(vp, p_lam * -delta, 0.0)
                p_hess = jnp.where(vp, p_hess * jnp.float32(2.0) * delta,
                                   0.0)
                lam_doc = p_lam.sum(axis=2) - p_lam.sum(axis=1)
                hess_doc = p_hess.sum(axis=2) + p_hess.sum(axis=1)
                lam_doc = jnp.where(valid, lam_doc * wb, 0.0)
                hess_doc = jnp.where(valid, hess_doc * wb, 0.0)
                return None, (lam_doc, hess_doc)

            _, (lam_b, hes_b) = jax.lax.scan(
                block, None, (doc_idx, lab, gain, inv, wts))
            # per-doc outputs land in [nb*QB*L]; every real row owns one
            # slot, so ONE gather (no scatter) maps them back to [n_pad].
            # Padded rows carry the DEAD slot (pad_to) and read the
            # appended zero cell — no positional live-row assumption, so
            # the mapping survives ordered-partition row permutations.
            zero = jnp.zeros((1,), dtype=jnp.float32)
            lam_flat = jnp.concatenate([lam_b.reshape(-1), zero])
            hes_flat = jnp.concatenate([hes_b.reshape(-1), zero])
            return lam_flat[row_slot], hes_flat[row_slot]

        return grad_fn

    def _sigmoid_lut(self, s: np.ndarray) -> np.ndarray:
        idx = ((s - self.min_in) * self.idx_factor).astype(np.int64)
        idx = np.clip(idx, 0, _SIGMOID_BINS - 1)
        out = self.sigmoid_table[idx]
        out = np.where(s <= self.min_in, self.sigmoid_table[0], out)
        out = np.where(s >= self.max_in, self.sigmoid_table[-1], out)
        return out

    def get_gradients(self, score):
        if self.impl == "device":
            return self._dev_fn(jnp.asarray(score), self._dev_state)
        score_np = np.asarray(score, dtype=np.float32)
        # Reference-order native path: bit-parity with the golden models
        # needs libstdc++ std::sort tie permutations and sequential fp32
        # pair accumulation (rank_objective.hpp:76-164) — see native/.
        res = native.lambdarank_grads(
            score_np[:self.num_data], self.metadata.label, self.qb,
            self.inverse_max_dcgs, self.label_gain, self.discount,
            self.sigmoid_table, self.min_in, self.max_in, self.idx_factor,
            self.weights, self.n_pad)
        if res is not None:
            return jnp.asarray(res[0]), jnp.asarray(res[1])
        # padded rows (beyond the last query boundary) stay zero
        lambdas = np.zeros(self.n_pad, dtype=np.float32)
        hessians = np.zeros(self.n_pad, dtype=np.float32)
        label = self.metadata.label
        for q in range(len(self.qb) - 1):
            a, b = int(self.qb[q]), int(self.qb[q + 1])
            self._one_query(score_np[a:b], label[a:b],
                            self.inverse_max_dcgs[q],
                            lambdas[a:b], hessians[a:b])
        if self.weights is not None:
            lambdas[:self.num_data] *= self.weights
            hessians[:self.num_data] *= self.weights
        return jnp.asarray(lambdas), jnp.asarray(hessians)

    def _one_query(self, score, label, inv_max_dcg, lambdas, hessians):
        """Vectorized pairwise lambdas for one query
        (reference rank_objective.hpp:76-164)."""
        cnt = len(score)
        if cnt == 0 or inv_max_dcg <= 0:
            return
        order = np.argsort(-score, kind="stable")
        rank_of = np.empty(cnt, dtype=np.int64)
        rank_of[order] = np.arange(cnt)
        best = score[order[0]]
        worst_idx = cnt - 1
        if worst_idx > 0 and score[order[worst_idx]] == K_MIN_SCORE:
            worst_idx -= 1
        worst = score[order[worst_idx]]

        lab_i = label.astype(np.int64)
        gain = self.label_gain[lab_i].astype(np.float32)     # [L]
        disc = self.discount[rank_of].astype(np.float32)     # [L]

        # pair (h, l): labels[h] > labels[l]
        hi = lab_i[:, None] > lab_i[None, :]
        valid = hi & (score[None, :] != K_MIN_SCORE) \
                   & (score[:, None] != K_MIN_SCORE)
        if not valid.any():
            return
        ds = (score[:, None] - score[None, :]).astype(np.float32)
        dcg_gap = gain[:, None] - gain[None, :]
        paired_disc = np.abs(disc[:, None] - disc[None, :])
        delta = (dcg_gap * paired_disc * np.float32(inv_max_dcg))
        if best != worst:
            delta = delta / (np.float32(0.01) + np.abs(ds))
        p_lambda = self._sigmoid_lut(ds)
        p_hess = p_lambda * (np.float32(2.0) - p_lambda)
        p_lambda = p_lambda * -delta
        p_hess = p_hess * np.float32(2.0) * delta
        p_lambda = np.where(valid, p_lambda, 0.0).astype(np.float32)
        p_hess = np.where(valid, p_hess, 0.0).astype(np.float32)
        lambdas += p_lambda.sum(axis=1) - p_lambda.sum(axis=0)
        hessians += p_hess.sum(axis=1) + p_hess.sum(axis=0)


def default_label_gain():
    # 2^i - 1 (reference src/io/config.cpp:221-227)
    return [0.0] + [float((1 << i) - 1) for i in range(1, 31)]


def check_rank_label(label: np.ndarray, num_gains: int) -> None:
    """Labels must index label_gain (reference dcg_calculator.cpp:65's
    Log::Fatal, checked up front here because the native kernels index
    label_cnt/label_gain without bounds checks)."""
    lab = np.asarray(label)
    if len(lab) and (lab.min() < 0 or lab.max() >= num_gains):
        log.fatal("Ranking label out of range of label_gain: %g"
                  % (lab.min() if lab.min() < 0 else lab.max()))


def max_dcg_at_k(k: int, label: np.ndarray, label_gain: np.ndarray,
                 discount: np.ndarray) -> float:
    """DCGCalculator::CalMaxDCGAtK (reference dcg_calculator.cpp:34-57)."""
    lab = np.sort(label.astype(np.int64))[::-1]
    k = min(k, len(lab))
    return float((label_gain[lab[:k]] * discount[:k]).sum())


def create_objective(config: Config) -> Optional[Objective]:
    t = config.objective
    if t == "regression":
        return RegressionL2(config)
    if t == "binary":
        return BinaryLogloss(config)
    if t == "multiclass":
        return MulticlassSoftmax(config)
    if t == "lambdarank":
        return LambdarankNDCG(config)
    if t == "none":
        return None
    log.fatal("Unknown objective type %s" % t)
