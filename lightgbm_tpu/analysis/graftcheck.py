"""graftcheck — whole-program contract analysis.

Verifies the invariants declared at definition sites via
analysis/contracts.py decorators (and the `__jax_free__` module
marker), interprocedurally, over the call graph and import graph built
by analysis/callgraph.py.  graftlint stops at the function/module
boundary; these rules cross it:

  GC001 host-sync-reached-from-traced-pure
        A host sync (np.asarray/np.array, jax.device_get/put,
        .item(), .block_until_ready()) anywhere in the transitive call
        closure of a @contract.traced_pure function or a fused step
        body — a sync three helpers deep serializes the device
        pipeline exactly like one written inline.
  GC002 jax-reached-from-jax-free
        A module declaring `__jax_free__ = True` whose module-level
        import CLOSURE reaches a jax import (any number of hops), or a
        @contract.jax_free function whose call closure reaches a lazy
        `import jax` — either way jax enters sys.modules on a path
        contractually free of it.
  GC003 parity-oracle-violation
        The @contract.parity_oracle annotation set must equal
        contracts.EXPECTED_PARITY_ORACLES (removing/renaming an oracle
        annotation is itself a finding), and no oracle may transitively
        reach the clock or RNG outside utils/mt19937.
  GC004 lock-discipline
        A @contract.locked_by("<lock>") function must either acquire
        the named lock itself or be called ONLY from sites that
        lexically hold it (or from functions carrying the same
        contract, checked recursively) — an unlocked public entry
        point reaching the mutator is a finding.
  GC005 fused-body-contract
        The @contract.fused_body annotation set must equal
        contracts.EXPECTED_FUSED_BODIES; each maker's resolved body
        must consume exactly the FUSED_CORE inputs plus its declared
        extras (CONSUME_KINDS-normalized), its transitive collective
        set must equal the declared one, and every maker must declare
        the SAME collectives — six bodies, one effect signature, so the
        planned composable fused-step builder can replace them without
        surprises.
  GC006 uncounted-device-flush
        `jax.device_get` outside a @contract.counted_flush function:
        every deferred flush must go through the counted wrapper so
        analysis/guards.py transfer accounting (bench's
        device_gets_per_100_trees) cannot silently under-count.
  GC007 jax-free-undeclared
        A module under contracts.DECLARE_DIRS with no explicit
        `__jax_free__ = True/False` declaration — new serving/io/utils
        modules must state their import contract to enter the tree.
  GC008 unsanctioned-durable-write
        A binary write (`open(.., "wb"/"ab"/..)` or np.savez/np.save)
        outside a @contract.durable_write function: durable artifacts
        must route through resilience/atomic.py (tmp + fsync +
        os.replace + sha256 footer) — a bare binary write truncates in
        place when the process dies mid-write, and a truncated
        cache/snapshot/model poisons every later run.

Two sibling analyzers run in the same pass and share this module's
entry points and Finding stream: graftsync.py (GC009 collective-
sequence-divergence, GC010 collective-in-rank-local-loop, GC011
collective-outside-dist — the static SPMD collective-safety rules) and
lockgraph.py (GC012 lock-order: acquisition cycles and blocking
operations under fast serving locks).  See their module docstrings.

Entry points: run_graftcheck() for the installed package (or an
explicit root), run_graftcheck_sources() for an in-memory
{relpath: source} mapping (unit tests, the seeded-violation harness).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .callgraph import CallGraph, FunctionInfo, _dotted, _lockish_name
from .contracts import (CONSUME_KINDS, DECLARE_DIRS,
                        EXPECTED_FUSED_BODIES, EXPECTED_PARITY_ORACLES,
                        FUSED_CORE)
from .graftlint import RULE_NAMES, Finding

__jax_free__ = True

CHECK_RULES: Dict[str, str] = {
    "GC001": "host-sync-reached-from-traced-pure",
    "GC002": "jax-reached-from-jax-free",
    "GC003": "parity-oracle-violation",
    "GC004": "lock-discipline",
    "GC005": "fused-body-contract",
    "GC006": "uncounted-device-flush",
    "GC007": "jax-free-undeclared",
    "GC008": "unsanctioned-durable-write",
}
# graftsync (SPMD collective sequences, GC009-GC011) and lockgraph
# (lock order, GC012) run as part of every graftcheck pass — same
# graph, same Finding stream, same exit-code/baseline contract
from .graftsync import SYNC_RULES, run_graftsync_graph  # noqa: E402
from .lockgraph import LOCK_RULES, run_lockgraph_graph  # noqa: E402

CHECK_RULES.update(SYNC_RULES)
CHECK_RULES.update(LOCK_RULES)
RULE_NAMES.update(CHECK_RULES)


def _chain_str(graph: CallGraph,
               parent: Dict[FunctionInfo, Optional[FunctionInfo]],
               fn: FunctionInfo) -> str:
    return " -> ".join(f.qual for f in graph.chain(parent, fn))


def _emit(findings: List[Finding], rel: str, line: int, rule: str,
          message: str) -> None:
    findings.append(Finding(rel, line, rule, message))


# ---------------------------------------------------------------------------
# GC001 — interprocedural trace purity
# ---------------------------------------------------------------------------

def check_traced_pure(graph: CallGraph,
                      findings: List[Finding]) -> None:
    roots = graph.contracted("traced_pure") + graph.contracted(
        "fused_body")
    parent = graph.reach(roots)
    for fn in parent:
        eff = graph.effects(fn)
        for line, what in eff.host_syncs:
            _emit(findings, fn.module.rel, line, "GC001",
                  "%s in %s is a host sync inside the traced-pure "
                  "closure: %s"
                  % (what, fn.qual, _chain_str(graph, parent, fn)))


# ---------------------------------------------------------------------------
# GC002 — transitive jax reach
# ---------------------------------------------------------------------------

def check_jax_free(graph: CallGraph, findings: List[Finding]) -> None:
    # module granularity: the whole module-level import closure
    for rel, mod in sorted(graph.modules.items()):
        if mod.jax_free is not True:
            continue
        chain = graph.jax_reach_chain(rel)
        if chain is not None and len(chain) > 1:
            _emit(findings, rel, 1, "GC002",
                  "jax-free module transitively imports jax: %s"
                  % " -> ".join(chain))
        elif chain is not None:
            _emit(findings, rel, 1, "GC002",
                  "module declares __jax_free__ = True but imports jax "
                  "at module level")
    # function granularity: the call closure must not execute a lazy
    # jax import either
    roots = graph.contracted("jax_free")
    parent = graph.reach(roots)
    for fn in parent:
        eff = graph.effects(fn)
        for line in eff.jax_imports:
            _emit(findings, fn.module.rel, line, "GC002",
                  "lazy jax import in %s is reachable from a "
                  "@contract.jax_free function: %s"
                  % (fn.qual, _chain_str(graph, parent, fn)))
        if fn.module.jax_module_level and fn not in roots:
            _emit(findings, fn.module.rel,
                  getattr(fn.node, "lineno", 1), "GC002",
                  "%s lives in a module that imports jax at module "
                  "level but is reachable from a @contract.jax_free "
                  "function: %s"
                  % (fn.qual, _chain_str(graph, parent, fn)))


# ---------------------------------------------------------------------------
# GC003 — parity oracles
# ---------------------------------------------------------------------------

def check_parity_oracles(graph: CallGraph,
                         findings: List[Finding]) -> None:
    annotated = graph.contracted("parity_oracle")
    have = {fn.qual for fn in annotated}
    want = set(EXPECTED_PARITY_ORACLES)
    for qual in sorted(want - have):
        rel = qual.split("::", 1)[0]
        _emit(findings, rel, 1, "GC003",
              "parity oracle %s is missing its @contract.parity_oracle "
              "annotation (registry: contracts.EXPECTED_PARITY_ORACLES "
              "— an oracle was removed or renamed without updating the "
              "contract)" % qual)
    for fn in annotated:
        if fn.qual not in want:
            _emit(findings, fn.module.rel,
                  getattr(fn.node, "lineno", 1), "GC003",
                  "%s carries @contract.parity_oracle but is not in "
                  "contracts.EXPECTED_PARITY_ORACLES — register it (the "
                  "oracle SET is part of the contract)" % fn.qual)
    parent = graph.reach(annotated)
    for fn in parent:
        eff = graph.effects(fn)
        for line, what in eff.rng_clock:
            _emit(findings, fn.module.rel, line, "GC003",
                  "%s in %s is reachable from a parity oracle "
                  "(randomness must come from utils/mt19937, no value "
                  "may depend on the clock): %s"
                  % (what, fn.qual, _chain_str(graph, parent, fn)))


# ---------------------------------------------------------------------------
# GC004 — lock discipline
# ---------------------------------------------------------------------------

def _call_under_lock(call: ast.AST, lock: str) -> bool:
    cur = getattr(call, "_gl_parent", None)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                if _lockish_name(item.context_expr) == lock:
                    return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        cur = getattr(cur, "_gl_parent", None)
    return False


def check_lock_discipline(graph: CallGraph,
                          findings: List[Finding]) -> None:
    from .callgraph import own_nodes
    targets = graph.contracted("locked_by")
    # one package scan indexes every attribute call by method name —
    # the per-target fallback below then reads the index instead of
    # re-walking the whole tree per contract
    wanted = {t.name for t in targets}
    attr_calls: Dict[str, List[Tuple[FunctionInfo, ast.Call]]] = {}
    if wanted:
        for mod in graph.modules.values():
            for fn in mod.all_functions:
                for node in own_nodes(fn.node):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Attribute) \
                            and node.func.attr in wanted:
                        attr_calls.setdefault(node.func.attr,
                                              []).append((fn, node))
    for target in targets:
        lock = str(target.contracts["locked_by"].get("lock", "_lock"))
        if lock in graph.effects(target).acquired_locks:
            continue  # self-acquiring: discipline holds locally
        sites: List[Tuple[FunctionInfo, ast.Call]] = \
            graph.call_sites_of(target)
        resolved_ids = {id(call) for _, call in sites}
        # resolution is conservative; a call shape the resolver cannot
        # bind (`for h in hists: h.observe(v)` on a passed-in object)
        # must not silently escape the contract.  Fallback: any
        # PACKAGE-WIDE attribute call matching the mutator's name is
        # held to the lock too.  Deliberately over-approximate — a
        # same-named method of an unrelated class gets flagged and
        # must rename or take the lock; for a lock rule that is the
        # right direction to fail in.
        for fn, node in attr_calls.get(target.name, []):
            if fn is not target and id(node) not in resolved_ids:
                sites.append((fn, node))
        if not sites:
            _emit(findings, target.module.rel,
                  getattr(target.node, "lineno", 1), "GC004",
                  "%s declares locked_by(%r) but no call site resolves "
                  "— the contract cannot be verified; acquire the lock "
                  "in the function itself or keep a resolvable call "
                  "shape" % (target.qual, lock))
            continue
        for caller, call in sites:
            if _call_under_lock(call, lock):
                continue
            caller_contract = caller.contracts.get("locked_by")
            if caller_contract is not None \
                    and caller_contract.get("lock") == lock:
                continue  # the caller's own call sites are checked
            _emit(findings, caller.module.rel,
                  getattr(call, "lineno", 1), "GC004",
                  "call to %s (locked_by %r) from %s without holding "
                  "the lock — every path into the mutator must hold %r"
                  % (target.qual, lock, caller.qual, lock))


# ---------------------------------------------------------------------------
# GC005 — fused-body effect signatures
# ---------------------------------------------------------------------------

def _resolve_fused_bodies(graph: CallGraph,
                          maker: FunctionInfo) -> List[FunctionInfo]:
    bodies: List[FunctionInfo] = []
    from .callgraph import own_nodes
    for node in own_nodes(maker.node):
        if not isinstance(node, ast.Call):
            continue
        for callee in graph._resolve_callee_expr(maker, node.func):
            if callee.name == "_batch_iters" and node.args:
                for b in graph._resolve_callee_expr(maker, node.args[0]):
                    if b not in bodies:
                        bodies.append(b)
    if not bodies:
        bodies = graph.returned_closures(maker)
    return bodies


def _body_consumes(body: FunctionInfo) -> Tuple[Set[str], List[str]]:
    """(normalized input kinds, parameter names with no declared kind)."""
    node = body.node
    assert isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    names = [a.arg for a in (list(node.args.posonlyargs)
                             + list(node.args.args)
                             + list(node.args.kwonlyargs))]
    if node.args.vararg is not None:
        names.append(node.args.vararg.arg)
    kinds: Set[str] = set()
    unknown: List[str] = []
    for n in names:
        kind = CONSUME_KINDS.get(n)
        if kind is None:
            unknown.append(n)
        else:
            kinds.add(kind)
    return kinds, unknown


def check_fused_bodies(graph: CallGraph,
                       findings: List[Finding]) -> None:
    annotated = graph.contracted("fused_body")
    have = {fn.qual for fn in annotated}
    want = set(EXPECTED_FUSED_BODIES)
    for qual in sorted(want - have):
        rel = qual.split("::", 1)[0]
        _emit(findings, rel, 1, "GC005",
              "fused step maker %s is missing its @contract.fused_body "
              "annotation (registry: contracts.EXPECTED_FUSED_BODIES — "
              "a maker was removed or renamed without updating the "
              "contract)" % qual)
    for fn in annotated:
        if fn.qual not in want:
            _emit(findings, fn.module.rel,
                  getattr(fn.node, "lineno", 1), "GC005",
                  "%s carries @contract.fused_body but is not in "
                  "contracts.EXPECTED_FUSED_BODIES — register it (the "
                  "maker SET is part of the contract)" % fn.qual)

    # uniformity of DECLARED collectives across all makers
    declared_sets = {fn.qual: frozenset(
        str(c) for c in fn.contracts["fused_body"].get(
            "collectives", ()))                     # type: ignore[union-attr]
        for fn in annotated}
    if len(set(declared_sets.values())) > 1:
        for fn in annotated:
            _emit(findings, fn.module.rel,
                  getattr(fn.node, "lineno", 1), "GC005",
                  "%s declares collectives %s but the fused bodies must "
                  "declare ONE uniform collective set (found %s across "
                  "makers)"
                  % (fn.qual, sorted(declared_sets[fn.qual]),
                     sorted({tuple(sorted(s))
                             for s in declared_sets.values()})))

    core = set(FUSED_CORE)
    for fn in annotated:
        spec = fn.contracts["fused_body"]
        declared_extras = {str(e) for e in spec.get("extras", ())}
        declared_coll = {str(c) for c in spec.get("collectives", ())}
        bodies = _resolve_fused_bodies(graph, fn)
        if not bodies:
            _emit(findings, fn.module.rel,
                  getattr(fn.node, "lineno", 1), "GC005",
                  "%s: could not resolve the fused step body through "
                  "the call graph (the maker must build its body via "
                  "_batch_iters or return a local closure)" % fn.qual)
            continue
        for body in bodies:
            kinds, unknown = _body_consumes(body)
            for name in unknown:
                _emit(findings, body.module.rel,
                      getattr(body.node, "lineno", 1), "GC005",
                      "%s (body of %s) consumes parameter %r with no "
                      "canonical input kind — extend "
                      "contracts.CONSUME_KINDS deliberately or use a "
                      "canonical name" % (body.qual, fn.qual, name))
            missing = core - kinds
            if missing:
                _emit(findings, body.module.rel,
                      getattr(body.node, "lineno", 1), "GC005",
                      "%s (body of %s) does not consume the uniform "
                      "core input(s) %s — all fused bodies share ONE "
                      "effect signature (contracts.FUSED_CORE)"
                      % (body.qual, fn.qual, sorted(missing)))
            undeclared = (kinds - core) - declared_extras
            if undeclared:
                _emit(findings, body.module.rel,
                      getattr(body.node, "lineno", 1), "GC005",
                      "%s (body of %s) consumes extra input kind(s) %s "
                      "not declared in @contract.fused_body(extras=...)"
                      % (body.qual, fn.qual, sorted(undeclared)))
            parent = graph.reach([body])
            seen_coll: Set[str] = set()
            for reached in parent:
                seen_coll |= graph.effects(reached).collectives
            if seen_coll != declared_coll:
                _emit(findings, body.module.rel,
                      getattr(body.node, "lineno", 1), "GC005",
                      "%s (body of %s) transitively uses collectives %s "
                      "but @contract.fused_body declares %s — the six "
                      "bodies must keep one uniform collective "
                      "signature"
                      % (body.qual, fn.qual, sorted(seen_coll),
                         sorted(declared_coll)))


# ---------------------------------------------------------------------------
# GC006 — counted flush discipline
# ---------------------------------------------------------------------------

def _in_counted_flush(fn: FunctionInfo) -> bool:
    cur: Optional[FunctionInfo] = fn
    while cur is not None:
        if "counted_flush" in cur.contracts:
            return True
        cur = cur.parent
    return False


def check_counted_flush(graph: CallGraph,
                        findings: List[Finding]) -> None:
    for rel, mod in sorted(graph.modules.items()):
        if rel.startswith("analysis/"):
            continue  # guards.py IS the counter
        for fn in mod.all_functions:
            if _in_counted_flush(fn):
                continue
            for line in graph.effects(fn).device_gets:
                _emit(findings, rel, line, "GC006",
                      "jax.device_get in %s, outside any "
                      "@contract.counted_flush function — deferred "
                      "flushes must go through the counted wrapper so "
                      "guards/bench transfer accounting stays honest"
                      % fn.qual)


# ---------------------------------------------------------------------------
# GC008 — durable-write discipline
# ---------------------------------------------------------------------------

_NP_SAVERS = ("np.savez", "numpy.savez", "np.savez_compressed",
              "numpy.savez_compressed", "np.save", "numpy.save")


def _durable_write_call(node: ast.AST) -> Optional[str]:
    """What kind of bare binary write this Call is, or None."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Name) and f.id == "open":
        mode: Optional[str] = None
        if len(node.args) >= 2 \
                and isinstance(node.args[1], ast.Constant) \
                and isinstance(node.args[1].value, str):
            mode = node.args[1].value
        for kw in node.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                mode = kw.value.value
        if mode and "b" in mode \
                and any(c in mode for c in ("w", "a", "x", "+")):
            return "open(.., %r)" % mode
        return None
    dotted = _dotted(f)
    if dotted in _NP_SAVERS:
        return dotted
    return None


def _in_durable_write(fn: FunctionInfo) -> bool:
    cur: Optional[FunctionInfo] = fn
    while cur is not None:
        if "durable_write" in cur.contracts:
            return True
        cur = cur.parent
    return False


def check_durable_writes(graph: CallGraph,
                         findings: List[Finding]) -> None:
    from .callgraph import own_nodes
    for rel, mod in sorted(graph.modules.items()):
        for fn in mod.all_functions:
            if _in_durable_write(fn):
                continue
            for node in own_nodes(fn.node):
                what = _durable_write_call(node)
                if what is not None:
                    _emit(findings, rel,
                          getattr(node, "lineno", 1), "GC008",
                          "%s in %s is a bare binary write to a "
                          "durable artifact — route it through "
                          "resilience/atomic.py (atomic_writer / "
                          "write_npz) or contract the function "
                          "@contract.durable_write" % (what, fn.qual))
        # module-level writes (rare, but a cache warm at import time
        # must not escape the rule): walk import-time statements —
        # function bodies are statements of their own and were
        # excluded at collection, so this covers exactly the rest
        for stmt in _module_level_write_stmts(mod.tree):
            for node in _walk_skip_contracted(stmt):
                what = _durable_write_call(node)
                if what is not None:
                    _emit(findings, rel,
                          getattr(node, "lineno", 1), "GC008",
                          "%s at module level is a bare binary write "
                          "to a durable artifact — route it through "
                          "resilience/atomic.py" % what)


def _walk_skip_contracted(stmt: ast.stmt) -> Iterable[ast.AST]:
    """ast.walk, except a function def nested inside a module-level
    compound statement (an `if`/`try` import shim) keeps its own
    contract: callgraph._collect_defs does not collect such defs, so
    this walk must honor an explicit @contract.durable_write on them
    instead of flagging the body as a module-level write."""
    from .callgraph import _contract_of_decorator
    stack: List[ast.AST] = [stmt]
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            parsed = (_contract_of_decorator(d)
                      for d in node.decorator_list)
            if any(p is not None and p[0] == "durable_write"
                   for p in parsed):
                continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _module_level_write_stmts(tree: ast.Module) -> List[ast.stmt]:
    out: List[ast.stmt] = []
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
            out.append(node)
        elif isinstance(node, ast.ClassDef):
            out.extend(s for s in node.body
                       if not isinstance(s, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)))
    return out


# ---------------------------------------------------------------------------
# GC007 — jax-free declarations
# ---------------------------------------------------------------------------

def check_declarations(graph: CallGraph,
                       findings: List[Finding]) -> None:
    from .contracts import EXPECTED_JAX_FREE
    for rel, mod in sorted(graph.modules.items()):
        top = rel.split("/", 1)[0] if "/" in rel else ""
        if top in DECLARE_DIRS and mod.jax_free is None \
                and rel not in EXPECTED_JAX_FREE:
            _emit(findings, rel, 1, "GC007",
                  "module under %s/ must declare `__jax_free__ = True` "
                  "or `__jax_free__ = False` explicitly (new modules "
                  "cannot silently escape the jax-free gate)" % top)
    # the pinned set: the load-bearing fast-path modules must STAY
    # declared jax-free — deleting or flipping the marker is a finding,
    # not an escape hatch
    for rel in EXPECTED_JAX_FREE:
        mod = graph.modules.get(rel)
        if mod is None:
            continue  # module deleted/renamed: the import graph breaks
        if mod.jax_free is not True:
            _emit(findings, rel, 1, "GC007",
                  "module is pinned jax-free by "
                  "contracts.EXPECTED_JAX_FREE but does not declare "
                  "`__jax_free__ = True` — the marker was removed or "
                  "flipped without updating the registry")


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------

def run_graftcheck_graph(graph: CallGraph,
                         graftsync: bool = True) -> List[Finding]:
    findings: List[Finding] = []
    for rel, msg in graph.errors:
        _emit(findings, rel, 1, "GC007", "unparseable module: %s" % msg)
    check_traced_pure(graph, findings)
    check_jax_free(graph, findings)
    check_parity_oracles(graph, findings)
    check_lock_discipline(graph, findings)
    check_fused_bodies(graph, findings)
    check_counted_flush(graph, findings)
    check_durable_writes(graph, findings)
    check_declarations(graph, findings)
    if graftsync:
        findings += run_graftsync_graph(graph)
        findings += run_lockgraph_graph(graph)
    # stable order + dedup (one defect can surface through two roots)
    uniq: Dict[Tuple[str, int, str, str], Finding] = {}
    for f in findings:
        uniq.setdefault((f.path, f.line, f.rule, f.message), f)
    out = list(uniq.values())
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out


def run_graftcheck(root: Optional[str] = None,
                   paths: Optional[Iterable[str]] = None,
                   graftsync: bool = True) -> List[Finding]:
    """Analyze the package rooted at `root` (default: the installed
    lightgbm_tpu).  `paths` optionally filters the REPORTED findings to
    the given package-relative module paths; the analysis itself is
    always whole-program (the rules are interprocedural)."""
    graph = CallGraph.from_root(root)
    findings = run_graftcheck_graph(graph, graftsync=graftsync)
    if paths is not None:
        keep = {p.replace("\\", "/") for p in paths}
        findings = [f for f in findings if f.path in keep]
    return findings


def run_graftcheck_sources(sources: Dict[str, str]) -> List[Finding]:
    """Analyze an in-memory {relpath: source} package image (the
    seeded-violation harness and unit tests)."""
    return run_graftcheck_graph(CallGraph(sources))
