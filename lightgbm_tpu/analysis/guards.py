"""Runtime guards: XLA compile counting and host<->device transfer
accounting.

The serving forest promises "steady state never recompiles" (its
power-of-two row buckets pre-compile in warm()) and the fused training
step promises one compile per (shape, config); until now nothing
measured either.  `track_compiles()` captures jax's own compile logging
("Compiling <name> ..." lowering records and "Finished XLA compilation"
backend records) through a logging.Handler while jax_log_compiles is
force-enabled, so a test can assert an exact compile budget.  Cache
HITS (the jit C++ fast path) log nothing — a steady-state dispatch of
an already-compiled executable counts zero.

Counted signals:
  * stats.compiles — lowerings ("Compiling ..."): every trace+lower of
    a new (shape, config) key, whether or not the backend compile is
    later served from the persistent cache.  This is the recompile
    signal the invariants are stated in.
  * stats.backend_compiles — XLA backend compile records.  CAVEAT: the
    dispatch timing record fires for persistent-cache DESERIALIZATION
    too, so this over-counts on cache-warm processes — use the
    cache_hits/cache_misses pair to split them.
  * stats.cache_hits / cache_misses — persistent compilation cache
    probes (jax lru_cache "Cache hit for key" records and the
    compiler's "PERSISTENT COMPILATION CACHE MISS" records).  A fresh
    process of an already-seen shape shows misses == 0: the cross-run
    zero-compile claim (tests/test_cache_cross_process.py, and
    bench.py's compile_s cold/cache-warm split).
  * stats.device_puts / device_gets — explicit jax.device_put /
    jax.device_get calls made through the `jax` module attributes
    (wrapped for the duration).  Implicit transfers are policed by the
    `transfer_guard` argument, which forwards to jax.transfer_guard
    (e.g. "disallow" makes any implicit transfer raise).

Use either the raw tracker or the budget-asserting wrapper:

    with track_compiles() as stats:
        f(x)
    assert stats.compiles == 1

    with compile_budget(max_compiles=0, what="serving steady state"):
        forest.predict(rows, "raw")

Pytest: the `xla_guard` fixture (registered via tests/conftest.py)
returns `compile_budget`, so tests write
`with xla_guard(0, what="..."):`.

Thread-safe enough for the serving tests: the capture handler appends
from whatever thread compiles (batcher workers included); list.append
is atomic under the GIL.
"""

from __future__ import annotations

__jax_free__ = True

import contextlib
import dataclasses
import logging
import re
from typing import Iterator, List, Optional

__all__ = ["GuardViolation", "GuardStats", "track_compiles",
           "compile_budget"]


class GuardViolation(AssertionError):
    """A guarded region exceeded its declared compile/transfer budget."""


@dataclasses.dataclass
class GuardStats:
    lowerings: List[str] = dataclasses.field(default_factory=list)
    backend_compiles: List[str] = dataclasses.field(default_factory=list)
    cache_hits: int = 0
    cache_misses: int = 0
    device_puts: int = 0
    device_gets: int = 0

    @property
    def compiles(self) -> int:
        return len(self.lowerings)

    def summary(self) -> str:
        names = ", ".join(self.lowerings[:8]) or "-"
        if len(self.lowerings) > 8:
            names += ", ... (%d total)" % len(self.lowerings)
        return ("%d compile(s) [%s], %d backend compile(s), "
                "%d cache hit(s)/%d miss(es), %d device_put, "
                "%d device_get"
                % (self.compiles, names, len(self.backend_compiles),
                   self.cache_hits, self.cache_misses,
                   self.device_puts, self.device_gets))


_COMPILING_RE = re.compile(r"Compiling (\S+)")
_FINISHED_RE = re.compile(r"Finished XLA compilation of (\S+)")
# persistent-cache probe records: the hit comes from the cache backend
# ("Cache hit for key: ..."), the authoritative miss from the compiler
# ("PERSISTENT COMPILATION CACHE MISS ..." — the backend also logs a
# lowercase "Cache miss for key" for the same probe, which is ignored
# so a miss counts once)
_CACHE_HIT_RE = re.compile(r"Cache hit for key")
_CACHE_MISS_RE = re.compile(r"PERSISTENT COMPILATION CACHE MISS")
# jax loggers that carry the records (jax 0.4.x: lowering logs from
# interpreters.pxla, backend-compile timing from dispatch, persistent-
# cache probes from lru_cache/compiler)
_LOGGER_NAMES = ("jax._src.interpreters.pxla", "jax._src.dispatch",
                 "jax._src.lru_cache", "jax._src.compiler")


class _CaptureHandler(logging.Handler):
    def __init__(self, stats: GuardStats):
        super().__init__(level=logging.DEBUG)
        self._stats = stats

    def emit(self, record: logging.LogRecord) -> None:
        msg = record.getMessage()
        m = _COMPILING_RE.search(msg)
        if m:
            self._stats.lowerings.append(m.group(1))
            return
        m = _FINISHED_RE.search(msg)
        if m:
            self._stats.backend_compiles.append(m.group(1))
            return
        if _CACHE_HIT_RE.search(msg):
            self._stats.cache_hits += 1
        elif _CACHE_MISS_RE.search(msg):
            self._stats.cache_misses += 1


@contextlib.contextmanager
def track_compiles(
        transfer_guard: Optional[str] = None) -> Iterator[GuardStats]:
    """Count XLA compiles (and explicit transfers) in a with-block.

    transfer_guard: forwarded to jax.transfer_guard for the scope
    ("log", "disallow", ...); None leaves the transfer policy alone.
    """
    import jax

    stats = GuardStats()
    handler = _CaptureHandler(stats)
    prev_flag = bool(jax.config.jax_log_compiles)
    jax.config.update("jax_log_compiles", True)
    touched: List[logging.Logger] = []
    prev_levels: List[int] = []
    prev_propagate: List[bool] = []
    for name in _LOGGER_NAMES:
        lg = logging.getLogger(name)
        touched.append(lg)
        prev_levels.append(lg.level)
        prev_propagate.append(lg.propagate)
        if lg.level > logging.DEBUG or lg.level == logging.NOTSET:
            lg.setLevel(logging.DEBUG)
        # keep the forced compile logging out of the user's stderr: the
        # records exist for the counter, not for display
        lg.propagate = False
        lg.addHandler(handler)

    real_put, real_get = jax.device_put, jax.device_get

    def counting_put(*args: object, **kw: object) -> object:
        stats.device_puts += 1
        return real_put(*args, **kw)

    def counting_get(*args: object, **kw: object) -> object:
        stats.device_gets += 1
        return real_get(*args, **kw)

    jax.device_put, jax.device_get = counting_put, counting_get
    try:
        if transfer_guard is not None:
            with jax.transfer_guard(transfer_guard):
                yield stats
        else:
            yield stats
    finally:
        jax.device_put, jax.device_get = real_put, real_get
        for lg, lv, pr in zip(touched, prev_levels, prev_propagate):
            lg.removeHandler(handler)
            lg.setLevel(lv)
            lg.propagate = pr
        jax.config.update("jax_log_compiles", prev_flag)


@contextlib.contextmanager
def compile_budget(max_compiles: int, *,
                   max_device_puts: Optional[int] = None,
                   max_device_gets: Optional[int] = None,
                   transfer_guard: Optional[str] = None,
                   what: str = "guarded region") -> Iterator[GuardStats]:
    """track_compiles + assertion: more than `max_compiles` lowerings
    (or transfers past their optional budgets) raises GuardViolation
    naming the offending executables."""
    with track_compiles(transfer_guard=transfer_guard) as stats:
        yield stats
    if stats.compiles > max_compiles:
        raise GuardViolation(
            "%s: %d XLA compile(s), budget %d — %s"
            % (what, stats.compiles, max_compiles, stats.summary()))
    if max_device_puts is not None and stats.device_puts > max_device_puts:
        raise GuardViolation(
            "%s: %d jax.device_put call(s), budget %d"
            % (what, stats.device_puts, max_device_puts))
    if max_device_gets is not None and stats.device_gets > max_device_gets:
        raise GuardViolation(
            "%s: %d jax.device_get call(s), budget %d"
            % (what, stats.device_gets, max_device_gets))


try:  # pytest is optional at runtime; the fixture only exists for tests
    import pytest as _pytest
except ImportError:  # pragma: no cover - production image without pytest
    _pytest = None  # type: ignore[assignment]

if _pytest is not None:
    @_pytest.fixture
    def xla_guard() -> object:
        """`with xla_guard(0, what="serving steady state"): ...` — the
        compile_budget context manager as a fixture, so tests declare
        compile budgets without importing the analysis package."""
        return compile_budget

    @_pytest.fixture
    def collective_trace() -> object:
        """`with collective_trace() as events: ...` — the per-rank
        host-collective ring buffer (parallel/dist.trace_collectives)
        as a fixture, same pattern as xla_guard.  Each event is a
        (name, shape, dtype, callsite) CollectiveEvent."""
        from ..parallel.dist import trace_collectives
        return trace_collectives
