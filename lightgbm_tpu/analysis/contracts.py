"""graftcheck contract registry — invariants declared at the definition
site, verified whole-program by analysis/graftcheck.py.

graftlint (graftlint.py) checks invariants it can see from ONE module's
AST.  The contracts here carry the invariants that are only meaningful
across modules: a fused step body must stay trace-pure through every
helper it calls (ops/grow.py, ops/predict.py, ...), a jax-free module
must stay jax-free through its whole import closure, a serving mutator
is only correct if every call path into it holds the lock.  Each
decorator is a ZERO-COST runtime no-op (it tags and returns the
function unchanged — stdlib only, safe in jax-free modules and on hot
paths); the analyzer reads the decoration from the AST, so the checks
run without importing the annotated code.

Contract classes (checking rules live in graftcheck.py):

  @contract.traced_pure
      This function (and, for factories, the closures it returns) is
      device code: nothing it TRANSITIVELY calls inside the package may
      host-sync (np.asarray/np.array, jax.device_get/put, .item(),
      .block_until_ready()).  Rule GC001.

  @contract.parity_oracle("why this path is the oracle")
      This function is a bit-parity oracle (PARITY.md): the K=1 /
      masked / general paths other configurations are tested against.
      Nothing it transitively calls may read the clock or any RNG
      outside utils/mt19937, and the set of oracles is pinned by
      EXPECTED_PARITY_ORACLES — removing or renaming an annotation is
      itself a finding.  Rule GC003.

  @contract.jax_free
      This function must be callable without jax entering sys.modules:
      nothing it transitively calls may import jax, not even lazily
      inside a function body.  (Module-granular jax-freedom is declared
      with a module-level `__jax_free__ = True` marker instead — see
      below.)  Rule GC002.

  @contract.locked_by("_lock")
      Every self.* store in this function is protected by the named
      lock, which the CALLER holds: the analyzer verifies every package
      call path into the function lexically holds a `with <...name>:`
      (or passes through another function with the same contract), and
      graftlint GL006 stops demanding per-line suppressions inside it.
      Rule GC004.

  @contract.fused_body(extras=(...), collectives=(...))
      This step MAKER builds one of the fused training-step bodies
      (models/gbdt.py).  The analyzer resolves the maker to its body
      closure(s) through the call graph and verifies the body's EFFECT
      SIGNATURE: it consumes exactly the FUSED_CORE inputs plus the
      declared extras (parameter names normalized via CONSUME_KINDS),
      its transitive collective set equals the declared one, and every
      maker declares the SAME collectives — so any drift between the
      six bodies that would break the planned composable fused-step
      builder (ROADMAP) is a lint error today.  The full maker set is
      pinned by EXPECTED_FUSED_BODIES.  Rule GC005.

  @contract.counted_flush
      This function is a sanctioned deferred-flush site: the ONLY place
      allowed to call jax.device_get, so analysis/guards.py transfer
      accounting (bench's device_gets_per_100_trees) cannot silently
      under-count when a new code path materializes device buffers.
      Rule GC006.

  @contract.durable_write
      This function is a sanctioned durable-artifact writer: binary
      writes (`open(.., "wb"/"ab")`, np.savez) are only legal inside a
      function carrying this contract — everything else must route
      through resilience/atomic.py (tmp + fsync + os.replace + sha256
      footer), because a bare binary write crash-truncates in place
      and poisons every later run.  Rule GC008.

  @contract.rank_uniform
      This function's RETURN VALUE is identical on every rank — it is
      derived only from fingerprint-synced config, collective results
      (vote_any / sync_max_ints / process_allgather), or deterministic
      counters that advance in lockstep.  The SPMD-divergence analyzer
      (graftsync, rules GC009/GC010) accepts a branch condition or
      loop bound fed by such a call as rank-uniform; everything else
      defaults to rank-LOCAL, because a collective behind a rank-local
      branch hangs the whole pool with no diagnostic.  Annotating a
      function that actually returns rank-local state disables the
      analyzer's protection for its callers — the annotation is a
      reviewed claim, like parity_oracle's note.  Rules GC009-GC010.

Module marker — jax-free modules declare themselves:

    __jax_free__ = True     # module + its import closure never pull jax

graftlint GL002 discovers its module set from this marker (the
hard-coded list is gone), graftcheck GC002 verifies the whole import
closure, and GC007 requires every module under DECLARE_DIRS to carry
an explicit `__jax_free__ = True/False` so a new serving/io module
cannot silently escape the gate.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Mapping, Tuple, TypeVar

__jax_free__ = True

F = TypeVar("F", bound=Callable[..., Any])

#: attribute the runtime decorators tag functions with (introspection
#: convenience only — the analyzer reads the AST, never this attribute)
CONTRACT_ATTR = "__contracts__"

#: module-level marker name declaring a module's jax-freedom
JAX_FREE_MARKER = "__jax_free__"

#: package directories where EVERY module must declare __jax_free__
#: explicitly (True or False) — rule GC007.  A new module dropped into
#: one of these trees is a finding until its author states the import
#: contract one way or the other.
DECLARE_DIRS: Tuple[str, ...] = ("serving", "io", "utils", "analysis",
                                 "native", "parallel", "models",
                                 "resilience", "ingest", "refresh")

#: modules PINNED jax-free: these must declare `__jax_free__ = True` —
#: deleting the marker (or flipping it to False) is a finding (GC007),
#: exactly like removing a parity-oracle annotation.  This is the old
#: hard-coded GL002 list reborn as a registry: discovery governs the
#: GATE (any marked module is checked), the registry governs the SET
#: (the load-bearing fast paths cannot silently leave it).
EXPECTED_JAX_FREE: Tuple[str, ...] = (
    "__init__.py", "__main__.py", "cli.py", "config.py",
    "predict_fast.py",
    "io/__init__.py", "io/parser.py", "io/binning.py", "io/dataset.py",
    "models/__init__.py", "models/tree.py",
    "native/__init__.py",
    "parallel/__init__.py", "parallel/dist.py",
    "serving/__init__.py", "serving/forest.py", "serving/batcher.py",
    "serving/server.py", "serving/fleet.py", "serving/frontend.py",
    # the low-latency lane: the flat-table engine and the host-side
    # rank-encode pack builder it shares with the device matmul route
    # both serve inside backend=native worker processes
    "serving/flatforest.py", "ops/predict_host.py",
    "utils/__init__.py", "utils/log.py", "utils/mt19937.py",
    "utils/compile_cache.py",
    # the fault-tolerance layer rides inside the jax-free fast paths
    # (predict_fast results, serving fallback, CLI snapshot cadence)
    "resilience/__init__.py", "resilience/atomic.py",
    "resilience/backoff.py", "resilience/faults.py",
    "resilience/net.py", "resilience/snapshot.py",
    # out-of-core ingestion: the parse/shard-write paths run in
    # jax-free lanes (CLI task=ingest, multiprocessing parse workers)
    "ingest/__init__.py", "ingest/manifest.py", "ingest/writer.py",
    "ingest/shards.py", "ingest/synth.py",
    # continuous refresh: the deploy agent is a supervisor-family
    # process (watch + subprocess + HTTP) — a jax import here would
    # tax every cycle with a backend init the agent never uses
    "refresh/__init__.py", "refresh/agent.py",
)

# ---------------------------------------------------------------------------
# Fused-body effect signature vocabulary (rule GC005)
# ---------------------------------------------------------------------------

#: canonical inputs EVERY fused step body consumes — the uniform core
#: the composable fused-step builder will be written against
FUSED_CORE: Tuple[str, ...] = ("scores", "valid_scores", "bag", "fmask",
                               "bins", "valid_bins", "gstate", "stopped")

#: body parameter name -> canonical effect-input kind.  A body parameter
#: whose name is missing here is an UNDECLARED input kind (a finding):
#: extend this table deliberately when the builder grows a new input.
CONSUME_KINDS: Mapping[str, str] = {
    "scores": "scores",
    "valid_scores": "valid_scores",
    "bag_mask": "bag", "bag_masks": "bag",
    "fmask": "fmask", "fmasks": "fmask",
    "bins": "bins",
    "valid_bins": "valid_bins",
    "gstate": "gstate",
    "stopped": "stopped",
    "row_order": "order",
    # DART device-bank inputs
    "bank_i": "bank", "bank_f": "bank", "leaf_bank": "bank",
    "vbanks": "bank", "t_row": "bank",
    # DART drop/normalize schedule inputs
    "drop_idx": "dart", "drop_mask": "dart", "lr": "dart", "kf": "dart",
}

#: collective primitives (matched as jax.lax.X / lax.X in the AST)
COLLECTIVE_OPS: Tuple[str, ...] = (
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "pshuffle", "psum_scatter", "axis_index",
)

# ---------------------------------------------------------------------------
# SPMD collective-sequence vocabulary (graftsync, rules GC009-GC011)
# ---------------------------------------------------------------------------

#: host-level collective wrappers exported by parallel/dist.py — the
#: ATOMS of the SPMD sequence model.  Every rank must execute these in
#: an identical order; graftsync verifies the order statically and the
#: runtime tracer (dist.trace_collectives) verifies it live.
HOST_COLLECTIVES: Tuple[str, ...] = (
    "process_allgather", "vote_any", "process_concat", "sync_max_ints",
    "sync_config_by_min", "check_config_fingerprint",
)

#: the ONE module allowed to touch jax.experimental.multihost_utils /
#: jax.distributed directly (rule GC011): every blocking host
#: collective must funnel through its wrappers so it inherits the
#: call_with_deadline degrade-don't-hang wrapping and the runtime
#: trace.  A bare multihost call anywhere else is a finding.
COLLECTIVE_ENTRY_MODULE = "parallel/dist.py"

#: names that are rank-LOCAL no matter what: a branch/loop condition
#: touching one of these can never be rank-uniform.  Matches bare
#: names, parameters, and any attribute segment (`self.rank`,
#: `config.rank` included — a per-rank id stays per-rank wherever it
#: is stored).
RANK_VARYING_NAMES: Tuple[str, ...] = (
    "rank", "process_id", "process_index", "row_rank", "local_rows",
    "local_ips",
)

#: instance-attribute names the analyzer accepts as rank-uniform.
#: Each entry is a reviewed claim about how the attribute is computed;
#: adding one without the justification holding re-opens the silent
#: SPMD-hang class GC009/GC010 exist to close.
RANK_UNIFORM_ATTRS: Tuple[str, ...] = (
    # config-derived (fingerprint-checked by check_config_fingerprint)
    "num_machines", "num_shards", "period", "keep", "max_iteration",
    "resume", "snapshots", "config", "cfg", "params",
    # jax.process_count()-derived flags, identical on every process
    "_mh", "_mh_fused", "_feat_mh",
    # training counters/state that advance in lockstep on every rank
    # (resume agreement pins the starting point, segments advance
    # uniformly, every rank grows the identical model)
    "iter", "num_used_model", "_models", "_bank",
    # bagging-compaction state: the window is config-shaped and the
    # overflow/arranged flags are sync_max_ints-agreed across ranks
    # (gbdt._bag_window_overflow) before anyone acts on them
    "_bag_window", "_bag_overflowed", "_bag_arranged",
    "_fused_sharded",
)

#: external calls whose results are identical on every rank.
#: jax.process_index is deliberately ABSENT — it is the canonical
#: rank-local value.
RANK_UNIFORM_CALLS: Tuple[str, ...] = (
    "jax.process_count", "jax.device_count",
)

# ---------------------------------------------------------------------------
# Lock-order vocabulary (lockgraph, rule GC012)
# ---------------------------------------------------------------------------

#: package functions that BLOCK (device dispatch, model parse+warm,
#: file/socket-bound work): holding a serving hot-path lock across one
#: stalls every thread behind that lock for the operation's duration.
BLOCKING_FUNCTIONS: Tuple[str, ...] = (
    "serving/forest.py::load_forest",
    "serving/forest.py::ServingForest.warm",
    "serving/forest.py::ServingForest.predict",
    "serving/forest.py::ServingForest.predict_text",
    "serving/fleet.py::ModelFleet._load_fresh",
    "serving/batcher.py::MicroBatcher.submit",
)

#: attribute-call terminals treated as blocking operations (socket
#: I/O, subprocess waits, sleeps).  `.wait()` on the HELD condition
#: variable is exempt — releasing the lock while waiting is the whole
#: point of a CV.
BLOCKING_ATTR_CALLS: Tuple[str, ...] = (
    "accept", "recv", "recvfrom", "sendall", "connect", "communicate",
    "sleep", "wait",
)

#: locks ALLOWED to be held across blocking operations, with the
#: justification (rendered in --list-rules style docs).  Everything
#: else is a fast lock: fleet.py's loads-outside-pool-lock discipline,
#: machine-checked instead of comment-enforced.
LOCK_ALLOWED_BLOCKING: Mapping[str, str] = {
    "ModelFleet._load_lock":
        "exists to serialize cold model loads; the pool lock stays "
        "free so warm hits keep serving",
    "ServingState._swap_lock":
        "serializes /reload only and is never taken on the request "
        "path; the old forest keeps serving while the fresh one warms",
}

# ---------------------------------------------------------------------------
# Registries: the annotation SET is part of the contract
# ---------------------------------------------------------------------------

#: the six fused step makers (qualnames are "<module relpath>::<path>"
#: as analysis/callgraph.py renders them).  graftcheck verifies the
#: @contract.fused_body annotation set equals this registry exactly:
#: removing, renaming or adding a maker without updating the registry
#: is a finding (GC005).
EXPECTED_FUSED_BODIES: Tuple[str, ...] = (
    "models/gbdt.py::_make_fused_step",
    "models/gbdt.py::_make_fused_step_reorder",
    "models/gbdt.py::_make_fused_step_dart",
    "models/gbdt.py::_make_fused_step_multi",
    "models/gbdt.py::_make_fused_step_multi_sharded",
    "models/gbdt.py::_make_fused_step_sharded",
)

#: the bit-parity oracle paths (PARITY.md / CONTRACTS.md).  graftcheck
#: verifies the @contract.parity_oracle annotation set equals this
#: registry exactly (GC003).
EXPECTED_PARITY_ORACLES: Tuple[str, ...] = (
    # the general per-tree path: one grow dispatch per tree, the oracle
    # every fused path is structure/value-tested against
    "models/gbdt.py::GBDT._train_tree",
    # K=1 pass-through: iteration batching returns the body UNCHANGED,
    # so K>1 is bit-parity with the per-iteration oracle by construction
    "models/gbdt.py::_batch_iters",
    # the plain fused body: bag_compact=off / masked-bagging oracle
    "models/gbdt.py::_fused_step_body",
    # the growth kernel under full-length masked bagging
    "ops/grow.py::grow_tree",
    # the two-op split scan: hist_fused=off reads the materialized
    # [F, B, 3] histogram through this XLA pass — the bit-parity oracle
    # the fused Pallas histogram+gain kernel is tested against
    "ops/split.py::find_best_split",
)


def _tag(fn: F, name: str, args: Dict[str, Any]) -> F:
    """Attach contract metadata; never fail on exotic callables."""
    try:
        contracts = getattr(fn, CONTRACT_ATTR, None)
        if contracts is None:
            contracts = {}
            setattr(fn, CONTRACT_ATTR, contracts)
        contracts[name] = args
    except (AttributeError, TypeError):  # pragma: no cover - jit wrappers
        pass
    return fn


class _Contract:
    """The `contract` namespace — every member is a no-op tagger."""

    @staticmethod
    def traced_pure(fn: F) -> F:
        return _tag(fn, "traced_pure", {})

    @staticmethod
    def parity_oracle(note: str) -> Callable[[F], F]:
        def deco(fn: F) -> F:
            return _tag(fn, "parity_oracle", {"note": note})
        return deco

    @staticmethod
    def jax_free(fn: F) -> F:
        return _tag(fn, "jax_free", {})

    @staticmethod
    def locked_by(lock: str) -> Callable[[F], F]:
        def deco(fn: F) -> F:
            return _tag(fn, "locked_by", {"lock": lock})
        return deco

    @staticmethod
    def fused_body(extras: Tuple[str, ...] = (),
                   collectives: Tuple[str, ...] = ()
                   ) -> Callable[[F], F]:
        def deco(fn: F) -> F:
            return _tag(fn, "fused_body",
                        {"extras": tuple(extras),
                         "collectives": tuple(collectives)})
        return deco

    @staticmethod
    def counted_flush(fn: F) -> F:
        return _tag(fn, "counted_flush", {})

    @staticmethod
    def durable_write(fn: F) -> F:
        return _tag(fn, "durable_write", {})

    @staticmethod
    def rank_uniform(fn: F) -> F:
        return _tag(fn, "rank_uniform", {})


contract = _Contract()

__all__ = ["contract", "CONTRACT_ATTR", "JAX_FREE_MARKER", "DECLARE_DIRS",
           "FUSED_CORE", "CONSUME_KINDS", "COLLECTIVE_OPS",
           "EXPECTED_FUSED_BODIES", "EXPECTED_PARITY_ORACLES",
           "HOST_COLLECTIVES", "COLLECTIVE_ENTRY_MODULE",
           "RANK_VARYING_NAMES", "RANK_UNIFORM_ATTRS",
           "RANK_UNIFORM_CALLS", "BLOCKING_FUNCTIONS",
           "BLOCKING_ATTR_CALLS", "LOCK_ALLOWED_BLOCKING"]
