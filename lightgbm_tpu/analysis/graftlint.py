"""graftlint — the project-specific AST linter.

Each rule guards one invariant the test suite cannot see directly (the
code works today; the rule keeps the NEXT edit from silently breaking
the performance or parity story).  Pure stdlib: no jax import, so the
linter runs in any environment, including the jax-free fast-path CI
lanes it protects.

Rules (README.md "Static analysis & invariants" has the full table):

  GL001 host-sync-in-traced-fn     `.item()`, `float()/int()/bool()` on
        traced values, `np.asarray`/`np.array`, `jax.device_get/put`
        inside jit-traced functions — each is a silent host round-trip
        that serializes the device pipeline.
  GL002 jax-import-in-jax-free-module  module-level `import jax` (or a
        module-level import of a non-jax-free package module) in the
        contractually jax-free import paths (predict_fast, cli,
        io/parser, serving fallback, ...).
  GL003 float64-in-device-code     explicit float64 dtypes inside traced
        functions: x64 is off, so these either fail or silently demote
        — and under x64 they would fork the executable from the f32
        parity configuration.
  GL004 jit-missing-static         jit-wrapped functions whose
        configuration-like parameters (keyword-only, or str/bool/int
        annotated or defaulted) are not in static_argnames/nums: each
        distinct value would retrace instead of re-specializing.
  GL005 wallclock-or-rng-in-parity-path  `time.*` / `random` /
        `np.random` in parity-load-bearing modules — all randomness
        must come from utils/mt19937 (the reference's stream) and no
        value may depend on the clock.
  GL006 unlocked-serving-mutation  `self.*` attribute stores in
        serving/ outside __init__ and outside a `with <...lock/cv>`
        block (attribute heuristic; suppressions document the
        intentionally lock-free writes).
  GL007 global-jax-config-mutation jax.config.update of process-wide
        knobs (x64, platforms, ...) outside the process-owning entry
        points (cli.py, __main__.py): a library import must never
        reconfigure its host process.
  GL008 stdout-bypasses-logger     print()/sys.stdout outside
        utils/log.py and cli.py: training-log parity diffs against the
        reference depend on every line going through the logger.
  GL009 suppression-missing-justification  `# graftlint: disable=` with
        no (or a trivial) `-- why` justification.
  GL010 unused-suppression         a disable comment whose rule did not
        actually fire on that line — stale suppressions rot.
  GL011 static-bag-shape           a bag-count/bag-size name treated as
        a TRACED value: `int()`/`.item()` on one inside a traced
        function, or a bag-size parameter of a jitted signature missing
        from static_argnames.  Bag counts are deterministic (mt19937
        host draws; config.bag_compact ceil_pads them into static
        windows), so they are SHAPE inputs — tracing one would retrace
        the fused step at every re-bagging epoch.
  GL012 host-sync-in-scan-carry    `.item()` / `int()`/`float()`/
        `bool()` / `np.asarray` / `jax.device_get` on a scan carry or
        per-iteration value inside a lax.scan body — the iteration-
        batched training loop (config.iter_batch) exists to remove the
        per-iteration host round-trip, and a host sync inside the scan
        body is a tracer error at best and a silent K-fold serialization
        at worst.  Wins over GL001 inside scan bodies (GL011 still wins
        for bag counts).

Suppression syntax (GL009/GL010 verify it):

    expr  # graftlint: disable=GL003 -- f64 is the contract here: ...

The justification after `--` must be non-trivial (>= 20 chars).  A
suppression applies to findings anchored on its own line, or — when
the comment is on a line of its own — to the line directly below.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

__jax_free__ = True

RULES: Dict[str, str] = {
    "GL001": "host-sync-in-traced-fn",
    "GL002": "jax-import-in-jax-free-module",
    "GL003": "float64-in-device-code",
    "GL004": "jit-missing-static",
    "GL005": "wallclock-or-rng-in-parity-path",
    "GL006": "unlocked-serving-mutation",
    "GL007": "global-jax-config-mutation",
    "GL008": "stdout-bypasses-logger",
    "GL009": "suppression-missing-justification",
    "GL010": "unused-suppression",
    "GL011": "static-bag-shape",
    "GL012": "host-sync-in-scan-carry",
}

# id -> human name for EVERY rule family that renders through Finding;
# graftcheck registers its GC0xx whole-program rules here on import
RULE_NAMES: Dict[str, str] = dict(RULES)

# lax.scan-family transforms whose body argument is a scan body (GL012:
# host syncs there serialize every batched iteration, not just one)
_SCAN_NAMES = {
    "jax.lax.scan", "lax.scan",
    "jax.lax.associative_scan", "lax.associative_scan",
}

# Names that hold a bag count / compacted-window size (the static-bag-
# shape contract, GL011).  Deliberately does NOT match bag_mask/bag_masks
# — masks are genuine traced row data; it is the COUNTS that are shapes.
BAG_SIZE_RE = re.compile(
    r"(^|_)(bag|compact)_?(rows|cnt|count|size|window)($|_)",
    re.IGNORECASE)

# Rules about the suppression mechanism itself can never be suppressed.
UNSUPPRESSABLE = {"GL009", "GL010"}

# ---------------------------------------------------------------------------
# Module sets (paths relative to the package root, posix separators)
# ---------------------------------------------------------------------------

# Modules that must stay importable without jax anywhere in sys.modules
# (the native task=predict fast path, CLI arg-parse, IO, the serving
# fallback engine, this analysis package itself) DECLARE themselves with
# a module-level `__jax_free__ = True` marker — the set is DISCOVERED
# per run (_discover_jax_free), not hard-coded, so a new serving/io
# module cannot silently escape the gate (graftcheck GC007 additionally
# requires an explicit declaration under contracts.DECLARE_DIRS).  At
# module level a marked module may import jax/jaxlib neither directly
# nor transitively (via a package module outside the marked set);
# function-local imports are the sanctioned lazy pattern.
_JAX_FREE_MARKER = "__jax_free__"
# cheap pre-filter only — the authoritative check is the AST walk below
# (a column-0 example line inside a docstring must NOT count)
_MARKER_HINT_RE = re.compile(r"^__jax_free__", re.MULTILINE)


def _tree_declares_jax_free(tree: ast.Module) -> Optional[bool]:
    """The module's `__jax_free__` declaration from its AST (module
    level, if/try blocks included like any import-time statement —
    but NOT docstring text or function-local assignments)."""
    stack: List[ast.stmt] = list(tree.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.If):
            stack.extend(node.body)
            stack.extend(node.orelse)
        elif isinstance(node, ast.Try):
            stack.extend(node.body)
            stack.extend(node.orelse)
            stack.extend(node.finalbody)
            for h in node.handlers:
                stack.extend(h.body)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == _JAX_FREE_MARKER \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, bool):
                    return node.value.value
    return None


def _source_declares_jax_free(source: str) -> Optional[bool]:
    """The module's own `__jax_free__` declaration, if any.  AST-based
    (matching analysis/callgraph.py), with a regex pre-filter so the
    package-wide discovery scan stays cheap."""
    if _MARKER_HINT_RE.search(source) is None:
        return None
    try:
        return _tree_declares_jax_free(ast.parse(source))
    except SyntaxError:
        return None

# Modules whose output must be bit-reproducible against the reference
# binary: no wall clock, no RNG outside utils/mt19937.
PARITY_MODULES: Set[str] = {
    "objectives.py", "metrics.py", "predict_fast.py",
    "models/gbdt.py", "models/tree.py",
    "io/parser.py", "io/binning.py", "io/dataset.py",
    "native/__init__.py", "utils/mt19937.py",
    "parallel/mesh.py", "parallel/dist.py",
    # out-of-core ingest: shard bytes must equal the in-memory
    # loader's bins bit-for-bit (synth.py is OUT on purpose — it
    # generates random benchmark data, not parity artifacts)
    "ingest/manifest.py", "ingest/writer.py", "ingest/shards.py",
    # the fused histogram+gain kernel: already covered by the ops/
    # prefix rule, pinned HERE explicitly too — fused-on is bit-parity
    # with the two-op oracle, so clock/RNG reach would be model drift
    "ops/hist_pallas.py",
}
PARITY_PREFIXES = ("ops/",)

SERVING_PREFIX = "serving/"

# Process-owning entry points may mutate global jax config (GL007).
ENTRY_MODULES = {"cli.py", "__main__.py"}

# The logger's home (and the CLI's stderr error report) may write to
# stdio directly (GL008).
STDIO_EXEMPT = {"utils/log.py", "cli.py"}

# jax.config keys whose process-wide mutation GL007 flags.  The
# compilation-cache keys are deliberately absent: utils/compile_cache
# exists to set them, and they do not change numerics or tracing.
GLOBAL_JAX_KNOBS = {
    "jax_enable_x64", "jax_platforms", "jax_default_matmul_precision",
    "jax_disable_jit", "jax_numpy_dtype_promotion",
}

# Functions whose RETURNED closures are device code by project
# convention (objective gradient factories; the fused-step makers are
# caught structurally via jax.jit/shard_map dataflow).
TRACED_FACTORY_NAMES = re.compile(
    r"^(make_grad_fn|make_permute_fn|_fused_step\w*|fused_step\w*)$")

_JIT_NAMES = {"jax.jit", "jit", "jax.pjit", "pjit"}
_TRACE_TRANSFORMS = {
    "jax.lax.scan", "jax.lax.while_loop", "jax.lax.fori_loop",
    "jax.lax.map", "jax.lax.cond", "jax.lax.switch",
    "jax.lax.associative_scan", "lax.scan", "lax.while_loop",
    "lax.fori_loop", "lax.map", "lax.cond", "lax.switch",
    "jax.vmap", "vmap", "jax.grad", "jax.value_and_grad",
    "shard_map", "jax.experimental.shard_map.shard_map",
    "pl.pallas_call", "pallas_call", "jax.checkpoint", "jax.remat",
}
_HOST_SYNC_CALLS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "np.ascontiguousarray", "numpy.ascontiguousarray",
    "np.frombuffer", "numpy.frombuffer",
    "jax.device_get", "jax.device_put",
}
_SHAPEISH_ATTRS = {"shape", "ndim", "dtype", "size", "itemsize"}
_F64_ATTRS = {"jnp.float64", "np.float64", "numpy.float64",
              "jax.numpy.float64"}
_TIME_ATTRS = {"time", "perf_counter", "monotonic", "sleep",
               "process_time", "perf_counter_ns", "time_ns",
               "monotonic_ns"}

MIN_JUSTIFICATION_CHARS = 20

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Z0-9,\s]+?)\s*(?:--\s*(.*))?$")


@dataclasses.dataclass
class Finding:
    path: str          # path as given (package-relative for the package walk)
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return "%s:%d: %s [%s] %s" % (
            self.path, self.line, self.rule,
            RULE_NAMES.get(self.rule, "typing"), self.message)


@dataclasses.dataclass
class Suppression:
    line: int          # the line the comment sits on
    rules: Tuple[str, ...]
    justification: str
    own_line: bool     # comment-only line: applies to the line below
    # staleness is PER RULE: disable=GL003,GL006 where only GL003 fires
    # must still report the GL006 half as stale
    used_rules: Set[str] = dataclasses.field(default_factory=set)


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------

def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.lax.scan' for Attribute/Name chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _names_bag_size(node: ast.AST) -> bool:
    """Does this expression reference a bag-count/bag-size name (GL011)?"""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and BAG_SIZE_RE.search(sub.id):
            return True
        if isinstance(sub, ast.Attribute) \
                and BAG_SIZE_RE.search(sub.attr):
            return True
    return False


def _attach_parents(tree: ast.AST) -> None:
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            child._gl_parent = parent  # type: ignore[attr-defined]


def _enclosing_functions(node: ast.AST) -> Iterable[ast.AST]:
    cur = getattr(node, "_gl_parent", None)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            yield cur
        cur = getattr(cur, "_gl_parent", None)


def _all_params(fn: ast.AST) -> List[ast.arg]:
    a = fn.args
    return list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)


def _const_str_tuple(node: ast.AST) -> Tuple[str, ...]:
    """static_argnames value -> names (string or tuple/list of strings)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
        return tuple(out)
    return ()


def _const_int_tuple(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(el.value for el in node.elts
                     if isinstance(el, ast.Constant)
                     and isinstance(el.value, int))
    return ()


# ---------------------------------------------------------------------------
# Trace analysis: which functions run under jit?
# ---------------------------------------------------------------------------

class _TraceIndex:
    """Classifies every function in a module as traced / host.

    Traced roots:
      * defs decorated @jax.jit / @functools.partial(jax.jit, ...)
      * local defs passed (by name) to jax.jit(...) / shard_map /
        jax.lax.* / pallas_call — directly or through a local variable
      * closures RETURNED by a "factory": a local def whose call result
        flows into jax.jit/shard_map (the fused-step makers), or whose
        name matches TRACED_FACTORY_NAMES (objective grad factories)
    Propagation: every def nested inside a traced def is traced.
    """

    def __init__(self, tree: ast.AST):
        self.defs: List[ast.AST] = [
            n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        self.by_name: Dict[str, List[ast.AST]] = {}
        for d in self.defs:
            self.by_name.setdefault(d.name, []).append(d)
        self.traced: Set[ast.AST] = set()
        self.scan_bodies: Set[ast.AST] = set()
        self.statics: Dict[ast.AST, Set[str]] = {}
        self.jit_roots: List[Tuple[ast.AST, Set[str]]] = []
        self._factories: Set[ast.AST] = set()
        self._collect(tree)
        self._propagate()

    # -- collection ----------------------------------------------------
    def _jit_call_statics(self, call: ast.Call,
                          target: Optional[ast.AST]) -> Set[str]:
        names = set()
        nums: Tuple[int, ...] = ()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                names.update(_const_str_tuple(kw.value))
            elif kw.arg == "static_argnums":
                nums = _const_int_tuple(kw.value)
        if target is not None and nums:
            params = _all_params(target)
            for i in nums:
                if 0 <= i < len(params):
                    names.add(params[i].arg)
        return names

    def _mark_traced(self, fn: ast.AST, statics: Set[str],
                     jit_root: bool) -> None:
        self.traced.add(fn)
        self.statics.setdefault(fn, set()).update(statics)
        if jit_root:
            self.jit_roots.append((fn, statics))

    def _local_def_from_expr(self, node: ast.AST,
                             assigned: Dict[str, List[ast.AST]]
                             ) -> List[ast.AST]:
        """Local defs whose call result `node` evaluates to (handles
        f(...), name-assigned-from-f(...), and conditional expressions
        over those)."""
        if isinstance(node, ast.IfExp):
            return (self._local_def_from_expr(node.body, assigned)
                    + self._local_def_from_expr(node.orelse, assigned))
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name is None and isinstance(node.func, ast.Attribute):
                # self.make_grad_fn() style: resolve by method name
                return self.by_name.get(node.func.attr, [])
            if name is not None:
                base = name.split(".")[-1]
                return self.by_name.get(base, [])
        if isinstance(node, ast.Name):
            return assigned.get(node.id, [])
        return []

    def _collect(self, tree: ast.AST) -> None:
        # decorator-based roots
        for d in self.defs:
            for dec in d.decorator_list:
                if isinstance(dec, ast.Call):
                    name = _dotted(dec.func)
                    if name in _JIT_NAMES:
                        self._mark_traced(
                            d, self._jit_call_statics(dec, d), True)
                    elif name in ("functools.partial", "partial"):
                        if dec.args and _dotted(dec.args[0]) in _JIT_NAMES:
                            self._mark_traced(
                                d, self._jit_call_statics(dec, d), True)
                    elif name in _TRACE_TRANSFORMS:
                        self._mark_traced(d, set(), False)
                else:
                    if _dotted(dec) in _JIT_NAMES:
                        self._mark_traced(d, set(), True)

        # name -> local defs whose call result the name holds
        assigned: Dict[str, List[ast.AST]] = {}
        for n in ast.walk(tree):
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                defs = self._local_def_from_expr(n.value, {})
                if defs:
                    assigned[n.targets[0].id] = defs

        for n in ast.walk(tree):
            if not isinstance(n, ast.Call):
                continue
            name = _dotted(n.func)
            if name in _JIT_NAMES and n.args:
                arg0 = n.args[0]
                if isinstance(arg0, ast.Lambda):
                    self._mark_traced(arg0, set(), True)
                elif isinstance(arg0, ast.Name):
                    hit = False
                    for d in self.by_name.get(arg0.id, []):
                        self._mark_traced(
                            d, self._jit_call_statics(n, d), True)
                        hit = True
                    if not hit:
                        for d in self._local_def_from_expr(arg0, assigned):
                            self._factories.add(d)
                else:
                    for d in self._local_def_from_expr(arg0, assigned):
                        self._factories.add(d)
            elif name in _TRACE_TRANSFORMS:
                for arg in list(n.args) + [kw.value for kw in n.keywords]:
                    if isinstance(arg, ast.Lambda):
                        self._mark_traced(arg, set(), False)
                    elif isinstance(arg, ast.Name):
                        for d in self.by_name.get(arg.id, []):
                            self._mark_traced(d, set(), False)
                        for d in self._local_def_from_expr(arg, assigned):
                            self._factories.add(d)
                    elif isinstance(arg, ast.Call):
                        for d in self._local_def_from_expr(arg, assigned):
                            self._factories.add(d)
                if name in _SCAN_NAMES and n.args:
                    # the FIRST argument is the scan body: host syncs on
                    # its carry/xs serialize every batched iteration
                    # (GL012).  Resolve the name LEXICALLY — prefer defs
                    # in the scan call's own enclosing functions, then
                    # module level — so an unrelated same-named def
                    # elsewhere (`def body` is a common inner-fn name)
                    # is not misclassified as a scan body.
                    body = n.args[0]
                    if isinstance(body, ast.Lambda):
                        self.scan_bodies.add(body)
                    elif isinstance(body, ast.Name):
                        cands = self.by_name.get(body.id, [])
                        encl = set(_enclosing_functions(n))
                        scoped = [d for d in cands
                                  if getattr(d, "_gl_parent", None)
                                  in encl]
                        if not scoped:
                            scoped = [d for d in cands if isinstance(
                                getattr(d, "_gl_parent", None),
                                ast.Module)]
                        self.scan_bodies.update(scoped or cands)

        for d in self.defs:
            if TRACED_FACTORY_NAMES.match(d.name):
                self._factories.add(d)

        # factories: their returned local closures are traced
        for f in self._factories:
            inner_names = {d.name for d in self.defs
                           if getattr(d, "_gl_parent", None) is f
                           or self._nested_in(d, f)}
            for ret in ast.walk(f):
                if isinstance(ret, ast.Return) and ret.value is not None:
                    for t in self._returned_closures(ret.value, inner_names):
                        self._mark_traced(t, set(), False)

    def _returned_closures(self, node: ast.AST,
                           inner_names: Set[str]) -> List[ast.AST]:
        if isinstance(node, ast.IfExp):
            return (self._returned_closures(node.body, inner_names)
                    + self._returned_closures(node.orelse, inner_names))
        if isinstance(node, ast.Lambda):
            return [node]
        if isinstance(node, ast.Name) and node.id in inner_names:
            return self.by_name.get(node.id, [])
        return []

    @staticmethod
    def _nested_in(d: ast.AST, f: ast.AST) -> bool:
        cur = getattr(d, "_gl_parent", None)
        while cur is not None:
            if cur is f:
                return True
            cur = getattr(cur, "_gl_parent", None)
        return False

    def _propagate(self) -> None:
        changed = True
        while changed:
            changed = False
            for d in self.defs:
                for anc in _enclosing_functions(d):
                    if anc in self.traced and d not in self.traced:
                        self.traced.add(d)
                        changed = True
                    if anc in self.scan_bodies \
                            and d not in self.scan_bodies:
                        # nested helpers inside a scan body inherit its
                        # carry discipline (GL012)
                        self.scan_bodies.add(d)
                        changed = True

    def is_traced(self, node: ast.AST) -> bool:
        """Is this (non-def) node's innermost enclosing function traced?"""
        for fn in _enclosing_functions(node):
            return fn in self.traced
        return False

    def in_scan_body(self, node: ast.AST) -> bool:
        """Is this node's innermost enclosing function a lax.scan body
        (or nested inside one)?"""
        for fn in _enclosing_functions(node):
            return fn in self.scan_bodies
        return False

    def innermost(self, node: ast.AST) -> Optional[ast.AST]:
        for fn in _enclosing_functions(node):
            return fn
        return None


# ---------------------------------------------------------------------------
# Per-function taint: which names hold traced values?
# ---------------------------------------------------------------------------

def _expr_tainted(node: ast.AST, tainted: Set[str]) -> bool:
    """Does this expression reference a traced value other than through
    shape/ndim/dtype metadata or len()?"""
    if isinstance(node, ast.Attribute) and node.attr in _SHAPEISH_ATTRS:
        return False
    if isinstance(node, ast.Call):
        fname = _dotted(node.func)
        if fname == "len":
            return False
        # a call can launder taint through a function; stay conservative
        # only for direct name args
    if isinstance(node, ast.Name):
        return node.id in tainted
    for child in ast.iter_child_nodes(node):
        if _expr_tainted(child, tainted):
            return True
    return False


def _function_taint(fn: ast.AST, statics: Set[str]) -> Set[str]:
    tainted: Set[str] = set()
    if isinstance(fn, ast.Lambda):
        params = list(fn.args.posonlyargs) + list(fn.args.args) \
            + list(fn.args.kwonlyargs)
    else:
        params = _all_params(fn)
    for i, p in enumerate(params):
        if i == 0 and p.arg in ("self", "cls"):
            continue
        if p.arg in statics:
            continue
        tainted.add(p.arg)
    if isinstance(fn, ast.Lambda):
        return tainted
    for n in ast.walk(fn):
        if isinstance(n, ast.Assign) and _expr_tainted(n.value, tainted):
            for t in n.targets:
                for leaf in ast.walk(t):
                    if isinstance(leaf, ast.Name):
                        tainted.add(leaf.id)
    return tainted


# ---------------------------------------------------------------------------
# The linter
# ---------------------------------------------------------------------------

class ModuleLint:
    def __init__(self, relpath: str, source: str, display_path: str):
        self.rel = relpath.replace(os.sep, "/")
        self.display = display_path
        self.source = source
        self.findings: List[Finding] = []
        self.tree = ast.parse(source, filename=display_path)
        _attach_parents(self.tree)
        self.lines = source.splitlines()
        self.suppressions = self._parse_suppressions()

    # -- suppressions --------------------------------------------------
    def _parse_suppressions(self) -> List[Suppression]:
        """Real COMMENT tokens only (a suppression example inside a
        docstring must not count)."""
        import io
        import tokenize
        out = []
        try:
            tokens = list(tokenize.generate_tokens(
                io.StringIO(self.source).readline))
        except (tokenize.TokenError, IndentationError):
            tokens = []
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            i = tok.start[0]
            rules = tuple(r.strip() for r in m.group(1).split(",")
                          if r.strip())
            just = (m.group(2) or "").strip()
            own = self.lines[i - 1].lstrip().startswith("#")
            out.append(Suppression(i, rules, just, own))
        return out

    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        self.findings.append(
            Finding(self.display, getattr(node, "lineno", 1), rule,
                    message))

    # -- GL001 / GL003 / GL004 (trace-aware rules) ----------------------
    def check_traced(self) -> None:
        idx = _TraceIndex(self.tree)
        taint_cache: Dict[ast.AST, Set[str]] = {}

        def taint_for(fn: ast.AST) -> Set[str]:
            got = taint_cache.get(fn)
            if got is None:
                got = _function_taint(fn, idx.statics.get(fn, set()))
                taint_cache[fn] = got
            return got

        for n in ast.walk(self.tree):
            fn = idx.innermost(n)
            if fn is None or fn not in idx.traced:
                continue
            # inside a lax.scan body the host-sync rules sharpen to
            # GL012: the sync lands on a scan carry / per-iteration
            # value and serializes EVERY batched iteration (GL011's
            # bag-count classification still wins)
            sync_rule = "GL012" if idx.in_scan_body(n) else "GL001"
            if isinstance(n, ast.Call):
                name = _dotted(n.func)
                if isinstance(n.func, ast.Attribute) \
                        and n.func.attr == "item" and not n.args:
                    if _names_bag_size(n.func.value):
                        self._emit(n, "GL011",
                                   ".item() on a bag count inside a "
                                   "traced function: bag counts are "
                                   "STATIC shapes (host mt19937 draws, "
                                   "ceil_padded windows) — keep them "
                                   "Python ints outside the trace")
                    elif sync_rule == "GL012":
                        self._emit(n, "GL012",
                                   ".item() on a scan carry/per-"
                                   "iteration value inside a scanned "
                                   "training-loop body — host sync "
                                   "serializes every batched iteration")
                    else:
                        self._emit(n, "GL001",
                                   ".item() forces a device->host sync "
                                   "inside a traced function")
                elif name in _HOST_SYNC_CALLS:
                    if sync_rule == "GL012":
                        self._emit(n, "GL012",
                                   "%s inside a lax.scan body is a host "
                                   "sync on scan state — it would "
                                   "serialize every iteration of the "
                                   "batched training loop" % name)
                    else:
                        self._emit(n, "GL001",
                                   "%s inside a traced function is a "
                                   "host round-trip (use jnp / keep it "
                                   "outside the trace)" % name)
                elif name in ("float", "int", "bool") and len(n.args) == 1:
                    if _expr_tainted(n.args[0], taint_for(fn)):
                        if _names_bag_size(n.args[0]):
                            self._emit(n, "GL011",
                                       "%s() on a traced bag count: bag "
                                       "counts are STATIC shapes — "
                                       "compute them on the host and "
                                       "close over them (or pass via "
                                       "static_argnames)" % name)
                        elif sync_rule == "GL012":
                            self._emit(n, "GL012",
                                       "%s() on a scan carry/per-"
                                       "iteration value concretizes it "
                                       "inside the scanned training "
                                       "loop (tracer error / K-fold "
                                       "host sync)" % name)
                        else:
                            self._emit(n, "GL001",
                                       "%s() on a traced value "
                                       "concretizes it (host sync / "
                                       "tracer error)" % name)
            # float64 mentions in device code
            if isinstance(n, ast.Attribute) \
                    and _dotted(n) in _F64_ATTRS:
                self._emit(n, "GL003",
                           "explicit float64 in device code (x64 is "
                           "off; f32 is the parity configuration)")
            if isinstance(n, ast.Constant) and n.value == "float64":
                parent = getattr(n, "_gl_parent", None)
                if isinstance(parent, ast.keyword) \
                        and parent.arg == "dtype":
                    self._emit(n, "GL003",
                               'dtype="float64" in device code (x64 is '
                               "off; f32 is the parity configuration)")

        # GL004: configuration-like params must be static
        for fn, statics in idx.jit_roots:
            if isinstance(fn, ast.Lambda):
                continue
            params = _all_params(fn)
            kwonly = {p.arg for p in fn.args.kwonlyargs}
            defaults: Dict[str, ast.AST] = {}
            pos = list(fn.args.posonlyargs) + list(fn.args.args)
            for p, d in zip(pos[len(pos) - len(fn.args.defaults):],
                            fn.args.defaults):
                defaults[p.arg] = d
            for p, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults):
                if d is not None:
                    defaults[p.arg] = d
            for i, p in enumerate(params):
                if i == 0 and p.arg in ("self", "cls"):
                    continue
                if p.arg in statics:
                    continue
                if BAG_SIZE_RE.search(p.arg):
                    # the static-bag-shape contract: a bag-size argument
                    # reaching a jitted signature non-statically would
                    # retrace the executable at every re-bagging epoch
                    self._emit(
                        fn, "GL011",
                        "jit of %r: bag-size parameter %r is not in "
                        "static_argnames — the compacted window must be "
                        "a static shape (zero recompiles across "
                        "re-bagging boundaries)" % (fn.name, p.arg))
                    continue
                confy = p.arg in kwonly
                d = defaults.get(p.arg)
                if isinstance(d, ast.Constant) \
                        and isinstance(d.value, (str, bool)):
                    confy = True
                ann = getattr(p, "annotation", None)
                if isinstance(ann, ast.Name) \
                        and ann.id in ("str", "bool", "int"):
                    confy = True
                if confy:
                    self._emit(
                        fn, "GL004",
                        "jit of %r: parameter %r looks configuration-"
                        "like but is not in static_argnames — every "
                        "distinct value will retrace"
                        % (fn.name, p.arg))

    # -- GL002 ----------------------------------------------------------
    def _declares_jax_free(self) -> bool:
        """This module's own declaration wins; otherwise the discovered
        package-wide marker set (so lint_source() of an in-memory
        module at a real path sees the installed module's contract)."""
        own = _tree_declares_jax_free(self.tree)
        if own is not None:
            return own
        return self.rel in _JAX_FREE

    def check_jax_free(self) -> None:
        if not self._declares_jax_free():
            return
        pkg_dir = os.path.dirname(self.rel)  # "" for top-level modules
        pkg_name = os.path.basename(package_root())

        def resolve(level: int, module: Optional[str]) -> Optional[str]:
            """Import -> package-relative module path (or None for
            out-of-package imports).  Handles both the relative form
            (level > 0) and the absolute `lightgbm_tpu.x.y` form."""
            if level == 0:
                mod = module or ""
                if mod == pkg_name:
                    return ""
                if mod.startswith(pkg_name + "."):
                    return mod[len(pkg_name) + 1:].replace(".", "/")
                return None
            base = pkg_dir
            for _ in range(level - 1):
                base = os.path.dirname(base)
            mod = (module or "").replace(".", "/")
            return ("%s/%s" % (base, mod)).strip("/") if mod else base

        def target_ok(path: Optional[str], names: Sequence[str]) -> List[str]:
            """Non-jax-free package modules reached by this import."""
            bad = []
            if path is None:
                return bad
            candidates = []
            if names:
                for nm in names:
                    candidates.append("%s/%s" % (path, nm) if path
                                      else nm)
            mods = candidates + [path]
            for cand in mods:
                for suffix in (cand + ".py", cand + "/__init__.py"):
                    if suffix in _ALL_MODULES:
                        if suffix not in _JAX_FREE:
                            bad.append(suffix)
                        break
            return bad

        def module_level_stmts(
                body: Sequence[ast.stmt]) -> Iterable[ast.stmt]:
            """Module-level statements, descending into `if` blocks (a
            conditionally-guarded import still executes at import time)
            — except TYPE_CHECKING blocks, which never run."""
            for node in body:
                if isinstance(node, ast.If):
                    test = _dotted(node.test)
                    if test in ("TYPE_CHECKING", "typing.TYPE_CHECKING"):
                        # the guarded body never runs — but its ELSE
                        # branch runs in every real process
                        yield from module_level_stmts(node.orelse)
                        continue
                    yield from module_level_stmts(node.body)
                    yield from module_level_stmts(node.orelse)
                elif isinstance(node, ast.Try):
                    yield from module_level_stmts(node.body)
                    yield from module_level_stmts(node.orelse)
                    yield from module_level_stmts(node.finalbody)
                    for h in node.handlers:
                        yield from module_level_stmts(h.body)
                else:
                    yield node

        for node in module_level_stmts(self.tree.body):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    root = alias.name.split(".")[0]
                    if root in ("jax", "jaxlib"):
                        self._emit(node, "GL002",
                                   "module-level `import %s` in a "
                                   "contractually jax-free module"
                                   % alias.name)
                    else:
                        path = resolve(0, alias.name)
                        for bad in target_ok(path, []):
                            self._emit(node, "GL002",
                                       "module-level import of %s, "
                                       "which is not jax-free, from a "
                                       "jax-free module" % bad)
            elif isinstance(node, ast.ImportFrom):
                root = (node.module or "").split(".")[0]
                if node.level == 0 and root in ("jax", "jaxlib"):
                    self._emit(node, "GL002",
                               "module-level `from %s import ...` in a "
                               "contractually jax-free module"
                               % node.module)
                    continue
                path = resolve(node.level, node.module)
                for bad in target_ok(path,
                                     [a.name for a in node.names]):
                    self._emit(node, "GL002",
                               "module-level import of %s, which is "
                               "not jax-free, from a jax-free module"
                               % bad)

    # -- GL005 ----------------------------------------------------------
    def check_parity(self) -> None:
        if self.rel not in PARITY_MODULES \
                and not self.rel.startswith(PARITY_PREFIXES):
            return
        for n in ast.walk(self.tree):
            if isinstance(n, ast.Import):
                for alias in n.names:
                    if alias.name in ("time", "random"):
                        self._emit(n, "GL005",
                                   "`import %s` in a parity-load-"
                                   "bearing module (randomness must "
                                   "come from utils/mt19937; no value "
                                   "may depend on the clock)"
                                   % alias.name)
            elif isinstance(n, ast.ImportFrom):
                if node_mod := (n.module or ""):
                    if node_mod in ("time", "random") and n.level == 0:
                        self._emit(n, "GL005",
                                   "`from %s import ...` in a parity-"
                                   "load-bearing module" % node_mod)
            elif isinstance(n, ast.Attribute):
                name = _dotted(n)
                # match only the base `np.random` attribute node — the
                # inner node of every `np.random.X` chain — so one use
                # emits one finding
                if name in ("np.random", "numpy.random"):
                    self._emit(n, "GL005",
                               "np.random in a parity-load-bearing "
                               "module — use utils/mt19937 (the "
                               "reference's stream)")
                elif name in {"time." + a for a in _TIME_ATTRS}:
                    self._emit(n, "GL005",
                               "%s in a parity-load-bearing module — "
                               "no value may depend on the clock"
                               % name)

    # -- GL006 ----------------------------------------------------------
    def check_serving_locks(self) -> None:
        if not self.rel.startswith(SERVING_PREFIX):
            return

        def lockish(expr: ast.AST) -> bool:
            name = _dotted(expr) or ""
            low = name.lower()
            return "lock" in low or low.endswith("_cv") or "cv" == \
                low.rsplit(".", 1)[-1]

        def under_lock(node: ast.AST) -> bool:
            cur = getattr(node, "_gl_parent", None)
            while cur is not None:
                if isinstance(cur, ast.With):
                    for item in cur.items:
                        ctx = item.context_expr
                        if isinstance(ctx, ast.Call):
                            ctx = ctx.func
                        if lockish(ctx):
                            return True
                if isinstance(cur, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    return False
                cur = getattr(cur, "_gl_parent", None)
            return False

        def has_locked_by_contract(fn: ast.AST) -> bool:
            """@contract.locked_by("...") moves the proof obligation to
            graftcheck GC004: every call path into the function must
            hold the named lock, so per-line suppressions inside it are
            no longer needed (or wanted)."""
            for dec in getattr(fn, "decorator_list", []):
                target = dec.func if isinstance(dec, ast.Call) else dec
                dotted = _dotted(target) or ""
                if dotted.endswith("contract.locked_by"):
                    return True
            return False

        def self_attr_target(t: ast.AST) -> Optional[str]:
            """'a.b.c' when the store target is an attribute chain (or
            a subscript of one — `self.requests[k] = ...` mutates the
            shared dict exactly like a plain store) rooted at `self`,
            else None."""
            while isinstance(t, ast.Subscript):
                t = t.value
            if not isinstance(t, ast.Attribute):
                return None
            name = _dotted(t)
            if name and name.startswith("self."):
                return name
            return None

        for n in ast.walk(self.tree):
            fn = None
            for f in _enclosing_functions(n):
                fn = f
                break
            if fn is None or isinstance(fn, ast.Lambda):
                continue
            if fn.name in ("__init__", "__init_subclass__", "__new__"):
                continue
            if has_locked_by_contract(fn):
                continue
            targets: List[ast.AST] = []
            if isinstance(n, ast.Assign):
                targets = n.targets
            elif isinstance(n, (ast.AugAssign, ast.AnnAssign)):
                targets = [n.target]
            else:
                continue
            for t in targets:
                name = self_attr_target(t)
                if name is None:
                    continue
                if "lock" in name.lower() or name.lower().endswith("_cv"):
                    continue
                if not under_lock(n):
                    self._emit(n, "GL006",
                               "store to shared attribute %s outside a "
                               "`with <lock>` block in serving code "
                               "(document intentionally lock-free "
                               "writes with a suppression)" % name)

    # -- GL007 ----------------------------------------------------------
    def check_global_config(self) -> None:
        if self.rel in ENTRY_MODULES:
            return
        for n in ast.walk(self.tree):
            if not isinstance(n, ast.Call):
                continue
            if _dotted(n.func) != "jax.config.update":
                continue
            if n.args and isinstance(n.args[0], ast.Constant) \
                    and n.args[0].value in GLOBAL_JAX_KNOBS:
                self._emit(n, "GL007",
                           "jax.config.update(%r) outside the CLI "
                           "entry points: a library import must not "
                           "reconfigure its host process"
                           % n.args[0].value)

    # -- GL008 ----------------------------------------------------------
    def check_stdio(self) -> None:
        # the analysis package is developer tooling: its own report
        # printing is not part of the training-log surface
        if self.rel in STDIO_EXEMPT or self.rel.startswith("analysis/"):
            return
        for n in ast.walk(self.tree):
            if isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
                    and n.func.id == "print":
                self._emit(n, "GL008",
                           "print() bypasses utils/log — training-log "
                           "parity diffs depend on the logger "
                           "formatting every line")
            elif isinstance(n, ast.Attribute) \
                    and _dotted(n) in ("sys.stdout", "sys.stderr"):
                self._emit(n, "GL008",
                           "%s used directly; route output through "
                           "utils/log" % _dotted(n))

    # -- driver ----------------------------------------------------------
    def run(self) -> List[Finding]:
        self.check_traced()
        self.check_jax_free()
        self.check_parity()
        self.check_serving_locks()
        self.check_global_config()
        self.check_stdio()
        return self._apply_suppressions()

    def _next_code_line(self, after: int) -> Optional[int]:
        """1-based number of the first non-blank, non-comment line
        strictly after `after` (justifications may span several comment
        lines; the suppression binds to the code they precede)."""
        for i in range(after, len(self.lines)):
            stripped = self.lines[i].strip()
            if stripped and not stripped.startswith("#"):
                return i + 1
        return None

    def _decorator_def_lines(self) -> Dict[int, int]:
        """Line of each decorator -> line of the def/class it adorns: a
        suppression comment written ABOVE a decorator must still bind
        to the def (findings anchor on the def line, not the decorator
        line)."""
        out: Dict[int, int] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) and node.decorator_list:
                for dec in node.decorator_list:
                    out[dec.lineno] = node.lineno
        return out

    def _apply_suppressions(self) -> List[Finding]:
        dec_to_def = self._decorator_def_lines()
        by_line: Dict[int, List[Suppression]] = {}
        for s in self.suppressions:
            by_line.setdefault(s.line, []).append(s)
            if s.own_line:
                target = self._next_code_line(s.line)
                if target is not None:
                    by_line.setdefault(target, []).append(s)
                    # comment above a decorated def: the next code line
                    # is the decorator, but the finding sits on the def
                    def_line = dec_to_def.get(target)
                    if def_line is not None:
                        by_line.setdefault(def_line, []).append(s)
        kept: List[Finding] = []
        for f in self.findings:
            hit = None
            for s in by_line.get(f.line, []):
                if f.rule in s.rules and f.rule not in UNSUPPRESSABLE:
                    hit = s
                    break
            if hit is None:
                kept.append(f)
            else:
                hit.used_rules.add(f.rule)
        for s in self.suppressions:
            unknown = [r for r in s.rules if r not in RULES]
            for r in unknown:
                kept.append(Finding(self.display, s.line, "GL009",
                                    "suppression names unknown rule %r"
                                    % r))
            if len(s.justification) < MIN_JUSTIFICATION_CHARS:
                kept.append(Finding(
                    self.display, s.line, "GL009",
                    "suppression of %s carries no real justification "
                    "(want `-- <why this invariant is safe to waive "
                    "here>`, >= %d chars)"
                    % (",".join(s.rules), MIN_JUSTIFICATION_CHARS)))
            for r in s.rules:
                if r in RULES and r not in s.used_rules:
                    kept.append(Finding(
                        self.display, s.line, "GL010",
                        "suppression of %s did not match any finding "
                        "on its line — stale, remove it" % r))
        kept.sort(key=lambda f: (f.path, f.line, f.rule))
        return kept


# populated per run: every module path in the package (for GL002's
# transitive resolution) and the subset declaring __jax_free__ = True
_ALL_MODULES: Set[str] = set()
_JAX_FREE: Set[str] = set()

# memoized package index per root: lint_source() is called ~100 times
# per test run and must not re-read + re-parse the whole package each
# time.  run_graftlint() always refreshes (it reads the files anyway).
_INDEX_CACHE: Dict[str, Tuple[Set[str], Set[str]]] = {}


def _package_index(root: str) -> Tuple[Set[str], Set[str]]:
    got = _INDEX_CACHE.get(root)
    if got is None:
        mods = {os.path.relpath(p, root).replace(os.sep, "/")
                for p in iter_package_files(root)}
        got = (mods, _discover_jax_free(root))
        _INDEX_CACHE[root] = got
    return got


def _discover_jax_free(root: str) -> Set[str]:
    """Package-relative paths of every module declaring
    `__jax_free__ = True` under `root`."""
    out: Set[str] = set()
    for path in iter_package_files(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        if _source_declares_jax_free(src):
            out.add(rel)
    return out


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_package_files(root: str) -> List[str]:
    out = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                out.append(os.path.join(dirpath, fn))
    return sorted(out)


def run_graftlint(paths: Optional[Sequence[str]] = None,
                  root: Optional[str] = None) -> List[Finding]:
    """Lint package files; returns surviving findings (already
    suppression-filtered).  `paths` defaults to every .py in the
    package rooted at `root` (default: the installed lightgbm_tpu)."""
    root = root or package_root()
    files = list(paths) if paths else iter_package_files(root)
    global _ALL_MODULES, _JAX_FREE
    _ALL_MODULES = {
        os.path.relpath(p, root).replace(os.sep, "/")
        for p in iter_package_files(root)}
    _JAX_FREE = _discover_jax_free(root)
    _INDEX_CACHE[root] = (_ALL_MODULES, _JAX_FREE)  # refresh the memo
    findings: List[Finding] = []
    for path in files:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
        except OSError as ex:
            findings.append(Finding(path, 1, "GL009",
                                    "unreadable file: %s" % ex))
            continue
        display = os.path.relpath(path, os.getcwd()) \
            if os.path.isabs(path) else path
        try:
            lint = ModuleLint(rel, src, display)
        except SyntaxError as ex:
            findings.append(Finding(display, ex.lineno or 1, "GL009",
                                    "syntax error: %s" % ex.msg))
            continue
        findings.extend(lint.run())
    return findings


def lint_source(source: str, relpath: str) -> List[Finding]:
    """Lint one in-memory module as if it lived at `relpath` inside the
    package (test helper)."""
    global _ALL_MODULES, _JAX_FREE
    saved, saved_free = _ALL_MODULES, _JAX_FREE
    try:
        if not _ALL_MODULES:
            _ALL_MODULES, _JAX_FREE = _package_index(package_root())
        return ModuleLint(relpath, source, relpath).run()
    finally:
        _ALL_MODULES, _JAX_FREE = saved, saved_free
