"""Static analysis + runtime guards for the project's hot-path invariants.

The codebase carries several load-bearing invariants that no ordinary
test exercises directly — they hold by construction until someone edits
the wrong line, and then they regress silently:

  * the native `task=predict` fast path and the CLI arg-parse never
    import jax (predict_fast.py docstring; BASELINE.md measured the
    JAX startup tax at over half the 1M-row predict wall);
  * device code never host-syncs mid-trace and never touches float64
    (x64 is off during training; bit-parity with the reference is the
    whole point, PARITY.md);
  * the serving forest never recompiles in steady state (the
    power-of-two pre-compile contract, serving/forest.py);
  * serving shared state mutates only under its lock.

This package machine-checks them:

  graftlint.py  AST linter (`python -m lightgbm_tpu.analysis`), ~10
                project-specific rules with verified inline
                suppressions.  Pure stdlib — runs without jax.
  typegate.py   annotation-completeness gate for the mypy-strict
                modules (config.py, api.py, serving/) so the typing
                bar holds even on machines without mypy.
  guards.py     runtime counters: XLA compile + explicit-transfer
                accounting as a context manager and pytest fixture,
                so tests can assert "zero recompiles" budgets.

See README.md "Static analysis & invariants" for the rule table and
the suppression syntax.
"""

__all__ = ["run_graftlint", "run_typegate", "compile_budget",
           "track_compiles", "GuardViolation"]


def __getattr__(name):  # PEP 562: keep `import lightgbm_tpu.analysis` light
    if name in ("run_graftlint",):
        from .graftlint import run_graftlint
        return run_graftlint
    if name in ("run_typegate",):
        from .typegate import run_typegate
        return run_typegate
    if name in ("compile_budget", "track_compiles", "GuardViolation"):
        from . import guards
        return getattr(guards, name)
    raise AttributeError(name)
