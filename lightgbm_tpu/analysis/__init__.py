"""Static analysis + runtime guards for the project's hot-path invariants.

The codebase carries several load-bearing invariants that no ordinary
test exercises directly — they hold by construction until someone edits
the wrong line, and then they regress silently:

  * the native `task=predict` fast path and the CLI arg-parse never
    import jax (predict_fast.py docstring; BASELINE.md measured the
    JAX startup tax at over half the 1M-row predict wall);
  * device code never host-syncs mid-trace and never touches float64
    (x64 is off during training; bit-parity with the reference is the
    whole point, PARITY.md);
  * the serving forest never recompiles in steady state (the
    power-of-two pre-compile contract, serving/forest.py);
  * serving shared state mutates only under its lock.

This package machine-checks them:

  graftlint.py  AST linter (`python -m lightgbm_tpu.analysis`), ~12
                per-module rules with verified inline suppressions.
                Pure stdlib — runs without jax.
  contracts.py  the contract registry: invariants DECLARED at the
                definition site (@contract.traced_pure, .parity_oracle,
                .jax_free, .locked_by, .fused_body, .counted_flush and
                the `__jax_free__` module marker), zero-cost at runtime.
  callgraph.py  package-wide symbol table + call graph: module/import
                resolution, method binding, closures, factories.
  graftcheck.py whole-program contract analysis (rules GC001-GC008):
                taint/effect propagation ACROSS calls — a host sync
                three helpers below a traced entry point, a transitive
                jax import two hops below a jax-free module, a serving
                mutator reachable from an unlocked public method.
  graftsync.py  SPMD collective-safety analysis (rules GC009-GC011):
                host-collective SEQUENCES identical across ranks —
                rank-gated/reordered collectives, collective loops
                with rank-local trip counts, multihost calls outside
                parallel/dist.py.  The runtime side lives in
                parallel/dist.trace_collectives.
  lockgraph.py  lock-order analysis (rule GC012): acquisition cycles
                and blocking operations (cold loads, dispatch, socket
                I/O) under fast serving locks.
  mutations.py  seeded-violation corpus: deliberate contract breaks
                applied as source transforms to copies of the real
                modules, proving every rule catches its bug class
                (tests/test_graftcheck_mutations.py).
  typegate.py   annotation-completeness gate for the mypy-strict
                modules (config.py, api.py, serving/, analysis/) so
                the typing bar holds even on machines without mypy.
  guards.py     runtime counters: XLA compile + explicit-transfer
                accounting as a context manager and pytest fixture,
                so tests can assert "zero recompiles" budgets.

See README.md "Static analysis & invariants" for the rule table and
suppression syntax, and CONTRACTS.md for the contract registry.
"""

__jax_free__ = True

__all__ = ["run_graftlint", "run_graftcheck", "run_typegate", "contract",
           "compile_budget", "track_compiles", "GuardViolation"]


def __getattr__(name: str) -> object:
    # PEP 562: keep `import lightgbm_tpu.analysis` light
    if name == "run_graftlint":
        from .graftlint import run_graftlint
        return run_graftlint
    if name == "run_graftcheck":
        from .graftcheck import run_graftcheck
        return run_graftcheck
    if name == "contract":
        from .contracts import contract
        return contract
    if name == "run_typegate":
        from .typegate import run_typegate
        return run_typegate
    if name in ("compile_budget", "track_compiles", "GuardViolation"):
        from . import guards
        return getattr(guards, name)
    raise AttributeError(name)
