"""`python -m lightgbm_tpu.analysis` — run graftlint + the typing gate.

Exit codes (scripts/lint.sh and CI gate on these):
  0  clean
  1  findings (lint violations, bad/stale suppressions, typing gaps)
  2  usage / internal error

Options:
  --list-rules     print the rule table and exit
  --no-typegate    graftlint only
  --json           machine-readable findings (one object per line)
  [paths...]       specific files (default: the whole package)
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional

from .graftlint import RULES, Finding, run_graftlint
from .typegate import gated_modules, run_typegate


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    as_json = False
    typegate = True
    paths: List[str] = []
    for arg in argv:
        if arg == "--list-rules":
            for rid, name in sorted(RULES.items()):
                print("%s  %s" % (rid, name))
            print("TYPE   annotation-completeness on: %s"
                  % ", ".join(gated_modules()))
            return 0
        if arg == "--json":
            as_json = True
        elif arg == "--no-typegate":
            typegate = False
        elif arg.startswith("-"):
            print("unknown option %s" % arg, file=sys.stderr)
            return 2
        else:
            paths.append(arg)

    try:
        findings: List[Finding] = run_graftlint(paths or None)
        if typegate:
            if paths:
                # explicit paths scope the run but must not silently
                # waive the typing bar for gated modules among them
                import os

                from .graftlint import package_root
                root = package_root()
                gated = [p for p in paths
                         if os.path.relpath(
                             os.path.abspath(p), root).replace(
                                 os.sep, "/") in gated_modules(root)]
                if gated:
                    findings += run_typegate(gated)
            else:
                findings += run_typegate()
    except Exception as ex:  # internal error must not read as "clean"
        print("graftlint internal error: %s" % ex, file=sys.stderr)
        return 2

    if as_json:
        for f in findings:
            print(json.dumps(f.__dict__))
    else:
        for f in findings:
            print(f.render())
        n_lint = sum(1 for f in findings if f.rule in RULES)
        n_type = len(findings) - n_lint
        if findings:
            print("graftlint: %d finding(s) (%d lint, %d typing)"
                  % (len(findings), n_lint, n_type))
        else:
            print("graftlint: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
