"""`python -m lightgbm_tpu.analysis` — graftlint + graftcheck + typegate.

Exit codes (scripts/lint.sh, scripts/check.sh and CI gate on these):
  0  clean
  1  findings (lint violations, contract violations, bad/stale
     suppressions, typing gaps) not covered by the baseline
  2  usage / internal error

Options:
  --list-rules     print the rule table and exit
  --no-typegate    skip the typing gate
  --no-graftcheck  skip the whole-program contract analysis
  --no-graftsync   skip the SPMD collective-sequence + lock-order
                   rules (GC009-GC012) within the graftcheck pass
  --json           machine-readable findings (one object per line:
                   {"path", "line", "rule", "message"})
  --baseline FILE  suppress findings recorded in FILE (a JSON list of
                   {"path", "rule", "message"} objects — line numbers
                   deliberately ignored so unrelated edits don't
                   un-baseline old findings); only NEW findings fail
                   the run.  analysis/baseline.json is the checked-in
                   baseline scripts/lint.sh uses, kept EMPTY while the
                   tree is clean.
  [paths...]       specific files (graftlint/typegate scope to them;
                   graftcheck always analyzes the whole program — the
                   rules are interprocedural — and reports findings
                   for the given modules only)
"""

from __future__ import annotations

__jax_free__ = True

import json
import os
import sys
from typing import List, Optional, Set, Tuple

from .graftlint import RULES, Finding, package_root, run_graftlint
from .typegate import gated_modules, run_typegate


def _norm_path(path: str) -> str:
    """Finding path -> package-relative path for baseline matching.
    graftlint emits cwd-relative filesystem paths while graftcheck
    emits package-relative ones; normalizing both to the part after
    the last '<pkg>/' segment makes baseline entries independent of
    the cwd and install location."""
    p = path.replace(os.sep, "/")
    marker = os.path.basename(package_root()) + "/"
    idx = p.rfind(marker)
    if idx >= 0:
        return p[idx + len(marker):]
    return p


def _load_baseline(path: str) -> Set[Tuple[str, str, str]]:
    with open(path, "r", encoding="utf-8") as f:
        entries = json.load(f)
    if not isinstance(entries, list):
        raise ValueError("baseline must be a JSON list")
    out: Set[Tuple[str, str, str]] = set()
    for e in entries:
        out.add((_norm_path(str(e["path"])), str(e["rule"]),
                 str(e["message"])))
    return out


def _rel_to_package(path: str) -> str:
    """CLI path argument -> package-relative module path (for scoping
    graftcheck findings)."""
    root = package_root()
    return os.path.relpath(os.path.abspath(path), root).replace(
        os.sep, "/")


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    as_json = False
    typegate = True
    graftcheck = True
    graftsync = True
    baseline_path: Optional[str] = None
    paths: List[str] = []
    i = 0
    while i < len(argv):
        arg = argv[i]
        if arg == "--list-rules":
            from .graftcheck import CHECK_RULES
            for rid, name in sorted(RULES.items()):
                print("%s  %s" % (rid, name))
            for rid, name in sorted(CHECK_RULES.items()):
                print("%s  %s" % (rid, name))
            print("TYPE   annotation-completeness on: %s"
                  % ", ".join(gated_modules()))
            return 0
        if arg == "--json":
            as_json = True
        elif arg == "--no-typegate":
            typegate = False
        elif arg == "--no-graftcheck":
            graftcheck = False
        elif arg == "--no-graftsync":
            graftsync = False
        elif arg == "--baseline":
            if i + 1 >= len(argv):
                print("--baseline needs a file argument", file=sys.stderr)
                return 2
            i += 1
            baseline_path = argv[i]
        elif arg.startswith("-"):
            print("unknown option %s" % arg, file=sys.stderr)
            return 2
        else:
            paths.append(arg)
        i += 1

    try:
        baseline: Set[Tuple[str, str, str]] = set()
        if baseline_path is not None:
            baseline = _load_baseline(baseline_path)

        findings: List[Finding] = run_graftlint(paths or None)
        if graftcheck:
            from .graftcheck import run_graftcheck
            scope = ([_rel_to_package(p) for p in paths] if paths
                     else None)
            findings += run_graftcheck(paths=scope, graftsync=graftsync)
        if typegate:
            if paths:
                # explicit paths scope the run but must not silently
                # waive the typing bar for gated modules among them
                root = package_root()
                gated = [p for p in paths
                         if _rel_to_package(p) in gated_modules(root)]
                if gated:
                    findings += run_typegate(gated)
            else:
                findings += run_typegate()
        if baseline:
            findings = [f for f in findings
                        if (_norm_path(f.path), f.rule, f.message)
                        not in baseline]
    except Exception as ex:  # internal error must not read as "clean"
        print("graftlint internal error: %s" % ex, file=sys.stderr)
        return 2

    if as_json:
        for f in findings:
            print(json.dumps(f.__dict__))
    else:
        for f in findings:
            print(f.render())
        n_lint = sum(1 for f in findings
                     if f.rule in RULES or f.rule.startswith("GC"))
        n_type = len(findings) - n_lint
        if findings:
            print("graftlint: %d finding(s) (%d lint, %d typing)"
                  % (len(findings), n_lint, n_type))
        else:
            print("graftlint: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
