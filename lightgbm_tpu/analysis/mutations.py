"""Seeded-violation corpus: proof that every contract rule has power.

A static-analysis rule that has never caught a bug is a hypothesis, not
a safety net.  This module holds a corpus of DELIBERATE contract
violations — at least two per contract class — expressed as source
transforms applied to in-memory copies of the real package modules.
The harness (tests/test_graftcheck_mutations.py) asserts that

  * the UNMUTATED tree analyzes clean (no cry-wolf findings), and
  * every mutation is flagged by the expected rule, anchored on the
    expected module, with the expected evidence in the message (the
    interprocedural chain, the lock name, the drifted input kind, ...).

Transforms anchor on exact source strings and RAISE when the anchor has
drifted — a refactor that invalidates a seeded violation fails the
harness loudly instead of silently shrinking the proof corpus.

The transforms produce syntactically valid Python that would be WRONG
to run (that is the point); nothing here is ever imported or executed —
analysis is pure AST.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable, Dict, List, Tuple

from .graftlint import iter_package_files, package_root

__jax_free__ = True


def _replace_once(src: str, old: str, new: str, *, what: str) -> str:
    n = src.count(old)
    if n != 1:
        raise AssertionError(
            "mutation anchor drifted for %s: %d occurrence(s) of %r — "
            "update analysis/mutations.py alongside the refactor"
            % (what, n, old[:60]))
    return src.replace(old, new)


def _insert_after(src: str, anchor: str, addition: str, *,
                  what: str) -> str:
    return _replace_once(src, anchor, anchor + addition, what=what)


def _insert_before(src: str, anchor: str, addition: str, *,
                   what: str) -> str:
    return _replace_once(src, anchor, addition + anchor, what=what)


def _remove_decorator(src: str, prefix: str, *, what: str) -> str:
    """Remove the (possibly multi-line) decorator whose first line,
    stripped, starts with `prefix` — paren-balanced so the removal ends
    exactly where the decorator call does.  Exactly one match required."""
    lines = src.splitlines(keepends=True)
    spans = []
    i = 0
    while i < len(lines):
        if lines[i].lstrip().startswith(prefix):
            depth = 0
            j = i
            while j < len(lines):
                depth += lines[j].count("(") - lines[j].count(")")
                j += 1
                if depth <= 0:
                    break
            spans.append((i, j))
            i = j
        else:
            i += 1
    if len(spans) != 1:
        raise AssertionError(
            "mutation anchor drifted for %s: %d decorator match(es) "
            "for %r" % (what, len(spans), prefix))
    lo, hi = spans[0]
    return "".join(lines[:lo] + lines[hi:])


@dataclasses.dataclass
class Mutation:
    name: str
    contract: str          # contract class being violated
    module: str            # package-relative path the transform edits
    expect_rule: str       # rule that must flag it
    expect_path: str       # module the finding must anchor on
    expect_substr: str     # evidence that must appear in the message
    description: str
    transform: Callable[[str], str]


def _m(name: str, contract: str, module: str, expect_rule: str,
       expect_path: str, expect_substr: str, description: str,
       transform: Callable[[str], str]) -> Mutation:
    return Mutation(name, contract, module, expect_rule, expect_path,
                    expect_substr, description, transform)


# ---------------------------------------------------------------------------
# traced_pure — host syncs smuggled into the traced closure
# ---------------------------------------------------------------------------

def _t_asarray_in_grow_tree(src: str) -> str:
    return _insert_before(
        src, "    def psum(x):\n",
        "    grad = np.asarray(grad)  # seeded violation\n\n",
        what="np.asarray into grow_tree")


def _t_item_in_find_best_split(src: str) -> str:
    return _insert_after(
        src, "    dt = hist.dtype\n",
        "    _dbg = sum_g.item()  # seeded violation\n",
        what=".item() into find_best_split")


# ---------------------------------------------------------------------------
# jax_free — jax smuggled into the jax-free closure
# ---------------------------------------------------------------------------

def _t_jax_into_models_tree(src: str) -> str:
    return _insert_after(
        src, "import numpy as np\n",
        "import jax  # seeded violation\n",
        what="module-level jax into models/tree.py")


def _t_marker_off_batcher(src: str) -> str:
    return _replace_once(
        src, "\n__jax_free__ = True\n", "\n",
        what="__jax_free__ marker removal from serving/batcher.py")


def _t_jax_into_ingest_writer(src: str) -> str:
    return _insert_after(
        src, "import numpy as np\n",
        "import jax  # seeded violation\n",
        what="module-level jax into ingest/writer.py")


def _t_marker_off_ingest_shards(src: str) -> str:
    return _replace_once(
        src, "\n__jax_free__ = True\n", "\n",
        what="__jax_free__ marker removal from ingest/shards.py")


def _t_marker_off_dist(src: str) -> str:
    return _replace_once(
        src, "\n__jax_free__ = True\n", "\n",
        what="__jax_free__ marker removal from parallel/dist.py")


def _t_lazy_jax_in_get_lib(src: str) -> str:
    return _insert_after(
        src, "def get_lib() -> Optional[ctypes.CDLL]:\n",
        "    import jax  # seeded violation\n",
        what="lazy jax import into native.get_lib")


def _t_lazy_jax_in_compile_flat(src: str) -> str:
    return _insert_before(
        src, "    th, tl = split_hi_lo(thr)\n",
        "    import jax  # seeded violation\n",
        what="lazy jax import into flatforest.compile_flat")


# ---------------------------------------------------------------------------
# parity_oracle — oracle set drift + RNG/clock reach
# ---------------------------------------------------------------------------

def _t_remove_grow_oracle(src: str) -> str:
    return _remove_decorator(src, "@contract.parity_oracle(",
                             what="parity_oracle removal from grow_tree")


def _t_remove_split_oracle(src: str) -> str:
    # the SPLIT-path oracle (round 16): hist_fused=off is only an
    # oracle while find_best_split stays pinned in the registry
    return _remove_decorator(
        src, "@contract.parity_oracle(",
        what="parity_oracle removal from find_best_split")


def _t_np_random_in_pack_tree(src: str) -> str:
    return _insert_after(
        src, "def _pack_tree(dev_tree):\n",
        "    _noise = np.random.uniform()  # seeded violation\n",
        what="np.random into _pack_tree")


# ---------------------------------------------------------------------------
# locked_by — call paths that drop the lock
# ---------------------------------------------------------------------------

def _t_unlocked_poke_in_batcher(src: str) -> str:
    return _insert_before(
        src, "    def _loop(self) -> None:\n",
        "    def poke(self) -> None:  # seeded violation\n"
        "        self._take_batch()\n\n",
        what="unlocked public poke() into MicroBatcher")


def _t_unlocked_observe_in_server(src: str) -> str:
    return _insert_after(
        src, "    def request_started(self, endpoint: str) -> None:\n",
        "        self.latency.observe(0.0)  # seeded violation\n",
        what="unlocked observe() into Metrics.request_started")


def _t_unlocked_lane_observe(src: str) -> str:
    return _insert_after(
        src, "    def request_started(self, endpoint: str) -> None:\n",
        "        self._lane_observe(\"fast\", 0.0)  # seeded violation\n",
        what="unlocked _lane_observe() into Metrics.request_started")


# ---------------------------------------------------------------------------
# fused_body — registry drift + effect-signature drift
# ---------------------------------------------------------------------------

_PLAIN_STEP_DEF = (
    "    def step(scores, valid_scores, bag_mask, fmask, bins, "
    "valid_bins,\n             gstate, stopped):\n")


def _t_remove_fused_annotation(src: str) -> str:
    # the plain maker's decorator is the only one with no extras=(...)
    return _remove_decorator(
        src, '@contract.fused_body(collectives=',
        what="fused_body removal from _make_fused_step")


def _t_rename_body_param(src: str) -> str:
    return _replace_once(
        src, _PLAIN_STEP_DEF,
        _PLAIN_STEP_DEF.replace("fmask", "feature_mask"),
        what="fmask rename in the plain fused body")


def _t_collective_drift(src: str) -> str:
    return _insert_after(
        src, _PLAIN_STEP_DEF,
        "        scores = jax.lax.ppermute(scores, 'data', [(0, 0)])"
        "  # seeded violation\n",
        what="undeclared collective into the plain fused body")


# ---------------------------------------------------------------------------
# counted_flush — transfers that dodge the accounting
# ---------------------------------------------------------------------------

def _t_rogue_device_get(src: str) -> str:
    return _insert_before(
        src,
        "        # device row slices stay unmaterialized: _flush_pending "
        "stacks\n",
        "        _probe = jax.device_get(scores)  # seeded violation\n",
        what="rogue jax.device_get into _run_fused_multi")


def _t_host_sync_in_prefetch_handoff(src: str) -> str:
    # an end-of-load device_get barrier planted right after the shard
    # windows drain: it stalls the load on every in-flight transfer
    # (defeating the async device_put pipelining the prefetch feed
    # builds) and round-trips the whole bin matrix back to the host —
    # all outside the sanctioned flush accounting
    return _insert_after(
        src,
        "        pad = self.n_pad - ds.num_data\n",
        "        parts = [jax.device_get(p) for p in parts]"
        "  # seeded violation\n",
        what="host sync into the _put_bins_streamed prefetch handoff")


def _t_remove_counted_flush(src: str) -> str:
    return _replace_once(
        src, "    @contract.counted_flush\n", "",
        what="counted_flush removal from _flush_pending")


# ---------------------------------------------------------------------------
# durable_write — binary writes that dodge the atomic helper
# ---------------------------------------------------------------------------

def _t_bare_checkpoint_write(src: str) -> str:
    return _insert_after(
        src, "        write_npz(path, arrays)\n",
        "        with open(path + '.bak', 'wb') as f:"
        "  # seeded violation\n"
        "            np.savez(f, **arrays)\n",
        what="bare open('wb') checkpoint write into save_checkpoint")


def _t_bare_sidecar_savez(src: str) -> str:
    return _insert_before(
        src, "def _rank_cache_matches(",
        "def _mirror_sidecar(path, ds):  # seeded violation\n"
        "    np.savez(path + '.rows.bak.npz', rows=ds.local_rows)\n"
        "\n\n",
        what="bare np.savez sidecar mirror into io/dataset.py")


def _t_marker_off_refresh_agent(src: str) -> str:
    return _replace_once(
        src, "\n__jax_free__ = True\n", "\n",
        what="__jax_free__ marker removal from refresh/agent.py")


def _t_bare_state_write_in_agent(src: str) -> str:
    return _insert_before(
        src, "        atomic_write_bytes(self._state_path,",
        "        with open(self._state_path, 'wb') as f:"
        "  # seeded violation\n"
        "            f.write(json.dumps(doc).encode())\n",
        what="bare open('wb') state write into the refresh agent")


# ---------------------------------------------------------------------------
# spmd_collectives — rank-divergent collective sequences (graftsync)
# ---------------------------------------------------------------------------

def _t_rank_gated_vote(src: str) -> str:
    return _replace_once(
        src,
        "        from ..parallel.dist import vote_any\n"
        "        return vote_any(flag)\n",
        "        from ..parallel.dist import vote_any\n"
        "        if self.rank == 0:  # seeded violation\n"
        "            return vote_any(flag)\n"
        "        return flag\n",
        what="rank-gated vote_any into sync_flag")


_AGREE_GATHER = (
    "        from ..parallel.dist import process_allgather\n"
    "        alls = process_allgather(\n"
    "            np.array([iteration], dtype=np.int64)).reshape(-1)\n")


def _t_branch_reordered_allgather(src: str) -> str:
    return _replace_once(
        src, _AGREE_GATHER,
        "        from ..parallel.dist import process_allgather, vote_any\n"
        "        if self.rank % 2 == 0:  # seeded violation\n"
        "            vote_any(False)\n"
        "            alls = process_allgather(\n"
        "                np.array([iteration], dtype=np.int64)"
        ").reshape(-1)\n"
        "        else:\n"
        "            alls = process_allgather(\n"
        "                np.array([iteration], dtype=np.int64)"
        ").reshape(-1)\n"
        "            vote_any(False)\n",
        what="rank-reordered allgather arms into _agree")


def _t_collective_in_rank_loop(src: str) -> str:
    return _insert_before(
        src,
        "        alls = process_allgather(\n",
        "        for _ in range(self.rank):  # seeded violation\n"
        "            process_allgather(np.zeros(1, dtype=np.int64))\n",
        what="collective inside a rank-local loop in _agree")


def _t_direct_multihost_in_write(src: str) -> str:
    return _insert_after(
        src,
        '        faultpoint("checkpoint.write")\n',
        "        from jax.experimental import multihost_utils"
        "  # seeded violation\n"
        '        multihost_utils.sync_global_devices("snapshot")\n',
        what="direct multihost_utils call into SnapshotManager.write")


# ---------------------------------------------------------------------------
# lock_order — inverted acquisition / blocking under the pool lock
# ---------------------------------------------------------------------------

def _t_inverted_lock_order(src: str) -> str:
    return _replace_once(
        src,
        "        fresh = (loader or self._load_fresh)(path)\n"
        "        with self._lock:\n"
        "            self._registered[path] = True\n",
        "        with self._lock:  # seeded violation\n"
        "            with self._load_lock:\n"
        "                fresh = (loader or self._load_fresh)(path)\n"
        "        with self._lock:\n"
        "            self._registered[path] = True\n",
        what="inverted _lock/_load_lock nesting into ModelFleet.reload")


def _t_cold_load_under_pool_lock(src: str) -> str:
    return _replace_once(
        src,
        "            fresh = self._load_fresh(path)\n"
        "            with self._lock:\n"
        "                self._pool[path] = fresh\n",
        "            with self._lock:\n"
        "                fresh = self._load_fresh(path)"
        "  # seeded violation\n"
        "                self._pool[path] = fresh\n",
        what="cold load moved under the pool lock in ModelFleet._load")


# ---------------------------------------------------------------------------
# The corpus
# ---------------------------------------------------------------------------

MUTATIONS: Tuple[Mutation, ...] = (
    _m("host-sync-in-grow-tree", "traced_pure", "ops/grow.py",
       "GC001", "ops/grow.py", "np.asarray",
       "np.asarray on the gradient inside grow_tree — a host round-trip "
       "one call below every fused body",
       _t_asarray_in_grow_tree),
    _m("item-sync-in-find-best-split", "traced_pure", "ops/split.py",
       "GC001", "ops/split.py", ".item()",
       ".item() on a leaf total inside find_best_split — a host sync "
       "several calls below the traced entry points",
       _t_item_in_find_best_split),

    _m("jax-into-models-tree", "jax_free", "models/tree.py",
       "GC002", "serving/server.py",
       "serving/forest.py -> models/tree.py",
       "module-level `import jax` in models/tree.py — reaches "
       "serving/server.py two import hops up the jax-free tree",
       _t_jax_into_models_tree),
    _m("marker-removed-from-batcher", "jax_free", "serving/batcher.py",
       "GC007", "serving/batcher.py", "__jax_free__",
       "deleting the __jax_free__ declaration from a serving module — "
       "modules under DECLARE_DIRS cannot opt out silently",
       _t_marker_off_batcher),
    _m("lazy-jax-in-native-get-lib", "jax_free", "native/__init__.py",
       "GC002", "native/__init__.py", "lazy jax import",
       "a lazy `import jax` inside native.get_lib — reached from the "
       "@contract.jax_free fast-predict / serving fallback closures",
       _t_lazy_jax_in_get_lib),
    _m("lazy-jax-in-compile-flat", "jax_free", "serving/flatforest.py",
       "GC002", "serving/flatforest.py", "lazy jax import",
       "a lazy `import jax` inside the flat-table compiler — "
       "compile_flat runs in warm() on the low-latency lane of a "
       "backend=native process and is @contract.jax_free",
       _t_lazy_jax_in_compile_flat),

    _m("jax-into-ingest-writer", "jax_free", "ingest/writer.py",
       "GC002", "ingest/writer.py", "jax",
       "module-level `import jax` in the ingest bin-pass — the "
       "parse/shard-write path must stay importable (and fork-safe) "
       "in jax-free lanes: CLI task=ingest, parse worker processes",
       _t_jax_into_ingest_writer),
    _m("marker-removed-from-ingest-shards", "jax_free",
       "ingest/shards.py", "GC007", "ingest/shards.py",
       "pinned jax-free",
       "deleting the __jax_free__ declaration from a module PINNED by "
       "EXPECTED_JAX_FREE under the new ingest/ tree",
       _t_marker_off_ingest_shards),

    _m("pinned-marker-removed-from-dist", "jax_free",
       "parallel/dist.py", "GC007", "parallel/dist.py",
       "pinned jax-free",
       "deleting the marker from a module PINNED by EXPECTED_JAX_FREE "
       "— the registry, not just the directory rule, must flag it",
       _t_marker_off_dist),

    _m("oracle-annotation-removed", "parity_oracle", "ops/grow.py",
       "GC003", "ops/grow.py", "missing its @contract.parity_oracle",
       "removing grow_tree's parity_oracle annotation — the oracle SET "
       "is pinned by EXPECTED_PARITY_ORACLES",
       _t_remove_grow_oracle),
    _m("split-oracle-annotation-removed", "parity_oracle",
       "ops/split.py", "GC003", "ops/split.py",
       "missing its @contract.parity_oracle",
       "removing find_best_split's parity_oracle annotation — "
       "hist_fused=off is the fused kernel's bit-parity oracle only "
       "while the split path stays pinned",
       _t_remove_split_oracle),
    _m("np-random-in-pack-tree", "parity_oracle", "models/gbdt.py",
       "GC003", "models/gbdt.py", "np.random",
       "np.random inside _pack_tree — reachable from the general-path "
       "parity oracle (GBDT._train_tree)",
       _t_np_random_in_pack_tree),

    _m("unlocked-poke-into-batcher", "locked_by", "serving/batcher.py",
       "GC004", "serving/batcher.py", "without holding",
       "a public MicroBatcher method calling _take_batch without "
       "holding _cv",
       _t_unlocked_poke_in_batcher),
    _m("unlocked-observe-in-server", "locked_by", "serving/server.py",
       "GC004", "serving/server.py", "Metrics.request_started",
       "Metrics.request_started calling _Histogram.observe outside "
       "`with self._lock`",
       _t_unlocked_observe_in_server),
    _m("unlocked-lane-observe-in-server", "locked_by",
       "serving/server.py", "GC004", "serving/server.py",
       "_lane_observe",
       "Metrics.request_started calling the per-lane latency recorder "
       "outside `with self._lock` — the lane counters and histograms "
       "share the metrics lock",
       _t_unlocked_lane_observe),

    _m("fused-annotation-removed", "fused_body", "models/gbdt.py",
       "GC005", "models/gbdt.py", "missing its @contract.fused_body",
       "removing _make_fused_step's fused_body annotation — the maker "
       "SET is pinned by EXPECTED_FUSED_BODIES",
       _t_remove_fused_annotation),
    _m("body-param-renamed", "fused_body", "models/gbdt.py",
       "GC005", "models/gbdt.py", "does not consume the uniform core",
       "renaming the plain body's fmask parameter — effect-signature "
       "drift between the six bodies",
       _t_rename_body_param),
    _m("collective-drift-in-plain-body", "fused_body", "models/gbdt.py",
       "GC005", "models/gbdt.py", "ppermute",
       "an undeclared collective in ONE body — the uniform collective "
       "signature across the six bodies breaks",
       _t_collective_drift),

    _m("rogue-device-get", "counted_flush", "models/gbdt.py",
       "GC006", "models/gbdt.py", "GBDT._run_fused_multi",
       "a jax.device_get outside the counted flush — bench's "
       "device_gets_per_100_trees would silently under-count",
       _t_rogue_device_get),
    _m("counted-flush-annotation-removed", "counted_flush",
       "models/gbdt.py", "GC006", "models/gbdt.py",
       "GBDT._flush_pending",
       "removing the counted_flush annotation — the flush's own "
       "device_get immediately loses its sanction",
       _t_remove_counted_flush),
    _m("host-sync-in-prefetch-handoff", "counted_flush",
       "models/gbdt.py", "GC006", "models/gbdt.py",
       "GBDT._put_bins_streamed",
       "a jax.device_get barrier planted at the end of the shard-"
       "window prefetch handoff — it stalls the load on every "
       "in-flight transfer, round-trips the bin matrix to the host, "
       "and dodges the flush accounting",
       _t_host_sync_in_prefetch_handoff),

    _m("bare-checkpoint-write", "durable_write", "models/gbdt.py",
       "GC008", "models/gbdt.py", "open(.., 'wb')",
       "a bare open('wb') checkpoint copy next to the atomic write — "
       "a crash mid-write truncates it in place and poisons the next "
       "resume",
       _t_bare_checkpoint_write),
    _m("bare-sidecar-savez", "durable_write", "io/dataset.py",
       "GC008", "io/dataset.py", "np.savez",
       "a bare np.savez of the rows sidecar outside the atomic helper "
       "— a truncated sidecar desyncs the cluster's row partition",
       _t_bare_sidecar_savez),

    _m("marker-removed-from-refresh-agent", "jax_free",
       "refresh/agent.py", "GC007", "refresh/agent.py",
       "pinned jax-free",
       "deleting the __jax_free__ declaration from the deploy agent — "
       "bypassing the EXPECTED_JAX_FREE registry would let a jax "
       "import tax every refresh cycle with a backend init",
       _t_marker_off_refresh_agent),
    _m("bare-state-write-in-agent", "durable_write",
       "refresh/agent.py", "GC008", "refresh/agent.py",
       "open(.., 'wb')",
       "a bare open('wb') of the agent's durable state file — a crash "
       "mid-write truncates the consumed-drops ledger and the rerun "
       "double-trains or skips data",
       _t_bare_state_write_in_agent),

    _m("rank-gated-vote-any", "spmd_collectives",
       "resilience/snapshot.py", "GC009", "resilience/snapshot.py",
       "vote_any",
       "vote_any behind `if self.rank == 0` in sync_flag — rank 0 "
       "enters the collective alone and blocks until the deadline",
       _t_rank_gated_vote),
    _m("branch-reordered-allgather", "spmd_collectives",
       "resilience/snapshot.py", "GC009", "resilience/snapshot.py",
       "different collective sequences",
       "the SAME collective set in a different ORDER per rank parity "
       "— the sequence-sensitive check catches what a set comparison "
       "(GC005-style) cannot",
       _t_branch_reordered_allgather),
    _m("collective-in-rank-local-loop", "spmd_collectives",
       "resilience/snapshot.py", "GC010", "resilience/snapshot.py",
       "range(self.rank)",
       "an allgather inside `for _ in range(self.rank)` — every rank "
       "runs a different collective count and the pool wedges",
       _t_collective_in_rank_loop),
    _m("direct-multihost-in-snapshot", "spmd_collectives",
       "resilience/snapshot.py", "GC011", "resilience/snapshot.py",
       "multihost_utils",
       "a bare multihost_utils call in SnapshotManager.write — it "
       "bypasses dist.py, so no deadline wrapping and no trace",
       _t_direct_multihost_in_write),

    _m("inverted-lock-order-in-fleet", "lock_order",
       "serving/fleet.py", "GC012", "serving/fleet.py", "cycle",
       "reload nests _load_lock under _lock while _load nests _lock "
       "under _load_lock — a deadlock window between /reload and a "
       "cold-miss request",
       _t_inverted_lock_order),
    _m("cold-load-under-pool-lock", "lock_order",
       "serving/fleet.py", "GC012", "serving/fleet.py", "_load_fresh",
       "the cold parse+warm moved under the POOL lock — every warm "
       "hit stalls behind a multi-second model load (the discipline "
       "fleet.py's comments used to carry, now machine-checked)",
       _t_cold_load_under_pool_lock),
)


def base_sources(root: str = "") -> Dict[str, str]:
    """{package-relative path: source} for the real tree."""
    root = root or package_root()
    out: Dict[str, str] = {}
    for path in iter_package_files(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            out[rel] = f.read()
    return out


def apply_mutation(sources: Dict[str, str],
                   mutation: Mutation) -> Dict[str, str]:
    """A mutated copy of `sources`; raises if the anchor drifted or the
    transform was a no-op."""
    if mutation.module not in sources:
        raise AssertionError("mutation %s targets missing module %s"
                             % (mutation.name, mutation.module))
    mutated = dict(sources)
    new_src = mutation.transform(sources[mutation.module])
    if new_src == sources[mutation.module]:
        raise AssertionError("mutation %s was a no-op" % mutation.name)
    mutated[mutation.module] = new_src
    return mutated


def contract_classes() -> List[str]:
    return sorted({m.contract for m in MUTATIONS})
