"""Package-wide symbol table + call graph for graftcheck.

Pure-stdlib AST analysis over every module in the package (or any
in-memory {relpath: source} mapping — the seeded-violation harness
feeds mutated copies through the same entry point):

  * module resolution and import following, including re-exports
    through package `__init__` modules (both plain `from .x import y`
    re-exports and the PEP 562 `_EXPORTS` lazy dict the package root
    uses);
  * attribute/method binding for the classes the package actually has:
    methods through `self.meth(...)`, instance attributes whose class
    is inferable from `self.attr = ClassName(...)` assignments
    (`self.lat_hist.observe(...)` binds to `_Histogram.observe`),
    base-class methods through `super().meth(...)` and plain
    inheritance;
  * closure and factory resolution: a factory's returned local defs
    (`_fused_step_body` -> its `step`), `functools.partial(f, ...)`
    unwrapping, and local defs passed by name into higher-order calls
    (`jax.lax.scan(body, ...)`, `shard_map(body, ...)`) — those bodies
    are invoked by the transform, so they are call-graph edges;
  * decorator unwrapping: decorations never hide a def, and
    `@contract.*` decorations are parsed into a per-function contract
    table (analysis/contracts.py) the checking rules consume;
  * per-function EFFECT records (host syncs, collectives, RNG/clock
    reads, lazy jax imports, lock acquisitions) over the function's
    OWN statements — nested defs are their own nodes, reached through
    the closure;
  * the module-level import graph (TYPE_CHECKING blocks excluded) with
    per-module jax flags and `__jax_free__` declarations, for the
    transitive jax-reach rule.

Resolution is deliberately conservative: a call that cannot be bound
to a package function is simply not an edge (external library calls,
values passed in as parameters).  The seeded-violation harness
(analysis/mutations.py) proves the edges that matter exist.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from .contracts import COLLECTIVE_OPS, JAX_FREE_MARKER
from .graftlint import (_attach_parents, _dotted, iter_package_files,
                        package_root)

__jax_free__ = True

_TIME_ATTRS = {"time", "perf_counter", "monotonic", "sleep",
               "process_time", "perf_counter_ns", "time_ns",
               "monotonic_ns"}
_HOST_SYNC_CALLS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "np.ascontiguousarray", "numpy.ascontiguousarray",
    "np.frombuffer", "numpy.frombuffer",
    "jax.device_get", "jax.device_put",
}


# ---------------------------------------------------------------------------
# Data model
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Binding:
    """What a module-local name imported from elsewhere refers to."""
    kind: str                      # "module" | "symbol" | "external"
    module: str = ""               # package-relative path for module/symbol
    symbol: str = ""               # original name for kind == "symbol"
    external: str = ""             # root package name for kind == "external"


@dataclasses.dataclass
class Effects:
    """Observable effects of ONE function's own statements."""
    host_syncs: List[Tuple[int, str]] = dataclasses.field(
        default_factory=list)           # (line, what)
    collectives: Set[str] = dataclasses.field(default_factory=set)
    rng_clock: List[Tuple[int, str]] = dataclasses.field(
        default_factory=list)
    jax_imports: List[int] = dataclasses.field(default_factory=list)
    acquired_locks: Set[str] = dataclasses.field(default_factory=set)
    device_gets: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class FunctionInfo:
    qual: str                      # "models/gbdt.py::GBDT._train_tree"
    name: str
    module: "ModuleInfo"
    node: ast.AST                  # FunctionDef | AsyncFunctionDef
    cls: Optional["ClassInfo"]
    parent: Optional["FunctionInfo"]
    contracts: Dict[str, Dict[str, object]] = dataclasses.field(
        default_factory=dict)
    nested: List["FunctionInfo"] = dataclasses.field(default_factory=list)

    def __hash__(self) -> int:
        return id(self)

    def __eq__(self, other: object) -> bool:
        return self is other

    @property
    def is_public(self) -> bool:
        return not self.name.startswith("_")


@dataclasses.dataclass
class ClassInfo:
    name: str
    module: "ModuleInfo"
    node: ast.ClassDef
    base_names: List[str]
    methods: Dict[str, FunctionInfo] = dataclasses.field(
        default_factory=dict)
    attr_types: Dict[str, str] = dataclasses.field(default_factory=dict)
    bases: List["ClassInfo"] = dataclasses.field(default_factory=list)

    def __hash__(self) -> int:
        return id(self)

    def __eq__(self, other: object) -> bool:
        return self is other

    def find_method(self, name: str) -> Optional[FunctionInfo]:
        """MRO-ish lookup: own methods first, then package bases."""
        seen: Set[int] = set()
        queue: List[ClassInfo] = [self]
        while queue:
            c = queue.pop(0)
            if id(c) in seen:
                continue
            seen.add(id(c))
            m = c.methods.get(name)
            if m is not None:
                return m
            queue.extend(c.bases)
        return None

    def find_attr_type(self, attr: str) -> Optional[str]:
        seen: Set[int] = set()
        queue: List[ClassInfo] = [self]
        while queue:
            c = queue.pop(0)
            if id(c) in seen:
                continue
            seen.add(id(c))
            t = c.attr_types.get(attr)
            if t is not None:
                return t
            queue.extend(c.bases)
        return None


@dataclasses.dataclass
class ModuleInfo:
    rel: str
    tree: ast.Module
    functions: Dict[str, FunctionInfo] = dataclasses.field(
        default_factory=dict)      # top-level defs by name
    all_functions: List[FunctionInfo] = dataclasses.field(
        default_factory=list)
    classes: Dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    imports: Dict[str, Binding] = dataclasses.field(default_factory=dict)
    module_imports: Set[str] = dataclasses.field(default_factory=set)
    jax_module_level: bool = False
    jax_free: Optional[bool] = None
    exports: Dict[str, Tuple[str, str]] = dataclasses.field(
        default_factory=dict)      # name -> (module rel, original name)

    def __hash__(self) -> int:
        return id(self)

    def __eq__(self, other: object) -> bool:
        return self is other


@dataclasses.dataclass
class Edge:
    callee: FunctionInfo
    line: int
    call: Optional[ast.Call]


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------

def _module_level_stmts(body: Iterable[ast.stmt]) -> Iterator[ast.stmt]:
    """Module-level statements, descending into if/try blocks (those
    still execute at import time) but skipping TYPE_CHECKING guards."""
    for node in body:
        if isinstance(node, ast.If):
            test = _dotted(node.test)
            if test in ("TYPE_CHECKING", "typing.TYPE_CHECKING"):
                # the guarded body never runs — but its ELSE branch
                # runs in every real process
                yield from _module_level_stmts(node.orelse)
                continue
            yield from _module_level_stmts(node.body)
            yield from _module_level_stmts(node.orelse)
        elif isinstance(node, ast.Try):
            yield from _module_level_stmts(node.body)
            yield from _module_level_stmts(node.orelse)
            yield from _module_level_stmts(node.finalbody)
            for h in node.handlers:
                yield from _module_level_stmts(h.body)
        else:
            yield node


def own_nodes(fn_node: ast.AST) -> Iterator[ast.AST]:
    """Walk a def's body without descending into nested defs (those are
    their own FunctionInfos); lambdas stay inline."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _contract_of_decorator(dec: ast.AST) -> Optional[Tuple[str,
                                                           Dict[str, object]]]:
    """Parse one decorator expression into (contract name, args)."""
    call = dec if isinstance(dec, ast.Call) else None
    target = call.func if call is not None else dec
    dotted = _dotted(target)
    if not dotted:
        return None
    parts = dotted.split(".")
    if len(parts) < 2 or parts[-2] != "contract":
        return None
    name = parts[-1]
    args: Dict[str, object] = {}
    if call is not None:
        consts: List[object] = []
        for a in call.args:
            if isinstance(a, ast.Constant):
                consts.append(a.value)
        if name == "parity_oracle" and consts:
            args["note"] = consts[0]
        if name == "locked_by" and consts:
            args["lock"] = consts[0]
        for kw in call.keywords:
            if kw.arg is None:
                continue
            if isinstance(kw.value, (ast.Tuple, ast.List)):
                args[kw.arg] = tuple(
                    el.value for el in kw.value.elts
                    if isinstance(el, ast.Constant))
            elif isinstance(kw.value, ast.Constant):
                args[kw.arg] = kw.value.value
    return name, args


def _lockish_name(expr: ast.AST) -> Optional[str]:
    """Last component of a with-context expression that looks like a
    lock/condition ('self._lock' -> '_lock')."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    dotted = _dotted(expr)
    if not dotted:
        return None
    last = dotted.split(".")[-1]
    low = last.lower()
    if "lock" in low or low.endswith("_cv") or low == "cv":
        return last
    return None


# ---------------------------------------------------------------------------
# The graph
# ---------------------------------------------------------------------------

# (rel, source) -> parsed tree, shared across CallGraph instances: the
# seeded-violation harness analyzes ~15 package images that differ in
# ONE module each, so all unchanged modules parse once.  Safe to share
# because nothing mutates the trees beyond the idempotent parent links.
_PARSE_CACHE: Dict[Tuple[str, int], ast.Module] = {}
_PARSE_CACHE_MAX = 256


def _parse_cached(rel: str, source: str) -> ast.Module:
    key = (rel, hash(source))
    tree = _PARSE_CACHE.get(key)
    if tree is None:
        tree = ast.parse(source, filename=rel)
        _attach_parents(tree)
        if len(_PARSE_CACHE) >= _PARSE_CACHE_MAX:
            _PARSE_CACHE.clear()
        _PARSE_CACHE[key] = tree
    return tree


class CallGraph:
    def __init__(self, sources: Dict[str, str],
                 pkg_name: str = "lightgbm_tpu"):
        self.pkg_name = pkg_name
        self.modules: Dict[str, ModuleInfo] = {}
        self.errors: List[Tuple[str, str]] = []
        self._edge_cache: Dict[FunctionInfo, List[Edge]] = {}
        self._effect_cache: Dict[FunctionInfo, Effects] = {}
        for rel in sorted(sources):
            try:
                tree = _parse_cached(rel, sources[rel])
            except SyntaxError as ex:
                self.errors.append((rel, "syntax error: %s" % ex.msg))
                continue
            self.modules[rel] = ModuleInfo(rel=rel, tree=tree)
        for mod in self.modules.values():
            self._collect_module(mod)
        for mod in self.modules.values():
            self._resolve_bases(mod)

    # -- construction ---------------------------------------------------
    @classmethod
    def from_root(cls, root: Optional[str] = None) -> "CallGraph":
        root = root or package_root()
        sources: Dict[str, str] = {}
        for path in iter_package_files(root):
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            with open(path, "r", encoding="utf-8") as f:
                sources[rel] = f.read()
        return cls(sources, pkg_name=os.path.basename(root))

    def _resolve_import(self, mod: ModuleInfo, level: int,
                        module: Optional[str]) -> Optional[str]:
        """Import statement -> package-relative directory/module path
        prefix, or None for out-of-package imports."""
        if level == 0:
            name = module or ""
            if name == self.pkg_name:
                return ""
            if name.startswith(self.pkg_name + "."):
                return name[len(self.pkg_name) + 1:].replace(".", "/")
            return None
        base = os.path.dirname(mod.rel)
        for _ in range(level - 1):
            base = os.path.dirname(base)
        part = (module or "").replace(".", "/")
        return ("%s/%s" % (base, part)).strip("/") if part else base

    def _module_at(self, path: Optional[str]) -> Optional[str]:
        """Path prefix -> actual module rel ('io/binning' ->
        'io/binning.py'; 'io' -> 'io/__init__.py'; '' -> '__init__.py')."""
        if path is None:
            return None
        if path == "":
            return "__init__.py" if "__init__.py" in self.modules else None
        for cand in (path + ".py", path + "/__init__.py"):
            if cand in self.modules:
                return cand
        return None

    def _collect_module(self, mod: ModuleInfo) -> None:
        # defs/classes (every nesting level)
        self._collect_defs(mod, mod.tree, prefix="", cls=None, parent=None)

        # imports: whole-module bindings + module-level import graph
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    root_name = alias.name.split(".")[0]
                    local = alias.asname or alias.name.split(".")[0]
                    target = self._module_at(
                        self._resolve_import(mod, 0, alias.name))
                    if target is not None:
                        mod.imports[local] = Binding("module", module=target)
                    else:
                        mod.imports[local] = Binding("external",
                                                     external=root_name)
            elif isinstance(node, ast.ImportFrom):
                root_name = (node.module or "").split(".")[0]
                path = self._resolve_import(mod, node.level, node.module)
                if path is None:
                    for alias in node.names:
                        local = alias.asname or alias.name
                        mod.imports[local] = Binding("external",
                                                     external=root_name)
                    continue
                for alias in node.names:
                    local = alias.asname or alias.name
                    sub = self._module_at(
                        ("%s/%s" % (path, alias.name)).strip("/"))
                    if sub is not None:
                        mod.imports[local] = Binding("module", module=sub)
                    else:
                        src = self._module_at(path)
                        if src is not None:
                            mod.imports[local] = Binding(
                                "symbol", module=src, symbol=alias.name)

        # module-level import graph + jax flag
        for node in _module_level_stmts(mod.tree.body):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.split(".")[0] in ("jax", "jaxlib"):
                        mod.jax_module_level = True
                    t = self._module_at(
                        self._resolve_import(mod, 0, alias.name))
                    if t is not None:
                        mod.module_imports.add(t)
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0 \
                        and (node.module or "").split(".")[0] in (
                            "jax", "jaxlib"):
                    mod.jax_module_level = True
                path = self._resolve_import(mod, node.level, node.module)
                if path is not None:
                    for alias in node.names:
                        sub = self._module_at(
                            ("%s/%s" % (path, alias.name)).strip("/"))
                        if sub is not None:
                            mod.module_imports.add(sub)
                    # importing anything from a package executes the
                    # package module itself, so it is always an edge
                    src = self._module_at(path)
                    if src is not None:
                        mod.module_imports.add(src)
            elif isinstance(node, ast.Assign):
                # __jax_free__ marker; _EXPORTS lazy re-export dict
                for t in node.targets:
                    if isinstance(t, ast.Name) \
                            and t.id == JAX_FREE_MARKER \
                            and isinstance(node.value, ast.Constant) \
                            and isinstance(node.value.value, bool):
                        mod.jax_free = node.value.value
                    if isinstance(t, ast.Name) and t.id == "_EXPORTS" \
                            and isinstance(node.value, ast.Dict):
                        self._collect_exports_dict(mod, node.value)

        # plain re-exports: every from-import alias in an __init__
        # module is re-exported under its local name (covers both
        # module-level re-exports and the PEP 562 __getattr__ pattern)
        if os.path.basename(mod.rel) == "__init__.py":
            for name, b in mod.imports.items():
                if b.kind == "symbol":
                    mod.exports[name] = (b.module, b.symbol)
                elif b.kind == "module":
                    mod.exports[name] = (b.module, "")

    def _collect_exports_dict(self, mod: ModuleInfo,
                              node: ast.Dict) -> None:
        for k, v in zip(node.keys, node.values):
            if not (isinstance(k, ast.Constant) and isinstance(
                    k.value, str) and isinstance(v, ast.Constant)
                    and isinstance(v.value, str)):
                continue
            dotted = v.value  # ".models.gbdt" relative to this package
            level = 0
            while dotted.startswith("."):
                level += 1
                dotted = dotted[1:]
            target = self._module_at(
                self._resolve_import(mod, max(level, 1), dotted or None))
            if target is not None:
                mod.exports[k.value] = (target, k.value)

    def _collect_defs(self, mod: ModuleInfo, tree: ast.AST, prefix: str,
                      cls: Optional[ClassInfo],
                      parent: Optional[FunctionInfo]) -> None:
        for node in ast.iter_child_nodes(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = "%s::%s%s" % (mod.rel, prefix, node.name)
                contracts: Dict[str, Dict[str, object]] = {}
                for dec in node.decorator_list:
                    parsed = _contract_of_decorator(dec)
                    if parsed is not None:
                        contracts[parsed[0]] = parsed[1]
                fi = FunctionInfo(qual=qual, name=node.name, module=mod,
                                  node=node, cls=cls, parent=parent,
                                  contracts=contracts)
                mod.all_functions.append(fi)
                if parent is not None:
                    parent.nested.append(fi)
                elif cls is not None:
                    cls.methods[node.name] = fi
                else:
                    mod.functions[node.name] = fi
                self._collect_defs(mod, node, prefix + node.name + ".",
                                   cls=cls, parent=fi)
            elif isinstance(node, ast.ClassDef):
                ci = ClassInfo(name=node.name, module=mod, node=node,
                               base_names=[
                                   d for d in (_dotted(b)
                                               for b in node.bases)
                                   if d is not None])
                mod.classes[node.name] = ci
                self._collect_defs(mod, node,
                                   prefix + node.name + ".",
                                   cls=ci, parent=None)
                self._collect_attr_types(ci)
            else:
                # defs inside module-level if/try blocks still exist
                if isinstance(node, (ast.If, ast.Try, ast.With)):
                    self._collect_defs(mod, node, prefix, cls, parent)

    def _collect_attr_types(self, ci: ClassInfo) -> None:
        """`self.attr = ClassName(...)` anywhere in the class body."""
        for node in ast.walk(ci.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            v = node.value
            if isinstance(v, ast.Call):
                name = _dotted(v.func)
                if name is not None:
                    ci.attr_types.setdefault(t.attr, name)

    def _resolve_bases(self, mod: ModuleInfo) -> None:
        for ci in mod.classes.values():
            for base in ci.base_names:
                resolved = self.resolve_class(mod, base)
                if resolved is not None:
                    ci.bases.append(resolved)

    # -- symbol resolution ---------------------------------------------
    def resolve_class(self, mod: ModuleInfo,
                      dotted: str) -> Optional[ClassInfo]:
        parts = dotted.split(".")
        if len(parts) == 1:
            ci = mod.classes.get(parts[0])
            if ci is not None:
                return ci
            b = mod.imports.get(parts[0])
            if b is not None and b.kind == "symbol":
                return self._class_in(b.module, b.symbol)
            return None
        b = mod.imports.get(parts[0])
        if b is not None and b.kind == "module" and len(parts) == 2:
            return self._class_in(b.module, parts[1])
        return None

    def _class_in(self, module_rel: str,
                  name: str) -> Optional[ClassInfo]:
        m = self.modules.get(module_rel)
        if m is None:
            return None
        ci = m.classes.get(name)
        if ci is not None:
            return ci
        exp = m.exports.get(name)
        if exp is not None and exp[1]:
            return self._class_in(exp[0], exp[1])
        return None

    def _function_in(self, module_rel: str,
                     name: str) -> Optional[FunctionInfo]:
        m = self.modules.get(module_rel)
        if m is None:
            return None
        fi = m.functions.get(name)
        if fi is not None:
            return fi
        ci = m.classes.get(name)
        if ci is not None:
            init = ci.find_method("__init__")
            if init is not None:
                return init
        exp = m.exports.get(name)
        if exp is not None and exp[1]:
            return self._function_in(exp[0], exp[1])
        return None

    def function(self, qual: str) -> Optional[FunctionInfo]:
        rel = qual.partition("::")[0]
        m = self.modules.get(rel)
        if m is None:
            return None
        for fi in m.all_functions:
            if fi.qual == qual:
                return fi
        return None

    def contracted(self, name: str) -> List[FunctionInfo]:
        """Every function in the graph carrying the named contract."""
        out = []
        for m in self.modules.values():
            for fi in m.all_functions:
                if name in fi.contracts:
                    out.append(fi)
        return out

    def _resolve_name(self, fn: FunctionInfo,
                      name: str) -> List[FunctionInfo]:
        """A bare name used inside `fn` -> function(s) it denotes."""
        # lexical: nested defs of enclosing functions, innermost first
        cur: Optional[FunctionInfo] = fn
        while cur is not None:
            for nested in cur.nested:
                if nested.name == name:
                    return [nested]
            cur = cur.parent
        mod = fn.module
        if name in mod.functions:
            return [mod.functions[name]]
        ci = mod.classes.get(name)
        if ci is not None:
            init = ci.find_method("__init__")
            return [init] if init is not None else []
        b = mod.imports.get(name)
        if b is not None:
            if b.kind == "symbol":
                hit = self._function_in(b.module, b.symbol)
                return [hit] if hit is not None else []
            return []
        return []

    def _resolve_callee_expr(self, fn: FunctionInfo,
                             expr: ast.AST) -> List[FunctionInfo]:
        """Function(s) the expression `expr` denotes at a call site."""
        if isinstance(expr, ast.Name):
            return self._resolve_name(fn, expr.id)
        if isinstance(expr, ast.IfExp):
            return (self._resolve_callee_expr(fn, expr.body)
                    + self._resolve_callee_expr(fn, expr.orelse))
        if isinstance(expr, ast.Call):
            # calling the RESULT of a call: factory().  Resolve the
            # factory, then its returned closures.
            inner = _dotted(expr.func)
            if inner in ("functools.partial", "partial") and expr.args:
                return self._resolve_callee_expr(fn, expr.args[0])
            out: List[FunctionInfo] = []
            for factory in self._resolve_callee_expr(fn, expr.func):
                out.extend(self.returned_closures(factory))
            return out
        if isinstance(expr, ast.Attribute):
            # super().meth
            if isinstance(expr.value, ast.Call) \
                    and isinstance(expr.value.func, ast.Name) \
                    and expr.value.func.id == "super":
                if fn.cls is not None:
                    for base in fn.cls.bases:
                        m = base.find_method(expr.attr)
                        if m is not None:
                            return [m]
                return []
            dotted = _dotted(expr)
            if dotted is None:
                return []
            parts = dotted.split(".")
            if parts[0] == "self" and fn.cls is not None:
                if len(parts) == 2:
                    m = fn.cls.find_method(parts[1])
                    return [m] if m is not None else []
                if len(parts) == 3:
                    t = fn.cls.find_attr_type(parts[1])
                    if t is not None:
                        ci = self.resolve_class(fn.module, t)
                        if ci is not None:
                            m = ci.find_method(parts[2])
                            return [m] if m is not None else []
                return []
            b = fn.module.imports.get(parts[0])
            if b is not None and b.kind == "module" and len(parts) == 2:
                hit = self._function_in(b.module, parts[1])
                return [hit] if hit is not None else []
            if len(parts) == 2:
                ci = fn.module.classes.get(parts[0])
                if ci is None and b is not None and b.kind == "symbol":
                    ci = self._class_in(b.module, b.symbol)
                if ci is not None:
                    m = ci.find_method(parts[1])
                    return [m] if m is not None else []
            return []
        return []

    def returned_closures(self, fn: FunctionInfo) -> List[FunctionInfo]:
        """Local defs a factory returns — directly (`return step`),
        through a wrapper call (`return jax.jit(body)`), or behind a
        conditional expression."""
        out: List[FunctionInfo] = []

        def from_expr(node: ast.AST) -> None:
            if isinstance(node, ast.Name):
                for nested in fn.nested:
                    if nested.name == node.id:
                        out.append(nested)
            elif isinstance(node, ast.IfExp):
                from_expr(node.body)
                from_expr(node.orelse)
            elif isinstance(node, ast.Call):
                for a in node.args:
                    from_expr(a)

        for node in own_nodes(fn.node):
            if isinstance(node, ast.Return) and node.value is not None:
                from_expr(node.value)
        return out

    # -- edges ----------------------------------------------------------
    def callees(self, fn: FunctionInfo) -> List[Edge]:
        cached = self._edge_cache.get(fn)
        if cached is not None:
            return cached
        edges: List[Edge] = []
        seen: Set[Tuple[int, int]] = set()

        def add(target: FunctionInfo, node: ast.AST,
                call: Optional[ast.Call]) -> None:
            key = (id(target), getattr(node, "lineno", 0))
            if key in seen:
                return
            seen.add(key)
            edges.append(Edge(callee=target,
                              line=getattr(node, "lineno", 0), call=call))

        for node in own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            for target in self._resolve_callee_expr(fn, node.func):
                add(target, node, node)
            # local defs passed by name into a higher-order call are
            # invoked by it (lax.scan/cond bodies, shard_map, jit, ...);
            # functools.partial(f, ...) arguments unwrap to f
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, (ast.Name, ast.Attribute)):
                    for target in self._resolve_callee_expr(fn, arg):
                        add(target, node, node)
                elif isinstance(arg, ast.Call):
                    inner = _dotted(arg.func)
                    if inner in ("functools.partial", "partial") \
                            and arg.args:
                        for target in self._resolve_callee_expr(
                                fn, arg.args[0]):
                            add(target, node, node)
        self._edge_cache[fn] = edges
        return edges

    # -- reach ----------------------------------------------------------
    def reach(self, roots: Iterable[FunctionInfo]
              ) -> Dict[FunctionInfo, Optional[FunctionInfo]]:
        """BFS closure over call edges + nested defs + returned
        closures; maps each reached function to its BFS parent (None
        for roots) so rules can render the call chain."""
        parent: Dict[FunctionInfo, Optional[FunctionInfo]] = {}
        queue: List[FunctionInfo] = []
        for r in roots:
            if r not in parent:
                parent[r] = None
                queue.append(r)
        while queue:
            fn = queue.pop(0)
            succ: List[FunctionInfo] = [e.callee for e in self.callees(fn)]
            succ.extend(fn.nested)
            succ.extend(self.returned_closures(fn))
            for s in succ:
                if s not in parent:
                    parent[s] = fn
                    queue.append(s)
        return parent

    @staticmethod
    def chain(parent: Dict[FunctionInfo, Optional[FunctionInfo]],
              fn: FunctionInfo) -> List[FunctionInfo]:
        out = [fn]
        cur = parent.get(fn)
        while cur is not None:
            out.append(cur)
            cur = parent.get(cur)
        out.reverse()
        return out

    # -- effects --------------------------------------------------------
    def effects(self, fn: FunctionInfo) -> Effects:
        cached = self._effect_cache.get(fn)
        if cached is not None:
            return cached
        eff = Effects()
        for node in own_nodes(fn.node):
            line = getattr(node, "lineno", 0)
            if isinstance(node, ast.Call):
                dotted = _dotted(node.func)
                if dotted in _HOST_SYNC_CALLS:
                    eff.host_syncs.append((line, dotted or ""))
                    if dotted == "jax.device_get":
                        eff.device_gets.append(line)
                elif isinstance(node.func, ast.Attribute) \
                        and not node.args and not node.keywords:
                    if node.func.attr == "item":
                        eff.host_syncs.append((line, ".item()"))
                    elif node.func.attr == "block_until_ready":
                        eff.host_syncs.append((line,
                                               ".block_until_ready()"))
                if dotted is not None:
                    parts = dotted.split(".")
                    if len(parts) >= 2 and parts[-2] == "lax" \
                            and parts[-1] in COLLECTIVE_OPS:
                        eff.collectives.add(parts[-1])
            elif isinstance(node, ast.Attribute):
                dotted = _dotted(node)
                if dotted in ("np.random", "numpy.random"):
                    eff.rng_clock.append((line, dotted or ""))
                elif dotted is not None and "." in dotted:
                    head, _, attr = dotted.rpartition(".")
                    if head == "time" and attr in _TIME_ATTRS:
                        eff.rng_clock.append((line, dotted))
                    elif head == "random":
                        eff.rng_clock.append((line, dotted))
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    root_name = alias.name.split(".")[0]
                    if root_name in ("jax", "jaxlib"):
                        eff.jax_imports.append(line)
                    if root_name in ("time", "random"):
                        eff.rng_clock.append((line,
                                              "import %s" % alias.name))
            elif isinstance(node, ast.ImportFrom):
                root_name = (node.module or "").split(".")[0]
                if node.level == 0 and root_name in ("jax", "jaxlib"):
                    eff.jax_imports.append(line)
                if node.level == 0 and root_name in ("time", "random"):
                    eff.rng_clock.append((line,
                                          "from %s import ..." % root_name))
            elif isinstance(node, ast.With):
                for item in node.items:
                    lock = _lockish_name(item.context_expr)
                    if lock is not None:
                        eff.acquired_locks.add(lock)
        self._effect_cache[fn] = eff
        return eff

    # -- module import closure -----------------------------------------
    def jax_reach_chain(self, rel: str) -> Optional[List[str]]:
        """Shortest module-import chain from `rel` to a module that
        imports jax at module level (None when unreachable).  The chain
        includes `rel` and ends at the jax-importing module."""
        start = self.modules.get(rel)
        if start is None:
            return None
        if start.jax_module_level:
            return [rel]
        parent: Dict[str, Optional[str]] = {rel: None}
        queue = [rel]
        while queue:
            cur = queue.pop(0)
            m = self.modules.get(cur)
            if m is None:
                continue
            for nxt in sorted(m.module_imports):
                if nxt in parent:
                    continue
                parent[nxt] = cur
                nm = self.modules.get(nxt)
                if nm is not None and nm.jax_module_level:
                    chain = [nxt]
                    back: Optional[str] = cur
                    while back is not None:
                        chain.append(back)
                        back = parent[back]
                    chain.reverse()
                    return chain
                queue.append(nxt)
        return None

    def call_sites_of(self, target: FunctionInfo
                      ) -> List[Tuple[FunctionInfo, ast.Call]]:
        """Every package call site resolving to `target`."""
        out: List[Tuple[FunctionInfo, ast.Call]] = []
        for m in self.modules.values():
            for fn in m.all_functions:
                for e in self.callees(fn):
                    if e.callee is target and e.call is not None:
                        out.append((fn, e.call))
        return out
