"""lockgraph — lock-order and lock-latency analysis (GC012).

The serving fleet runs eleven lock sites across five classes; its two
standing disciplines have so far been comment-enforced:

  * acquisition ORDER is a partial order (fleet.py takes `_load_lock`
    then `_lock`, never the reverse) — an inverted nesting anywhere
    creates a deadlock window that no single-threaded test can see;
  * hot-path locks are FAST: cold model loads, device dispatch and
    socket I/O happen OUTSIDE the pool/metrics/breaker locks, so a
    slow operation can never stall every serving thread behind a lock
    (fleet.py's loads-outside-pool-lock discipline).

GC012 machine-checks both.  The lock-acquisition graph is built from
`with self._lock:` syntax (locks named per owning class, with
module-global singletons like faults._REG resolved to their class) plus
the existing `@contract.locked_by` declarations; edges are lexical
nesting and calls made while holding a lock whose transitive closure
acquires another lock.  Findings:

  * a CYCLE in the acquisition graph (potential deadlock);
  * a blocking operation — a call to a contracts.BLOCKING_FUNCTIONS
    entry (model parse+warm, device dispatch, batcher submit) or a
    blocking attribute call (socket recv/accept/connect, sleep,
    subprocess communicate) — reached while holding a lock not listed
    in contracts.LOCK_ALLOWED_BLOCKING.  `.wait()` on the held
    condition variable is exempt (releasing the lock is the point).

Scope: `with` sites in serving/ and resilience/ (the threaded
subsystems); closures are computed package-wide so a blocking call two
modules away is still attributed to the lock held at the top.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import (CallGraph, FunctionInfo, _dotted, _lockish_name,
                        own_nodes)
from .contracts import (BLOCKING_ATTR_CALLS, BLOCKING_FUNCTIONS,
                        LOCK_ALLOWED_BLOCKING)
from .graftlint import Finding

__jax_free__ = True

LOCK_RULES: Dict[str, str] = {
    "GC012": "lock-order",
}

#: module prefixes whose `with <lock>` sites are checked
_SCOPE_PREFIXES = ("serving/", "resilience/")


def _in_scope(rel: str) -> bool:
    return any(rel.startswith(p) for p in _SCOPE_PREFIXES)


class _BlockingOp:
    def __init__(self, qual: str, line: int, what: str,
                 receiver_lock: Optional[str]):
        self.qual = qual          # function the op lives in
        self.line = line
        self.what = what          # human-readable operation
        self.receiver_lock = receiver_lock   # lockish receiver of .wait


class _LockAnalyzer:
    def __init__(self, graph: CallGraph):
        self.graph = graph
        self._global_types = self._module_global_types()
        self._acq_memo: Dict[FunctionInfo, Set[str]] = {}
        self._blk_memo: Dict[FunctionInfo, List[_BlockingOp]] = {}

    # -- lock node naming ------------------------------------------------
    def _module_global_types(self) -> Dict[Tuple[str, str], str]:
        """{(module rel, global name): class name} for module-level
        `NAME = ClassName(...)` singletons (faults._REG)."""
        out: Dict[Tuple[str, str], str] = {}
        for rel, mod in self.graph.modules.items():
            for node in mod.tree.body:
                if not isinstance(node, ast.Assign) \
                        or len(node.targets) != 1:
                    continue
                t = node.targets[0]
                v = node.value
                if isinstance(t, ast.Name) and isinstance(v, ast.Call):
                    cls = _dotted(v.func)
                    if cls is not None and "." not in cls \
                            and cls in mod.classes:
                        out[(rel, t.id)] = cls
        return out

    def lock_node(self, fn: FunctionInfo,
                  ctx_expr: ast.AST) -> Optional[str]:
        """Class-qualified lock name for one with-context expression
        ('self._lock' in a ModelFleet method -> 'ModelFleet._lock'),
        or None when it is not a lock or its owner is unknown."""
        attr = _lockish_name(ctx_expr)
        if attr is None:
            return None
        expr = ctx_expr
        if isinstance(expr, ast.Call):
            expr = expr.func
        dotted = _dotted(expr)
        if dotted is None:
            return None
        parts = dotted.split(".")
        if parts[0] == "self" and fn.cls is not None and len(parts) == 2:
            return "%s.%s" % (fn.cls.name, parts[1])
        if len(parts) == 2:
            cls = self._global_types.get((fn.module.rel, parts[0]))
            if cls is not None:
                return "%s.%s" % (cls, parts[1])
        return None

    # -- transitive summaries ---------------------------------------------
    def acquired_closure(self, fn: FunctionInfo) -> Set[str]:
        """Lock nodes acquired by fn or anything it reaches."""
        memo = self._acq_memo.get(fn)
        if memo is not None:
            return memo
        out: Set[str] = set()
        for reached in self.graph.reach([fn]):
            for node in own_nodes(reached.node):
                if isinstance(node, ast.With):
                    for item in node.items:
                        ln = self.lock_node(reached, item.context_expr)
                        if ln is not None:
                            out.add(ln)
        self._acq_memo[fn] = out
        return out

    @staticmethod
    def classify_blocking(call: ast.Call
                          ) -> Optional[Tuple[str, Optional[str]]]:
        """(human-readable op, lockish `.wait` receiver or None) when
        this call is a blocking operation; None otherwise.  The ONE
        classifier behind both the direct under-lock check and the
        transitive closure — the two must never drift.  notify/
        notify_all never block; `.wait` blocks regardless of receiver
        (the caller exempts only a wait on the HELD condition
        variable, which releases the lock)."""
        dotted = _dotted(call.func)
        term = dotted.rpartition(".")[2] if dotted else ""
        if dotted == "time.sleep" or (
                term in BLOCKING_ATTR_CALLS
                and isinstance(call.func, ast.Attribute)):
            if term in ("notify", "notify_all"):
                return None
            recv = None
            if term == "wait" and isinstance(call.func, ast.Attribute):
                recv = _lockish_name(call.func.value)
            return "%s(...)" % (dotted or ".%s" % term), recv
        return None

    def _own_blocking(self, fn: FunctionInfo) -> List[_BlockingOp]:
        out: List[_BlockingOp] = []
        for node in own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            op = self.classify_blocking(node)
            if op is not None:
                out.append(_BlockingOp(
                    fn.qual, getattr(node, "lineno", 1), op[0], op[1]))
        return out

    def blocking_closure(self, fn: FunctionInfo) -> List[_BlockingOp]:
        """Blocking evidence anywhere in fn's transitive call closure,
        including fn itself being a declared blocking primitive."""
        memo = self._blk_memo.get(fn)
        if memo is not None:
            return memo
        out: List[_BlockingOp] = []
        for reached in self.graph.reach([fn]):
            if reached.qual in BLOCKING_FUNCTIONS:
                out.append(_BlockingOp(
                    reached.qual, getattr(reached.node, "lineno", 1),
                    "declared blocking primitive %s" % reached.qual,
                    None))
            out.extend(self._own_blocking(reached))
        self._blk_memo[fn] = out
        return out


def _with_calls(with_node: ast.With) -> List[ast.Call]:
    """Calls lexically inside a with block (nested defs/lambdas are
    deferred and excluded)."""
    out: List[ast.Call] = []
    stack: List[ast.AST] = []
    for stmt in with_node.body:
        stack.append(stmt)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _inner_withs(with_node: ast.With) -> List[ast.With]:
    out: List[ast.With] = []
    stack: List[ast.AST] = []
    for stmt in with_node.body:
        stack.append(stmt)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.With):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def check_lock_order(graph: CallGraph,
                     findings: List[Finding]) -> None:
    an = _LockAnalyzer(graph)
    # edges: held-lock -> acquired-lock, with one evidence site each
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add_edge(a: str, b: str, rel: str, line: int,
                 how: str) -> None:
        if a != b:
            edges.setdefault((a, b), (rel, line, how))

    for rel in sorted(graph.modules):
        if not _in_scope(rel):
            continue
        mod = graph.modules[rel]
        for fn in mod.all_functions:
            for node in own_nodes(fn.node):
                if not isinstance(node, ast.With):
                    continue
                held = [an.lock_node(fn, item.context_expr)
                        for item in node.items]
                held = [h for h in held if h is not None]
                if not held:
                    continue
                line = getattr(node, "lineno", 1)
                for lock in held:
                    # lexically nested acquisitions
                    for inner in _inner_withs(node):
                        for item in inner.items:
                            ln = an.lock_node(fn, item.context_expr)
                            if ln is not None:
                                add_edge(lock, ln, rel,
                                         getattr(inner, "lineno", line),
                                         "nested `with` in %s" % fn.qual)
                    lock_attr = lock.rpartition(".")[2]
                    allowed = lock in LOCK_ALLOWED_BLOCKING
                    for call in _with_calls(node):
                        cline = getattr(call, "lineno", line)
                        targets = graph._resolve_callee_expr(
                            fn, call.func)
                        for t in targets:
                            for ln in an.acquired_closure(t):
                                add_edge(lock, ln, rel, cline,
                                         "call to %s under %s in %s"
                                         % (t.qual, lock, fn.qual))
                        if allowed:
                            continue
                        # direct blocking operation under the lock —
                        # the SAME classifier the transitive closure
                        # uses, so the two checks cannot drift.  A
                        # .wait() on the held cv releases the lock and
                        # is exempt; on anything else (an Event,
                        # another cv) it blocks WITH the lock held.
                        op = an.classify_blocking(call)
                        if op is not None:
                            what, recv = op
                            if recv == lock_attr:
                                continue
                            findings.append(Finding(
                                rel, cline, "GC012",
                                "%s while holding %s in %s — a "
                                "blocking operation under a fast lock "
                                "stalls every thread behind it; move "
                                "it outside the lock or register the "
                                "lock in contracts.LOCK_ALLOWED_"
                                "BLOCKING with a justification"
                                % (what, lock, fn.qual)))
                            continue
                        # blocking reached through a resolved callee
                        for t in targets:
                            for op in an.blocking_closure(t):
                                if op.receiver_lock == lock_attr:
                                    continue   # wait on the held cv
                                findings.append(Finding(
                                    rel, cline, "GC012",
                                    "call to %s while holding %s in "
                                    "%s reaches a blocking operation "
                                    "(%s at %s:%d) — cold loads/"
                                    "dispatch/socket I/O must run "
                                    "outside fast locks (fleet.py's "
                                    "loads-outside-pool-lock "
                                    "discipline); or register the "
                                    "lock in contracts.LOCK_ALLOWED_"
                                    "BLOCKING"
                                    % (t.qual, lock, fn.qual, op.what,
                                       op.qual, op.line)))
                                break   # one evidence line per callee

    # cycle detection over the acquisition graph
    adj: Dict[str, List[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
    for outs in adj.values():
        outs.sort()
    reported: Set[Tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: List[str],
            on_path: Set[str]) -> None:
        for nxt in adj.get(node, []):
            if nxt == start:
                cycle = path + [nxt]
                key = tuple(sorted(set(cycle)))
                if key in reported:
                    continue
                reported.add(key)
                rel, line, how = edges[(path[-1], nxt)]
                findings.append(Finding(
                    rel, line, "GC012",
                    "lock acquisition cycle %s — two threads taking "
                    "these locks in opposite orders deadlock; pick "
                    "ONE order (evidence for the closing edge: %s)"
                    % (" -> ".join(cycle), how)))
            elif nxt not in on_path:
                # bound the walk: cycles in this graph are tiny
                if len(path) < 6:
                    dfs(start, nxt, path + [nxt], on_path | {nxt})

    for start in sorted(adj):
        dfs(start, start, [start], {start})


def run_lockgraph_graph(graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    check_lock_order(graph, findings)
    return findings
