"""typegate — annotation-completeness gate for the mypy-strict modules.

pyproject.toml runs mypy --strict over config.py, api.py and serving/;
this container-independent gate enforces the part of that bar that an
AST can check (every function fully annotated: parameters AND return),
so the typing floor holds even on machines without mypy installed.
`scripts/lint.sh` runs real mypy too whenever it is available.

Rules mirror mypy's disallow_untyped_defs / disallow_incomplete_defs:
  * every parameter except self/cls needs an annotation (including
    *args / **kwargs);
  * every function needs a return annotation, except __init__ /
    __init_subclass__ (mypy infers -> None there when the params are
    annotated);
  * nested functions count (mypy strict checks them).
Lambdas are exempt, as in mypy.
"""

from __future__ import annotations

__jax_free__ = True

import ast
import os
from typing import List, Optional, Sequence

from .graftlint import Finding, _attach_parents, package_root

# package-relative modules held to the strict-typing bar (keep in sync
# with [tool.mypy] in pyproject.toml).  serving/ and analysis/ are
# globbed at run time so a new module in either cannot silently escape
# the gate — the analyzer holds itself to the bar it enforces.
GATED_MODULES = (
    "config.py",
    "api.py",
)
GATED_DIRS = ("serving", "analysis", "refresh")


def gated_modules(root: Optional[str] = None) -> List[str]:
    """Every package-relative module the typing gate covers, with the
    gated directories expanded to their current contents."""
    root = root or package_root()
    out = list(GATED_MODULES)
    for d in GATED_DIRS:
        full = os.path.join(root, d)
        if os.path.isdir(full):
            out.extend(sorted(
                "%s/%s" % (d, fn) for fn in os.listdir(full)
                if fn.endswith(".py")))
    return out

RETURN_EXEMPT = {"__init__", "__init_subclass__"}


def _check_module(tree: ast.AST, display: str) -> List[Finding]:
    out: List[Finding] = []
    _attach_parents(tree)
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        in_class = isinstance(getattr(node, "_gl_parent", None),
                              ast.ClassDef)
        args = node.args
        params = (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs))
        missing = []
        for i, p in enumerate(params):
            if in_class and i == 0 and p.arg in ("self", "cls"):
                continue
            if p.annotation is None:
                missing.append(p.arg)
        for star in (args.vararg, args.kwarg):
            if star is not None and star.annotation is None:
                missing.append("*" + star.arg)
        if missing:
            out.append(Finding(
                display, node.lineno, "TYPE",
                "def %s: unannotated parameter(s) %s"
                % (node.name, ", ".join(missing))))
        n_annotated = sum(1 for p in params if p.annotation is not None)
        # mypy only infers -> None for __init__ when at least one
        # parameter is annotated; a zero-argument __init__ still needs
        # the explicit -> None under strict
        exempt = node.name in RETURN_EXEMPT and n_annotated > 0
        if node.returns is None and not exempt:
            out.append(Finding(
                display, node.lineno, "TYPE",
                "def %s: missing return annotation" % node.name))
    return out


def run_typegate(paths: Optional[Sequence[str]] = None,
                 root: Optional[str] = None) -> List[Finding]:
    root = root or package_root()
    if paths is None:
        paths = [os.path.join(root, rel.replace("/", os.sep))
                 for rel in gated_modules(root)]
    out: List[Finding] = []
    for path in paths:
        display = (os.path.relpath(path, os.getcwd())
                   if os.path.isabs(path) else path)
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src, filename=display)
        except (OSError, SyntaxError) as ex:
            out.append(Finding(display, 1, "TYPE",
                               "unreadable/unparseable: %s" % ex))
            continue
        out.extend(_check_module(tree, display))
    out.sort(key=lambda f: (f.path, f.line))
    return out


def check_source(source: str, display: str = "<string>") -> List[Finding]:
    """Gate one in-memory module (test helper)."""
    return _check_module(ast.parse(source), display)
