"""graftsync — static SPMD collective-safety analysis (GC009-GC011).

The class of bug that kills a distributed GBDT run is one rank
skipping or reordering a HOST collective behind a rank-local branch:
every other rank blocks inside its allgather with no diagnostic until
the deadline fires.  graftcheck GC005 already verifies the SET of
device collectives per fused body is uniform; these rules verify the
SEQUENCE of host-level collectives (parallel/dist.py wrappers:
process_allgather, vote_any, sync_max_ints, process_concat, the
config/fingerprint syncs) is identical across control-flow paths on
every rank.

  GC009 collective-sequence-divergence
        A branch whose condition is NOT provably rank-uniform emits
        different collective sequences on its arms (including "one arm
        emits, the other doesn't" and "same set, different order"), or
        exits a collective-emitting function early on one rank only.
        Conditions count as rank-uniform when they derive from
        fingerprint-synced config, collective results (vote_any /
        sync_max_ints / process_allgather return identical values on
        every rank), jax.process_count(), or calls annotated
        @contract.rank_uniform; a `log.fatal`/`raise` arm is exempt —
        an aborting rank surfaces as a typed NetworkError on its peers
        via the call_with_deadline wrapping, not as a silent hang.
  GC010 collective-in-rank-local-loop
        A loop whose trip count is not provably rank-uniform contains
        a collective (directly or through any resolvable call chain),
        or a rank-local break/return inside a collective-emitting
        loop: ranks would run different collective COUNTS.
  GC011 collective-outside-dist
        Direct use of jax.experimental.multihost_utils or
        jax.distributed outside parallel/dist.py: every blocking host
        collective must funnel through the dist.py wrappers so it
        inherits the per-collective deadline (NetworkError instead of
        an indefinite hang) and the runtime collective trace.

Model notes (deliberate approximations, both conservative for the
sequences they CAN see): calls the resolver cannot bind (values passed
as parameters, `self.stop_sync(...)`-style hooks) contribute no atoms
— the runtime tracer (dist.trace_collectives) is the complementary
check that sees every dynamic call; lambdas and nested defs emit
nothing at definition site (they run when invoked).  Uniformity is a
statement-order dataflow over one function: a name is rank-uniform at
a use iff its latest assignment was uniform (so vote-then-branch, the
tree's standard pattern, resolves correctly), names assigned under a
rank-LOCAL branch are poisoned afterwards (whether the assignment ran
depends on the rank), `while` heads are re-checked against the
post-body environment (the head re-evaluates every iteration), and
names in contracts.RANK_VARYING_NAMES never launder to uniform.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, FunctionInfo, _dotted
from .contracts import (COLLECTIVE_ENTRY_MODULE, HOST_COLLECTIVES,
                        RANK_UNIFORM_ATTRS, RANK_UNIFORM_CALLS,
                        RANK_VARYING_NAMES)
from .graftlint import Finding

__jax_free__ = True

SYNC_RULES: Dict[str, str] = {
    "GC009": "collective-sequence-divergence",
    "GC010": "collective-in-rank-local-loop",
    "GC011": "collective-outside-dist",
}

#: builtins that preserve rank-uniformity of their arguments
_UNIFORM_BUILTINS = {
    "int", "float", "bool", "str", "len", "min", "max", "abs", "sum",
    "any", "all", "round", "sorted", "tuple", "set", "frozenset",
    "range", "enumerate", "zip", "isinstance", "getattr", "hasattr",
    "type",
}

#: names denoting pure value namespaces: a method chained off one is
#: uniform when its arguments are.  `os` is deliberately absent —
#: os.path.exists/os.listdir read the rank-LOCAL filesystem.
_UNIFORM_ROOTS = {"np", "numpy", "math", "set", "frozenset"}

# Sequence events (compared structurally):
#   ("c", name)                   one host collective
#   ("br", arm_a, arm_b)          rank-uniform branch, differing arms
#   ("loop", body)                rank-uniform loop over a collective body
_Seq = Tuple[object, ...]


def _terminal(dotted: Optional[str]) -> str:
    return dotted.rpartition(".")[2] if dotted else ""


def _is_abort_call(dotted: Optional[str]) -> bool:
    return dotted in ("log.fatal", "sys.exit", "os._exit", "exit")


#: statement-termination kinds that are rank-divergence candidates
#: (unlike "abort", which is exempt — see the GC009 rule notes)
_EXIT_KINDS = ("return", "break", "continue")


class _SyncAnalyzer:
    """Per-graph sequence/uniformity analysis shared by GC009/GC010."""

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self._seq_memo: Dict[FunctionInfo, _Seq] = {}
        self._in_progress: Set[FunctionInfo] = set()
        self._findings: Dict[FunctionInfo, List[Finding]] = {}
        # per-callsite resolution memo: every Call is resolved up to
        # three times (atom probe, summary splice, uniformity) and the
        # loop dry-scan doubles it again — the graph's trees are
        # stable for this analyzer's lifetime, so cache by node id
        self._resolve_memo: Dict[Tuple[int, int],
                                 List[FunctionInfo]] = {}
        #: function NAMES carrying @contract.rank_uniform — used as a
        #: fallback for call shapes the resolver cannot bind
        #: (`snaps.sync_flag(...)` through an attribute of unknown
        #: type).  Deliberately name-matched, like GC004's fallback.
        self._uniform_names: Set[str] = {
            fn.name for fn in graph.contracted("rank_uniform")}

    # -- atoms ----------------------------------------------------------
    def _resolve(self, fn: FunctionInfo,
                 expr: ast.AST) -> List[FunctionInfo]:
        key = (id(fn), id(expr))
        hit = self._resolve_memo.get(key)
        if hit is None:
            hit = self.graph._resolve_callee_expr(fn, expr)
            self._resolve_memo[key] = hit
        return hit

    def _atom_of(self, fn: FunctionInfo,
                 call: ast.Call) -> Optional[str]:
        """Host-collective name this call dispatches, or None."""
        targets = self._resolve(fn, call.func)
        for t in targets:
            if t.module.rel == COLLECTIVE_ENTRY_MODULE \
                    and t.name in HOST_COLLECTIVES:
                return t.name
        if not targets:
            name = _terminal(_dotted(call.func))
            if name in HOST_COLLECTIVES:
                return name
        return None

    def _callee_seq(self, fn: FunctionInfo, call: ast.Call) -> _Seq:
        """Spliced summary of a resolved non-atom package call."""
        targets = self._resolve(fn, call.func)
        if len(targets) != 1:
            return ()
        return self.seq(targets[0])

    @staticmethod
    def _own_calls(fn: FunctionInfo) -> List[ast.Call]:
        from .callgraph import own_nodes
        return [n for n in own_nodes(fn.node) if isinstance(n, ast.Call)]

    # -- rank-uniformity of an expression -------------------------------
    def _uniform(self, fn: FunctionInfo, expr: ast.AST,
                 env: Dict[str, bool]) -> bool:
        if isinstance(expr, ast.Constant):
            return True
        if isinstance(expr, ast.Name):
            if expr.id in RANK_VARYING_NAMES:
                return False
            if expr.id in env:
                return env[expr.id]
            if expr.id.isupper() or expr.id in _UNIFORM_BUILTINS \
                    or expr.id in _UNIFORM_ROOTS:
                return True        # module constant / pure namespace
            return self._is_param(fn, expr.id)
        if isinstance(expr, ast.Attribute):
            dotted = _dotted(expr)
            if dotted is None:
                return False
            segs = dotted.split(".")
            if any(s in RANK_VARYING_NAMES for s in segs):
                return False
            if segs[0] in ("config", "cfg") \
                    or "config" in segs[1:-1] or "cfg" in segs[1:-1]:
                return True        # fingerprint-synced configuration
            return segs[-1] in RANK_UNIFORM_ATTRS
        if isinstance(expr, ast.BoolOp):
            return all(self._uniform(fn, v, env) for v in expr.values)
        if isinstance(expr, ast.UnaryOp):
            return self._uniform(fn, expr.operand, env)
        if isinstance(expr, ast.BinOp):
            return (self._uniform(fn, expr.left, env)
                    and self._uniform(fn, expr.right, env))
        if isinstance(expr, ast.Compare):
            return (self._uniform(fn, expr.left, env)
                    and all(self._uniform(fn, c, env)
                            for c in expr.comparators))
        if isinstance(expr, ast.IfExp):
            return (self._uniform(fn, expr.test, env)
                    and self._uniform(fn, expr.body, env)
                    and self._uniform(fn, expr.orelse, env))
        if isinstance(expr, ast.Subscript):
            return (self._uniform(fn, expr.value, env)
                    and self._uniform(fn, expr.slice, env))
        if isinstance(expr, ast.Slice):
            return all(self._uniform(fn, p, env)
                       for p in (expr.lower, expr.upper, expr.step)
                       if p is not None)
        if isinstance(expr, ast.Tuple):
            return all(self._uniform(fn, e, env) for e in expr.elts)
        if isinstance(expr, ast.Starred):
            return self._uniform(fn, expr.value, env)
        if isinstance(expr, ast.Call):
            return self._uniform_call(fn, expr, env)
        if isinstance(expr, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp, ast.DictComp)):
            env2 = dict(env)
            for gen in expr.generators:
                it_u = self._uniform(fn, gen.iter, env2)
                self._bind(gen.target, it_u, env2)
                if not all(self._uniform(fn, c, env2)
                           for c in gen.ifs):
                    return False
            if isinstance(expr, ast.DictComp):
                return (self._uniform(fn, expr.key, env2)
                        and self._uniform(fn, expr.value, env2))
            return self._uniform(fn, expr.elt, env2)
        # List/Dict/Set literals are mutable containers (a closure or
        # signal handler can poke them rank-locally: cli.train's
        # preempted flag); attribute soup: unknown.
        return False

    def _uniform_call(self, fn: FunctionInfo, call: ast.Call,
                      env: Dict[str, bool]) -> bool:
        dotted = _dotted(call.func)
        if dotted in RANK_UNIFORM_CALLS:
            return True
        name = _terminal(dotted) if dotted else ""
        if dotted == "isinstance" and len(call.args) == 2:
            # the TYPE argument is a class expression — program text,
            # identical on every rank by construction — so only the
            # tested VALUE decides uniformity (a module-level class
            # name would otherwise read as attribute soup)
            return self._uniform(fn, call.args[0], env)
        if dotted is not None and dotted in _UNIFORM_BUILTINS:
            return all(self._uniform(fn, a, env) for a in call.args)
        targets = self._resolve(fn, call.func)
        if targets:
            return all(
                ("rank_uniform" in t.contracts)
                or (t.module.rel == COLLECTIVE_ENTRY_MODULE
                    and t.name in HOST_COLLECTIVES)
                for t in targets)
        # unresolvable: name-matched fallbacks only
        if name in HOST_COLLECTIVES or name in self._uniform_names:
            return True
        # method chained off a uniform value (alls.reshape, x.max, ...)
        if isinstance(call.func, ast.Attribute) \
                and self._uniform(fn, call.func.value, env):
            return all(self._uniform(fn, a, env) for a in call.args)
        return False

    @staticmethod
    def _is_param(fn: FunctionInfo, name: str) -> bool:
        """Parameters default to rank-uniform: SPMD entry points pass
        config-derived values; genuinely per-rank parameters are named
        rank/process_index (RANK_VARYING_NAMES) by convention, which
        wins above."""
        node = fn.node
        assert isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        a = node.args
        names = [p.arg for p in (list(a.posonlyargs) + list(a.args)
                                 + list(a.kwonlyargs))]
        for extra in (a.vararg, a.kwarg):
            if extra is not None:
                names.append(extra.arg)
        return name in names

    # -- sequence construction + checking -------------------------------
    def seq(self, fn: FunctionInfo) -> _Seq:
        memo = self._seq_memo.get(fn)
        if memo is not None:
            return memo
        if fn in self._in_progress:   # recursion back-edge
            return ()
        self._in_progress.add(fn)
        findings: List[Finding] = []
        env: Dict[str, bool] = {}
        try:
            body = list(getattr(fn.node, "body", []))
            out, _term, _pending = self._stmts_seq(
                fn, body, env, findings, loop_coll=False)
            # pending early-exit divergences with NO collective after
            # them are harmless: every rank that reaches a collective
            # took the same prefix.  They drop here.
        finally:
            self._in_progress.discard(fn)
        self._seq_memo[fn] = out
        self._findings[fn] = findings
        return out

    def findings_for(self, fn: FunctionInfo) -> List[Finding]:
        self.seq(fn)
        return self._findings.get(fn, [])

    def _expr_seq(self, fn: FunctionInfo, expr: Optional[ast.AST],
                  ) -> _Seq:
        """Atoms/summaries of every call inside one expression, in
        EVALUATION order: post-order over the expression tree, so a
        call nested in another call's arguments emits BEFORE the outer
        call (Python evaluates arguments first — a lineno/col sort
        would invert them and cry wolf on equivalent arms).  Lambdas
        and nested defs that merely BUILD deferred callables
        contribute nothing at this site."""
        if expr is None:
            return ()
        out: List[object] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                return
            for child in ast.iter_child_nodes(node):
                visit(child)
            if isinstance(node, ast.Call):
                atom = self._atom_of(fn, node)
                if atom is not None:
                    out.append(("c", atom))
                else:
                    out.extend(self._callee_seq(fn, node))

        visit(expr)
        return tuple(out)

    def _assign_env(self, fn: FunctionInfo, stmt: ast.stmt,
                    env: Dict[str, bool]) -> None:
        if isinstance(stmt, ast.Assign):
            u = self._uniform(fn, stmt.value, env)
            for t in stmt.targets:
                self._bind(t, u, env)
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            self._bind(stmt.target,
                       self._uniform(fn, stmt.value, env), env)
        elif isinstance(stmt, ast.AugAssign):
            u = (self._uniform(fn, stmt.target, env)
                 and self._uniform(fn, stmt.value, env))
            self._bind(stmt.target, u, env)

    @staticmethod
    def _bind(target: ast.AST, uniform: bool,
              env: Dict[str, bool]) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = uniform and \
                target.id not in RANK_VARYING_NAMES
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                _SyncAnalyzer._bind(el, uniform, env)

    def _stmts_seq(self, fn: FunctionInfo, stmts: List[ast.stmt],
                   env: Dict[str, bool], findings: List[Finding],
                   loop_coll: bool
                   ) -> Tuple[_Seq, Optional[str],
                              List[Tuple[int, str, str]]]:
        """(sequence, termination, pending) of a statement list.
        termination: None = falls through, "return"/"break"/
        "continue" = the respective early exit, "abort" = raise /
        log.fatal / sys.exit.
        pending: rank-dependent early exits seen so far with no
        collective after them YET — a later statement that emits one
        converts each pending record into a GC009 finding (ranks that
        exited early would skip it); pendings with no collective
        downstream are harmless and drop at the function boundary."""
        seq: List[object] = []
        pending: List[Tuple[int, str, str]] = []
        for stmt in stmts:
            s, term, p = self._stmt_seq(fn, stmt, env, findings,
                                        loop_coll)
            self._convert_pending(fn, pending, s, findings)
            seq.extend(s)
            pending.extend(p)
            if term is not None:
                return tuple(seq), term, pending
        return tuple(seq), None, pending

    def _convert_pending(self, fn: FunctionInfo,
                         pending: List[Tuple[int, str, str]], later: _Seq,
                         findings: List[Finding]) -> None:
        """Convert pending rank-dependent early exits into GC009
        findings when `later` — a sequence the exiting ranks would
        skip — emits collectives; clears the list in place."""
        if not pending or not self._flatten_atoms(later):
            return
        for pline, cond, _kind in pending:
            findings.append(Finding(
                fn.module.rel, pline, "GC009",
                "rank-dependent early exit `%s` in %s skips the later "
                "collective sequence %s — exiting ranks would leave "
                "their peers blocked inside it"
                % (cond, fn.qual,
                   self._render(tuple(self._flatten_events(later))))))
        del pending[:]

    @classmethod
    def _flatten_events(cls, seq: _Seq) -> List[object]:
        return [("c", a) for a in cls._flatten_atoms(seq)]

    def _stmt_seq(self, fn: FunctionInfo, stmt: ast.stmt,
                  env: Dict[str, bool], findings: List[Finding],
                  loop_coll: bool
                  ) -> Tuple[_Seq, Optional[str],
                             List[Tuple[int, str, str]]]:
        rel = fn.module.rel
        line = getattr(stmt, "lineno", 1)
        none: List[Tuple[int, str, str]] = []
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return (), None, none
        if isinstance(stmt, ast.Return):
            return self._expr_seq(fn, stmt.value), "return", none
        if isinstance(stmt, ast.Break):
            return (), "break", none
        if isinstance(stmt, ast.Continue):
            return (), "continue", none
        if isinstance(stmt, ast.Raise):
            return self._expr_seq(fn, stmt.exc), "abort", none
        if isinstance(stmt, ast.Expr):
            val = stmt.value
            s = self._expr_seq(fn, val)
            if isinstance(val, ast.Call) \
                    and _is_abort_call(_dotted(val.func)):
                return s, "abort", none
            return s, None, none
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(stmt, "value", None)
            s = self._expr_seq(fn, value)
            self._assign_env(fn, stmt, env)
            return s, None, none
        if isinstance(stmt, ast.If):
            return self._if_seq(fn, stmt, env, findings, loop_coll)
        if isinstance(stmt, (ast.While, ast.For)):
            return self._loop_seq(fn, stmt, env, findings)
        if isinstance(stmt, ast.With):
            seq: List[object] = []
            for item in stmt.items:
                seq.extend(self._expr_seq(fn, item.context_expr))
            body, term, p = self._stmts_seq(fn, stmt.body, env,
                                            findings, loop_coll)
            return tuple(seq) + body, term, p
        if isinstance(stmt, ast.Try):
            seq_l: List[object] = []
            pend: List[Tuple[int, str, str]] = []
            body, term, p = self._stmts_seq(fn, stmt.body, env,
                                            findings, loop_coll)
            seq_l.extend(body)
            pend.extend(p)
            for h in stmt.handlers:
                hseq, _ht, _hp = self._stmts_seq(fn, h.body, env,
                                                 findings, loop_coll)
                if hseq:
                    findings.append(Finding(
                        rel, getattr(h, "lineno", line), "GC009",
                        "collective sequence %s inside an exception "
                        "handler in %s — exception arrival is not "
                        "rank-uniform, so the handler's collectives "
                        "run on a subset of ranks"
                        % (self._render(hseq), fn.qual)))
            if term is None:
                o, oterm, op = self._stmts_seq(fn, stmt.orelse, env,
                                               findings, loop_coll)
                # a pending early exit from the try body skips the
                # orelse: a collective there converts it (same rule as
                # the statement-list walk)
                self._convert_pending(fn, pend, o, findings)
                seq_l.extend(o)
                pend.extend(op)
                term = oterm
            # NOTE: no conversion against finalbody — `finally` runs on
            # the early-exiting rank too, so its collectives are not
            # skipped; the pendings stay live for statements AFTER the
            # try (which an early exit does skip)
            fin, fterm, fp = self._stmts_seq(fn, stmt.finalbody, env,
                                             findings, loop_coll)
            seq_l.extend(fin)
            pend.extend(fp)
            return tuple(seq_l), fterm or term, pend
        if isinstance(stmt, (ast.Import, ast.ImportFrom, ast.Pass,
                             ast.Global, ast.Nonlocal, ast.Assert,
                             ast.Delete)):
            return (), None, none
        return (), None, none

    def _if_seq(self, fn: FunctionInfo, stmt: ast.If,
                env: Dict[str, bool], findings: List[Finding],
                loop_coll: bool
                ) -> Tuple[_Seq, Optional[str],
                           List[Tuple[int, str, str]]]:
        rel = fn.module.rel
        line = getattr(stmt, "lineno", 1)
        test_seq = self._expr_seq(fn, stmt.test)
        uniform = self._uniform(fn, stmt.test, env)
        pend: List[Tuple[int, str, str]] = []
        pre_env = dict(env)
        a_seq, a_term, ap = self._stmts_seq(fn, stmt.body, env,
                                            findings, loop_coll)
        b_seq, b_term, bp = self._stmts_seq(fn, stmt.orelse, env,
                                            findings, loop_coll)
        pend.extend(ap)
        pend.extend(bp)
        if not uniform:
            # a name assigned under a rank-LOCAL condition is rank-
            # local afterwards no matter how uniform the assigned value
            # looked (whether the assignment ran depends on the rank) —
            # without this, `if rank == 0: flag = True` launders flag.
            # Uniform-test branches keep the last-assignment-wins rule:
            # the vote-then-branch idiom relies on it.
            for name, val in list(env.items()):
                if pre_env.get(name) is not val:
                    env[name] = False
        if not uniform:
            cond = ast.unparse(stmt.test) if hasattr(ast, "unparse") \
                else "<condition>"
            arms = [(a_seq, a_term), (b_seq, b_term)]
            live = [(s, t) for s, t in arms if t != "abort"]
            for s, t in arms:
                if t == "abort" and s:
                    findings.append(Finding(
                        rel, line, "GC009",
                        "collective sequence %s on an aborting arm of "
                        "the rank-dependent branch `%s` in %s — a "
                        "subset of ranks would enter the collective "
                        "before dying" % (self._render(s), cond,
                                          fn.qual)))
            if len(live) == 2 and live[0][0] != live[1][0]:
                findings.append(Finding(
                    rel, line, "GC009",
                    "branch arms emit different collective sequences "
                    "(%s vs %s) under the rank-dependent condition "
                    "`%s` in %s — every rank must execute the "
                    "identical collective sequence (prove the "
                    "condition rank-uniform via vote_any / synced "
                    "config / @contract.rank_uniform, or lift the "
                    "collectives out of the branch)"
                    % (self._render(live[0][0]),
                       self._render(live[1][0]), cond, fn.qual)))
            exits = [t for _, t in live if t in _EXIT_KINDS]
            if exits and len(exits) != len(live):
                if loop_coll:
                    findings.append(Finding(
                        rel, line, "GC010",
                        "rank-dependent early exit `%s` inside a "
                        "collective-emitting loop in %s — ranks would "
                        "run different collective counts" % (cond,
                                                             fn.qual)))
                else:
                    # divergence only matters if a collective follows:
                    # the enclosing walk resolves or drops it, honoring
                    # what each exit kind actually skips
                    pend.append((line, cond, exits[0]))
        # summary event + termination
        if a_seq == b_seq and a_term == b_term:
            ev: _Seq = a_seq
            term = a_term
        else:
            ev = (("br", (a_seq, a_term), (b_seq, b_term)),) \
                if (a_seq or b_seq) else ()
            if a_term is not None and b_term is not None:
                kinds = [t for t in (a_term, b_term)
                         if t in _EXIT_KINDS]
                term: Optional[str] = kinds[0] if kinds else "abort"
            else:
                term = None
        return test_seq + tuple(ev), term, pend

    def _loop_seq(self, fn: FunctionInfo, stmt: ast.stmt,
                  env: Dict[str, bool], findings: List[Finding]
                  ) -> Tuple[_Seq, Optional[str],
                             List[Tuple[int, str, str]]]:
        rel = fn.module.rel
        line = getattr(stmt, "lineno", 1)
        if isinstance(stmt, ast.While):
            head = stmt.test
        else:
            assert isinstance(stmt, ast.For)
            head = stmt.iter
        head_seq = self._expr_seq(fn, head)
        uniform = self._uniform(fn, head, env)
        if isinstance(stmt, ast.For):
            self._bind(stmt.target, uniform, env)
        # dry scan: does the body emit collectives at all?  (needed
        # before walking, so rank-local exits inside get GC010)
        probe: List[Finding] = []
        body_probe, _, _ = self._stmts_seq(fn, stmt.body, dict(env),
                                           probe, loop_coll=False)
        has_coll = bool(self._flatten_atoms(body_probe))
        body_seq, _term, bp = self._stmts_seq(fn, stmt.body, env,
                                              findings,
                                              loop_coll=has_coll)
        tail_seq, tail_term, tp = self._stmts_seq(fn, stmt.orelse, env,
                                                  findings,
                                                  loop_coll=False)
        # what each body exit kind skips: `return` skips the loop's
        # else-clause AND everything after the loop; `break` skips the
        # else-clause only; `continue` skips nothing outside its own
        # iteration.  (Exit-divergences in COLLECTIVE loops already
        # became GC010 via loop_coll.)
        live_after = [q for q in bp if q[2] == "return"]
        skip_else = [q for q in bp if q[2] in ("return", "break")]
        self._convert_pending(fn, skip_else, tail_seq, findings)
        # anything converted against the else is done; unconverted
        # returns stay live for the caller's statement walk
        live_after = [q for q in live_after if q in skip_else]
        if isinstance(stmt, ast.While) and uniform:
            # a `while` head re-evaluates every iteration: the body's
            # LAST assignments feed the next test, so a body that
            # leaves the condition rank-local (e.g. drops the re-sync)
            # diverges from iteration 2 on even when entry was uniform
            uniform = self._uniform(fn, head, env)
        if has_coll and not uniform:
            cond = ast.unparse(head) if hasattr(ast, "unparse") \
                else "<head>"
            findings.append(Finding(
                rel, line, "GC010",
                "collective sequence %s inside a loop whose trip "
                "count depends on `%s`, which is not provably "
                "rank-uniform (at entry or after the body's "
                "reassignments), in %s — ranks would run different "
                "collective counts; derive the bound from synced "
                "config/collective results or hoist the collective"
                % (self._render(body_seq), cond, fn.qual)))
        ev: _Seq = (("loop", body_seq),) if body_seq else ()
        return head_seq + ev + tail_seq, tail_term, live_after + tp

    # -- rendering -------------------------------------------------------
    @classmethod
    def _flatten_atoms(cls, seq: _Seq) -> List[str]:
        out: List[str] = []
        for ev in seq:
            assert isinstance(ev, tuple)
            if ev[0] == "c":
                out.append(str(ev[1]))
            elif ev[0] == "br":
                for arm in (ev[1], ev[2]):
                    out.extend(cls._flatten_atoms(arm[0]))
            elif ev[0] == "loop":
                out.extend(cls._flatten_atoms(ev[1]))
        return out

    @classmethod
    def _render(cls, seq: _Seq) -> str:
        atoms = cls._flatten_atoms(seq)
        return "[%s]" % ", ".join(atoms) if atoms else "[]"


# ---------------------------------------------------------------------------
# GC009 / GC010 — whole-package sweep
# ---------------------------------------------------------------------------

def check_collective_sequences(graph: CallGraph,
                               findings: List[Finding]) -> None:
    analyzer = _SyncAnalyzer(graph)
    for rel in sorted(graph.modules):
        mod = graph.modules[rel]
        for fn in mod.all_functions:
            findings.extend(analyzer.findings_for(fn))


# ---------------------------------------------------------------------------
# GC011 — single collective entry point
# ---------------------------------------------------------------------------

def check_collective_entry(graph: CallGraph,
                           findings: List[Finding]) -> None:
    for rel in sorted(graph.modules):
        if rel == COLLECTIVE_ENTRY_MODULE:
            continue            # the sanctioned site
        mod = graph.modules[rel]
        seen: Set[Tuple[int, str]] = set()

        def emit(line: int, what: str) -> None:
            if (line, what) in seen:
                return
            seen.add((line, what))
            findings.append(Finding(
                rel, line, "GC011",
                "%s outside %s — blocking host collectives must route "
                "through the parallel/dist.py wrappers so they "
                "inherit call_with_deadline (NetworkError instead of "
                "an indefinite hang) and the runtime collective trace"
                % (what, COLLECTIVE_ENTRY_MODULE)))

        for node in ast.walk(mod.tree):
            line = getattr(node, "lineno", 1)
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if "multihost_utils" in alias.name \
                            or alias.name == "jax.distributed" \
                            or alias.name.startswith("jax.distributed."):
                        emit(line, "import of %s" % alias.name)
            elif isinstance(node, ast.ImportFrom):
                m = node.module or ""
                if "multihost_utils" in m:
                    emit(line, "import from %s" % m)
                elif m in ("jax", "jax.experimental"):
                    for alias in node.names:
                        if alias.name in ("multihost_utils",
                                          "distributed"):
                            emit(line, "import of %s.%s"
                                 % (m, alias.name))
                elif m == "jax.distributed" \
                        or m.startswith("jax.distributed."):
                    emit(line, "import from %s" % m)
            elif isinstance(node, ast.Attribute):
                dotted = _dotted(node)
                if dotted is None:
                    continue
                if dotted.startswith("jax.distributed.") \
                        or "multihost_utils." in dotted:
                    emit(line, "direct use of %s" % dotted)


# ---------------------------------------------------------------------------
# Static model exports (the runtime-trace test cross-checks these)
# ---------------------------------------------------------------------------

def collective_sites(graph: CallGraph) -> Set[Tuple[str, int, str]]:
    """Every statically-resolved host-collective call site:
    {(module rel, line, collective name)}.  The 2-process runtime
    trace test asserts every traced callsite inside the package is one
    of these — a dynamically-dispatched collective the static model
    cannot see (a hook like GBDT.stop_sync) would fail the test and
    must be registered."""
    analyzer = _SyncAnalyzer(graph)
    out: Set[Tuple[str, int, str]] = set()
    for rel in sorted(graph.modules):
        mod = graph.modules[rel]
        for fn in mod.all_functions:
            for call in analyzer._own_calls(fn):
                atom = analyzer._atom_of(fn, call)
                if atom is not None:
                    out.add((rel, getattr(call, "lineno", 0), atom))
    return out


def run_graftsync_graph(graph: CallGraph) -> List[Finding]:
    findings: List[Finding] = []
    check_collective_sequences(graph, findings)
    check_collective_entry(graph, findings)
    return findings
