"""JAX-free native fast path for `task=predict`.

The reference serves prediction from one warm process: TextReader blocks
feed an OpenMP loop that parses, descends the trees and formats each row
(src/application/predictor.hpp:82-130).  The framework's default predict
path pays costs the reference never sees — Python+JAX import, TPU tunnel
upload, device readback — which BASELINE.md measured at over half the
end-to-end wall for a 1M-row file.  This module is the equivalent warm
loop: the model text is parsed host-side (no jax import anywhere on this
path), flattened into contiguous arrays, and each input chunk runs one
fused native parse -> descend -> transform -> "%g" pass
(native.predict_chunk / ingest.cpp lgt_predict_*_mt), streaming to the
output file with bounded memory.

Output is byte-identical to the default path (and to the reference
binary): same Atof parse arithmetic, same `<= threshold` descent, same
double accumulation order, same sigmoid/softmax expressions, same "%g"
formatting.  test_predict_fast pins fast-vs-default identity across
formats and modes; test_e2e_parity's golden predict tests run through
this path via the CLI.

Returns False from try_fast_predict when the native library is
unavailable so cli.Application falls back to the JAX path.
"""

from __future__ import annotations

__jax_free__ = True

from typing import List, Optional, Tuple

import numpy as np

from .analysis.contracts import contract
from .config import Config
from .io.parser import sniff_format
from .models.tree import Tree, parse_model_text
from .utils import log

# Input chunk size: large enough to amortize thread spawn per chunk,
# small enough to bound memory for arbitrarily large inputs.
CHUNK_BYTES = 64 << 20


def format_pred_rows(res: "np.ndarray", leaf: bool) -> bytes:
    """Predict results -> output bytes, the ONE home of the prediction
    output format (Predictor::SaveTextPredictionsToFile role), shared by
    cli.predict's streaming blocks and the serving subsystem so the two
    cannot drift: leaf mode tab-joins integer leaf ids per row; score
    mode is bulk native "%g" (byte-identical to Python's "%g" for
    finite doubles) with the Python loop as the no-toolchain fallback.

    res: [N, T] leaf indices when leaf, else [K, N] scores.  0-row
    input returns b"" (the serving 0-row contract; cli blocks are never
    empty)."""
    if leaf:
        if res.shape[0] == 0:
            return b""
        return ("\n".join(
            "\t".join(str(int(v)) for v in row) for row in res)
            + "\n").encode()
    if res.shape[1] == 0:
        return b""
    from . import native
    rows = np.ascontiguousarray(res.T)               # [N, K]
    blob = native.format_g(rows)
    if blob is not None:
        return blob
    return ("\n".join(
        "\t".join("%g" % v for v in res[:, i])
        for i in range(res.shape[1])) + "\n").encode()


class _LightModel:
    """Model-text header + trees, parsed without models.gbdt (which
    imports jax).  The actual reader is models.tree.parse_model_text,
    shared with GBDT.load_model_from_string so the two paths cannot
    drift; sigmoid defaults like cli.init_predict's prediction-only
    GBDT (no binary objective configured -> -1)."""

    def __init__(self, model_str: str):
        header, trees = parse_model_text(model_str)
        self.num_class = header["num_class"]
        self.label_idx = header["label_index"]
        self.max_feature_idx = header["max_feature_idx"]
        self.sigmoid = (header["sigmoid"]
                        if header["sigmoid"] is not None else -1.0)
        self.trees: List[Tree] = trees

    def used_trees(self, num_model_predict: int) -> List[Tree]:
        """cli.init_predict's set_num_used_model call, resolved
        (models.tree.select_used_trees, shared with serving)."""
        from .models.tree import select_used_trees
        return select_used_trees(self.trees, self.num_class,
                                 num_model_predict)


def _read_chunks(path: str, has_header: bool):
    """Yield line-aligned byte chunks of the input file, skipping the
    first NON-blank line when has_header (matching io/dataset
    _skip_header and cli.predict's blocks()).

    The header skip runs BEFORE chunking starts and carries the partial
    header across reads explicitly, so a header line longer than
    CHUNK_BYTES (or preceded by blank lines) can never truncate data:
    the old interleaved skip left that guarantee implicit in the
    chunk-boundary handling (test_predict_fast pins the regression)."""
    with open(path, "rb") as f:
        carry = b""
        skip = has_header
        while skip:
            block = f.read(CHUNK_BYTES)
            if not block:
                return  # whole file is the header (or blanks): no rows
            carry += block
            pos = 0
            while True:
                eol = carry.find(b"\n", pos)
                if eol < 0:
                    # header (or leading blanks) continue into the next
                    # read: keep the partial line as the carry
                    carry = carry[pos:]
                    break
                if carry[pos:eol].strip(b"\r"):
                    carry = carry[eol + 1:]   # past the header line
                    skip = False
                    break
                pos = eol + 1                 # blank line: keep looking
        while True:
            block = f.read(CHUNK_BYTES)
            if not block:
                break
            buf = carry + block
            cut = buf.rfind(b"\n")
            if cut < 0:
                carry = buf
                continue
            chunk, carry = buf[:cut + 1], buf[cut + 1:]
            yield chunk
        if carry.strip(b"\r\n"):
            yield carry


# bytes per _sniff_format read; the sniff keeps reading past this until
# it has complete data lines (a header alone can exceed one read)
SNIFF_BYTES = 1 << 20


def _sniff_format(path: str, has_header: bool) -> Tuple[str, str]:
    """(fmt, sep) from the first data lines (Parser::CreateParser role),
    via the shared complete-lines sniff (io/parser.sniff_format — also
    the serving request sniff, so the two paths cannot drift)."""
    with open(path, "rb") as f:
        return sniff_format(lambda: f.read(SNIFF_BYTES), has_header)


@contract.jax_free
@contract.rank_uniform
def try_fast_predict(cfg: Config) -> bool:
    """Run task=predict through the native path; False -> caller falls
    back to the default JAX path (native toolchain unavailable).

    @contract.jax_free: the whole point of this path is the reference
    binary's process-startup profile — graftcheck GC002 verifies
    nothing it transitively calls imports jax, even lazily.
    @contract.rank_uniform: the decision derives from config (task,
    modes, native-engine availability) and the shared input model
    artifact — identical on every rank of a fleet, so graftsync's
    GC009 accepts the CLI's fast-path early exit ahead of the
    jax-path fallback (whose booster init allgathers under
    multi-host)."""
    from . import native
    if native.get_lib() is None:
        return False
    if not cfg.input_model:
        log.fatal("Need a model file for prediction (input_model)")
    log.info("Started prediction...")
    with open(cfg.input_model) as f:
        model = _LightModel(f.read())
    trees = model.used_trees(cfg.num_model_predict)
    forest = native.ForestSpec(trees, model.num_class, model.sigmoid)
    mode = (2 if cfg.is_predict_leaf_index
            else 1 if cfg.is_predict_raw_score else 0)
    num_feat = model.max_feature_idx + 1
    fmt, sep = _sniff_format(cfg.data, cfg.has_header)

    # pull the first chunk BEFORE opening (truncating) the output file so
    # an empty input fatals without clobbering a previous result (same
    # no-clobber contract as cli.predict)
    gen = _read_chunks(cfg.data, cfg.has_header)
    first: Optional[bytes] = None
    row0 = 0
    for chunk in gen:
        got = native.predict_chunk(chunk, fmt, sep, model.label_idx,
                                   num_feat, forest, mode, row0=row0)
        if got is None:
            return False  # native refused (capacity edge): slow path
        blob, rows = got
        row0 += rows
        if blob:
            first = blob
            break
    if first is None:
        log.fatal("Data file %s is empty" % cfg.data)
    from .resilience.atomic import atomic_writer
    with atomic_writer(cfg.output_result) as out_f:
        out_f.write(first)
        for chunk in gen:
            got = native.predict_chunk(chunk, fmt, sep, model.label_idx,
                                       num_feat, forest, mode, row0=row0)
            if got is None:
                # mid-file native refusal: finishing through two paths
                # would interleave buffers — fatal rather than corrupt
                log.fatal("Native predict failed mid-file on %s" % cfg.data)
            blob, rows = got
            row0 += rows
            out_f.write(blob)
    log.info("Finished prediction, results saved to %s" % cfg.output_result)
    return True
