"""Out-of-core text -> binned-shard ingestion under a memory budget.

The reference solves TB-scale loading with two-round streaming
(DatasetLoader sample-based `CostructFromSampleData` + a second pass
that writes bins directly, dataset_loader.cpp:170-185).  This module
is that design taken out-of-core: instead of quantizing into one
host-resident [F, N] matrix, the second pass writes fixed-row-count
column-oriented shard files, so neither the text NOR the binned matrix
ever lives whole in host memory.

Passes (both streaming, chunk_bytes at a time):

  1. sample pass — count rows, reservoir-sample
     `bin_construct_sample_cnt` lines on the seeded mt19937
     (io/dataset.reservoir_offer, the EXACT stream `_load_two_round`
     replays, so ingest bins == two-round text bins bit-for-bit), find
     bins via io/binning.find_bin (or a caller-supplied hook wrapping
     find_bins_distributed for multi-rank ingest).  Writes `bins.npz`
     (mapper pack) + `ingest_plan.json`.
  2. bin pass — N parallel parse workers (multiprocessing, reusing
     io/parser) quantize chunks straight to uint8/16 columns; the
     parent assembles fixed-row-count shards and commits each through
     resilience/atomic (sha-footered, crash-safe), with the
     `ingest.shard_write` faultpoint ahead of every commit.  The
     manifest.json commit (written LAST) marks completion.

Resume: a killed ingest leaves plan + bins.npz + a prefix of valid
shards.  The next run fingerprint-checks the plan, deep-verifies the
shard prefix, and re-streams the source skipping already-binned rows
(an IO-only line scan — no re-parse, no re-bin) before continuing at
the first missing shard.  The result is byte-identical to an
uninterrupted ingest (chaos-tested).

Memory budget (`ingest_memory_budget_mb`): bounds the chunk size, the
in-flight worker results and the shard assembly buffer.  O(N) scalars
(labels; the reservoir sample) are outside the per-chunk budget but
small: ~4 bytes/row and `bin_construct_sample_cnt` lines.
"""

from __future__ import annotations

__jax_free__ = True

import os
import sys
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from ..config import Config
from ..io.binning import BinMapper, pack_bin_mappers
from ..io.dataset import (_chunk_line_spans, _load_sidecar,
                          _scan_libsvm_max_idx, _skip_header,
                          _stream_line_chunks, reservoir_offer,
                          resolve_sample_schema)
from ..io.parser import detect_format, parse_file_bytes
from ..resilience.atomic import write_npz
from ..resilience.faults import faultpoint
from ..utils import log
from ..utils.mt19937 import Mt19937Random
from .manifest import (BINS_NAME, MANIFEST_NAME, PLAN_NAME, Manifest,
                       ManifestError, config_fingerprint,
                       fingerprint_diff, load_manifest, save_manifest,
                       shard_meta_name, shard_name, source_fingerprint)
from .shards import shard_is_valid, write_shard, write_shard_meta

#: type of the optional bin-finding hook: (sample_used_cols [S, U] f64,
#: total_sample_cnt) -> List[BinMapper] for the used columns, in order.
#: Multi-rank ingests pass a wrapper over io/binning.
#: find_bins_distributed so every rank lands identical mappers.
FindBinsFn = Callable[[np.ndarray, int], List[BinMapper]]


def source_list(data_spec: str) -> List[str]:
    """data= value -> ordered source file list (comma-separated for a
    sharded file set); every entry must exist."""
    out = [s.strip() for s in data_spec.split(",") if s.strip()]
    if not out:
        log.fatal("task=ingest needs data=<file>[,<file>...]")
    for p in out:
        if not os.path.isfile(p):
            log.fatal("Ingest source %s does not exist" % p)
    return out


# ---------------------------------------------------------------------------
# budget plan
# ---------------------------------------------------------------------------

#: smallest chunk the pipeline will use — below this the per-chunk
#: python/IPC overhead dominates the parse itself
_CHUNK_FLOOR = 1 << 18


def _auto_workers(config: Config) -> int:
    """Parse worker count.  Explicit ingest_workers is operator-owned;
    auto additionally respects the memory budget — every in-flight
    chunk costs ~6x its bytes, so a tight budget caps the fan-out
    rather than silently overrunning (the budget is HARD)."""
    if config.ingest_workers > 0:
        return config.ingest_workers
    budget = max(int(config.ingest_memory_budget_mb), 8) << 20
    by_budget = (budget // 2) // (6 * _CHUNK_FLOOR) - 2
    return max(1, min(4, os.cpu_count() or 1, by_budget))


def _plan_chunk_bytes(config: Config, workers: int) -> int:
    """Per-chunk byte size: each in-flight chunk costs ~6x its size
    (raw bytes + the parsed f64 row block + the binned columns) and up
    to workers + 2 chunks are in flight, so budget/2 bounds the parse
    pipeline and budget/4 the shard buffer (below)."""
    budget = max(int(config.ingest_memory_budget_mb), 8) << 20
    per = (budget // 2) // (6 * (workers + 2))
    return int(min(max(per, _CHUNK_FLOOR), 32 << 20))


def _plan_shard_rows(config: Config, num_features: int,
                     itemsize: int = 1) -> int:
    """Rows per shard: the [F, shard_rows] assembly buffer must fit in
    budget/4 (one shard is also the training-side feeding window).
    `itemsize` keeps uint16 bins honest against the same bound."""
    if config.ingest_shard_rows > 0:
        return config.ingest_shard_rows
    budget = max(int(config.ingest_memory_budget_mb), 8) << 20
    rows = (budget // 4) // max(num_features * itemsize, 1)
    return int(min(max(rows, 4096), 1 << 23))


# ---------------------------------------------------------------------------
# pass 1: sample
# ---------------------------------------------------------------------------

class _Schema:
    """Resolved file schema + bin mappers (the sample-pass product)."""

    def __init__(self) -> None:
        self.names: List[str] = []
        self.fmt: str = "tsv"
        self.label_idx: int = 0
        self.ncols: int = 0            # feature columns (label removed)
        self.weight_idx: int = -1      # shifted feature-space index
        self.group_idx: int = -1
        self.bin_mappers: List[BinMapper] = []
        self.used_feature_map: np.ndarray = np.zeros(0, np.int32)
        self.real_feature_index: np.ndarray = np.zeros(0, np.int32)
        self.n_total: int = 0
        self.dtype: str = "uint8"


def _sample_pass(sources: Sequence[str], config: Config,
                 chunk_bytes: int) -> Tuple[List[str], Optional[str],
                                            bytes, int, List[bytes],
                                            int]:
    """Streaming round 1 over the source list: row count + reservoir
    sample (bit-exact `_load_two_round` stream) + libsvm width scan."""
    target = max(1, config.bin_construct_sample_cnt)
    rng = Mt19937Random(config.data_random_seed)
    kept: List[bytes] = []
    seen = 0
    n_total = 0
    fmt: Optional[str] = None
    libsvm_max = -1
    first_line = b""
    names: Optional[List[str]] = None
    for path in sources:
        with open(path, "rb") as f:
            nm = _skip_header(f, config)
            if names is None:
                names = nm
            for chunk in _stream_line_chunks(f, chunk_bytes):
                starts, lens = _chunk_line_spans(chunk)
                k = len(starts)
                if k == 0:
                    continue
                if fmt is None:
                    l2 = [bytes(chunk[int(starts[t]):
                                      int(starts[t] + lens[t])])
                          for t in range(min(2, k))]
                    first_line = l2[0]
                    fmt = detect_format([ln.decode("utf-8", "replace")
                                         for ln in l2])
                if fmt == "libsvm":
                    libsvm_max = max(libsvm_max,
                                     _scan_libsvm_max_idx(chunk))
                n_total += k
                seen = reservoir_offer(kept, rng, target, seen, chunk,
                                       starts, lens)
    if n_total == 0:
        log.fatal("Data file %s is empty" % ",".join(sources))
    return names or [], fmt, first_line, libsvm_max, kept, n_total


def _resolve_schema(names: List[str], fmt: Optional[str],
                    first_line: bytes, libsvm_max: int,
                    kept: List[bytes], n_total: int, config: Config,
                    find_bins_fn: Optional[FindBinsFn]) -> _Schema:
    """Schema + mappers from the reservoir sample, via the SHARED
    io/dataset.resolve_sample_schema — the ingest writer and the
    two-round text loader resolve columns with the same code, so their
    bins-parity contract cannot drift."""
    rs = resolve_sample_schema(kept, names, fmt, first_line, libsvm_max,
                               config, find_bins_hook=find_bins_fn,
                               what="ingest sources")
    s = _Schema()
    s.n_total = n_total
    s.names = rs.names
    s.fmt = rs.fmt
    s.label_idx = rs.label_idx
    s.ncols = rs.ncols
    s.weight_idx = rs.weight_idx
    s.group_idx = rs.group_idx
    s.used_feature_map = rs.used_feature_map
    s.bin_mappers = rs.bin_mappers
    s.real_feature_index = rs.real_feature_index
    s.dtype = ("uint8"
               if max(m.num_bin for m in rs.bin_mappers) <= 256
               else "uint16")
    return s


# ---------------------------------------------------------------------------
# pass 2: parallel parse + quantize workers
# ---------------------------------------------------------------------------

#: worker-process state installed by _init_worker (multiprocessing
#: initializer; also used inline when ingest_workers resolves to 1)
_W: dict = {}


def _init_worker(packed: np.ndarray, real_index: np.ndarray,
                 label_idx: int, fmt: str, ncols: int, weight_idx: int,
                 group_idx: int, dtype: str) -> None:
    from ..io.binning import unpack_bin_mappers
    _W.clear()
    _W.update(mappers=unpack_bin_mappers(packed),
              real_index=np.asarray(real_index, dtype=np.int64),
              label_idx=label_idx, fmt=fmt, ncols=ncols,
              weight_idx=weight_idx, group_idx=group_idx,
              dtype=np.dtype(dtype))


def _bin_chunk_task(raw: bytes) -> Tuple[np.ndarray, np.ndarray,
                                         Optional[np.ndarray],
                                         Optional[np.ndarray]]:
    """One chunk: parse (io/parser — reference Atof semantics) and
    quantize (BinMapper.value_to_bin) -> ([F, k] bins, [k] label,
    weights, qid).  Mirrors `_load_two_round` round 2's fallback path
    exactly, so shard bytes match the in-memory loader's bins."""
    g = _W
    chunk = b"\n".join(ln for ln in raw.split(b"\n") if ln) + b"\n"
    f_cnt = len(g["mappers"])
    if chunk == b"\n":
        return (np.zeros((f_cnt, 0), g["dtype"]),
                np.zeros(0, np.float32), None, None)
    clabel, cfeats, _ = parse_file_bytes(chunk, g["label_idx"],
                                         g["fmt"])
    ncols = g["ncols"]
    if cfeats.shape[1] < ncols:
        cfeats = np.pad(cfeats, ((0, 0), (0, ncols - cfeats.shape[1])))
    elif cfeats.shape[1] > ncols:
        cfeats = cfeats[:, :ncols]
    k = len(clabel)
    bins = np.empty((f_cnt, k), dtype=g["dtype"])
    for inner, real in enumerate(g["real_index"]):
        bins[inner] = g["mappers"][inner].value_to_bin(
            cfeats[:, real]).astype(g["dtype"])
    w = (cfeats[:, g["weight_idx"]].astype(np.float32)
         if g["weight_idx"] >= 0 else None)
    q = (cfeats[:, g["group_idx"]].astype(np.int64)
         if g["group_idx"] >= 0 else None)
    return bins, clabel.astype(np.float32), w, q


def _make_pool(workers: int, initargs: tuple):
    """multiprocessing pool for the parse workers.  `fork` shares the
    parent's pages (cheap); once jax is loaded in this process its
    runtime threads make fork unsafe, so fall back to `spawn` (workers
    re-import only the jax-free ingest closure)."""
    import multiprocessing

    method = "fork"
    if "jax" in sys.modules or "fork" not in \
            multiprocessing.get_all_start_methods():
        method = "spawn"
    ctx = multiprocessing.get_context(method)
    return ctx.Pool(workers, initializer=_init_worker,
                    initargs=initargs)


class _ShardAssembler:
    """Order-preserving assembly of parsed chunks into fixed-row-count
    shards, committed through the atomic writer with the
    `ingest.shard_write` faultpoint ahead of every commit."""

    def __init__(self, out_dir: str, plan: Manifest, schema: _Schema,
                 first_shard: int,
                 weights_sidecar: Optional[np.ndarray]):
        self.out = out_dir
        self.plan = plan
        self.schema = schema
        f_cnt = plan.num_features
        rows = plan.shard_rows
        self.buf = np.zeros((f_cnt, rows), dtype=np.dtype(plan.dtype))
        self.lab = np.zeros(rows, dtype=np.float32)
        self.wcol = (np.zeros(rows, dtype=np.float32)
                     if schema.weight_idx >= 0 else None)
        self.qid = (np.zeros(rows, dtype=np.int64)
                    if schema.group_idx >= 0 else None)
        self.shard = first_shard
        self.fill = 0
        self.row0 = plan.shard_row0(first_shard)   # global row counter
        self.wside = weights_sidecar

    def consume(self, result) -> None:
        bins, label, w, q = result
        k = len(label)
        o = 0
        while o < k:
            cap = self.plan.shard_row_counts[self.shard]
            take = min(cap - self.fill, k - o)
            self.buf[:, self.fill:self.fill + take] = bins[:, o:o + take]
            self.lab[self.fill:self.fill + take] = label[o:o + take]
            if self.wcol is not None and w is not None:
                self.wcol[self.fill:self.fill + take] = w[o:o + take]
            if self.qid is not None and q is not None:
                self.qid[self.fill:self.fill + take] = q[o:o + take]
            self.fill += take
            o += take
            if self.fill == cap:
                self._flush(cap)

    def _flush(self, used: int) -> None:
        i = self.shard
        # the chaos seam: a SIGKILL here (or inside the writes — they
        # are atomic) loses at most THIS shard; resume re-bins it
        faultpoint("ingest.shard_write")
        write_shard(os.path.join(self.out, shard_name(i)),
                    self.buf[:, :used])
        w = None
        if self.plan.has_weights:
            if self.wside is not None:
                w = np.asarray(self.wside[self.row0:self.row0 + used],
                               dtype=np.float32)
            elif self.wcol is not None:
                w = self.wcol[:used]
        write_shard_meta(os.path.join(self.out, shard_meta_name(i)),
                         self.lab[:used], w,
                         self.qid[:used] if self.qid is not None
                         else None)
        self.row0 += used
        self.shard += 1
        self.fill = 0

    def finish(self) -> None:
        if self.fill:
            assert self.fill == self.plan.shard_row_counts[self.shard], \
                "shard %d assembled %d rows, plan says %d" \
                % (self.shard, self.fill,
                   self.plan.shard_row_counts[self.shard])
            self._flush(self.fill)
        assert self.shard == self.plan.num_shards, \
            "assembled %d shards, plan says %d" \
            % (self.shard, self.plan.num_shards)


def _chunks_skipping(sources: Sequence[str], config: Config,
                     chunk_bytes: int, skip_rows: int):
    """Stream line chunks across the source list, skipping the first
    `skip_rows` data rows with an IO-only line scan (resume: rows
    already committed to valid shards are never re-parsed)."""
    remaining = skip_rows
    for path in sources:
        with open(path, "rb") as f:
            _skip_header(f, config)
            for chunk in _stream_line_chunks(f, chunk_bytes):
                if remaining > 0:
                    starts, lens = _chunk_line_spans(chunk)
                    k = len(starts)
                    if k <= remaining:
                        remaining -= k
                        continue
                    chunk = chunk[int(starts[remaining]):]
                    remaining = 0
                yield chunk


def _bin_pass(sources: Sequence[str], config: Config, schema: _Schema,
              plan: Manifest, out_dir: str, first_shard: int,
              chunk_bytes: int, workers: int) -> None:
    wside = None
    if plan.has_weights and len(sources) == 1:
        w = _load_sidecar(sources[0] + ".weight")
        if w is not None:
            if len(w) != plan.num_rows:
                # Metadata::LoadWeights' rule (metadata.cpp): a
                # mis-sized sidecar must fatal, not write shards whose
                # meta rows disagree with their weight payloads
                log.fatal("Weights file %s.weight has %d values for "
                          "%d data rows" % (sources[0], len(w),
                                            plan.num_rows))
            wside = w.astype(np.float32)
    asm = _ShardAssembler(out_dir, plan, schema, first_shard, wside)
    initargs = (pack_bin_mappers(schema.bin_mappers, config.max_bin),
                schema.real_feature_index, schema.label_idx, schema.fmt,
                schema.ncols, schema.weight_idx, schema.group_idx,
                plan.dtype)
    gen = _chunks_skipping(sources, config, chunk_bytes,
                           plan.shard_row0(first_shard))
    if workers <= 1:
        _init_worker(*initargs)
        for chunk in gen:
            asm.consume(_bin_chunk_task(bytes(chunk)))
    else:
        with _make_pool(workers, initargs) as pool:
            pending: deque = deque()
            for chunk in gen:
                pending.append(
                    pool.apply_async(_bin_chunk_task, (bytes(chunk),)))
                # bounded in-flight window: Pool.imap would drain the
                # generator (the whole FILE) into its task queue
                while len(pending) >= workers + 2:
                    asm.consume(pending.popleft().get())
            while pending:
                asm.consume(pending.popleft().get())
    asm.finish()


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

_INGEST_FILES = (MANIFEST_NAME, PLAN_NAME, BINS_NAME)


def _wipe_ingest_dir(out_dir: str) -> None:
    """Remove every ingest artifact (stale manifest/plan/shards) ahead
    of a fresh ingest — partial leftovers must never mix generations."""
    try:
        names = os.listdir(out_dir)
    except OSError:
        return
    for name in names:
        if (name in _INGEST_FILES or name.startswith("shard_")
                or (name.startswith("rank_r")
                    and name.endswith(".rows.npz"))):
            try:
                os.remove(os.path.join(out_dir, name))
            except OSError:
                pass


def _manifest_reuse_diff(m: Manifest, cfg_fp: str, src_fp: str,
                         sources: Sequence[str]) -> str:
    """Empty string when the existing manifest/plan matches this run,
    else a human-readable reason naming the moved keys (config drift,
    source size/mtime drift, a different source list)."""
    if list(m.sources) != [os.path.abspath(s) for s in sources]:
        return ("source list: manifest %s vs run %s"
                % (",".join(m.sources), ",".join(sources)))
    parts = []
    if m.config_fp != cfg_fp:
        parts.append("config drift: "
                     + fingerprint_diff(m.config_fp, cfg_fp))
    if m.source_fp != src_fp:
        parts.append("source drift: "
                     + fingerprint_diff(m.source_fp, src_fp))
    return "; ".join(parts)


def _valid_shard_prefix(out_dir: str, plan: Manifest) -> int:
    """Length of the leading run of deep-verified shards (sha256 over
    every payload byte: resume must not trust externally damaged
    files).  Files past the prefix are removed."""
    k = 0
    while k < plan.num_shards and shard_is_valid(out_dir, plan, k,
                                                 deep=True):
        k += 1
    for i in range(k, plan.num_shards):
        for name in (shard_name(i), shard_meta_name(i)):
            try:
                os.remove(os.path.join(out_dir, name))
            except OSError:
                pass
    return k


def _shard_counts(n_total: int, shard_rows: int) -> List[int]:
    full, tail = divmod(n_total, shard_rows)
    return [shard_rows] * full + ([tail] if tail else [])


def ingest(sources: Sequence[str], out_dir: str, config: Config,
           find_bins_fn: Optional[FindBinsFn] = None) -> Manifest:
    """Ingest `sources` into `out_dir` (idempotent + resumable).

    - A COMPLETE matching manifest: reused as-is (fast stat probe).
    - A manifest/plan whose config or source fingerprint moved: warned
      with the moved keys, wiped, re-ingested.
    - A plan with a valid shard prefix (killed ingest): resumed at the
      first missing shard.
    """
    sources = [os.path.abspath(s) for s in sources]
    for p in sources:
        if not os.path.isfile(p):
            log.fatal("Ingest source %s does not exist" % p)
    if len(sources) > 1:
        for side in (".weight", ".query", ".init"):
            if any(os.path.isfile(p + side) for p in sources):
                log.warning("Ignoring %s sidecars: metadata sidecars "
                            "are honored for single-file ingests only"
                            % side)
    os.makedirs(out_dir, exist_ok=True)
    cfg_fp = config_fingerprint(config)
    src_fp = source_fingerprint(sources)

    try:
        m = load_manifest(out_dir)
    except ManifestError as ex:
        log.warning("Ignoring unreadable manifest under %s (%s)"
                    % (out_dir, ex))
        _wipe_ingest_dir(out_dir)   # orphaned shards must not mix
        m = None
    if m is not None:
        from ..resilience.atomic import verify_file
        why = _manifest_reuse_diff(m, cfg_fp, src_fp, sources)
        if not why and verify_file(
                os.path.join(out_dir, BINS_NAME)) != "ok":
            why = "missing/corrupt bins.npz mapper pack"
        if not why and all(shard_is_valid(out_dir, m, i)
                           for i in range(m.num_shards)):
            log.info("Reusing ingest manifest %s (%d shards, %d rows)"
                     % (out_dir, m.num_shards, m.num_rows))
            return m
        log.warning("Re-ingesting %s: %s" % (
            out_dir, why or "missing/incomplete shard files"))
        _wipe_ingest_dir(out_dir)

    workers = _auto_workers(config)
    chunk_bytes = _plan_chunk_bytes(config, workers)

    plan = None
    try:
        plan = load_manifest(out_dir, PLAN_NAME)
    except ManifestError:
        plan = None
    first_shard = 0
    schema: Optional[_Schema] = None
    if plan is not None:
        why = _manifest_reuse_diff(plan, cfg_fp, src_fp, sources)
        if why:
            log.warning("Ignoring stale ingest plan under %s: %s"
                        % (out_dir, why))
            _wipe_ingest_dir(out_dir)
            plan = None
        else:
            schema = _schema_from_plan(out_dir, plan, config)
            if schema is None:
                _wipe_ingest_dir(out_dir)
                plan = None
            else:
                first_shard = _valid_shard_prefix(out_dir, plan)
                log.info("Resuming killed ingest under %s at shard "
                         "%d/%d" % (out_dir, first_shard,
                                    plan.num_shards))

    if plan is None:
        names, fmt, first_line, libsvm_max, kept, n_total = \
            _sample_pass(sources, config, chunk_bytes)
        schema = _resolve_schema(names, fmt, first_line, libsvm_max,
                                 kept, n_total, config, find_bins_fn)
        del kept
        shard_rows = _plan_shard_rows(
            config, len(schema.bin_mappers),
            np.dtype(schema.dtype).itemsize)
        qcounts = None
        if len(sources) == 1:
            qraw = _load_sidecar(sources[0] + ".query")
            if qraw is not None:
                qcounts = qraw.astype(np.int64)
                if int(qcounts.sum()) != n_total:
                    log.fatal("Query sizes (%d) do not sum to data "
                              "count (%d)" % (int(qcounts.sum()),
                                              n_total))
            if os.path.isfile(sources[0] + ".init"):
                log.warning("%s.init: init-score sidecars apply at "
                            "TRAINING time (they are not baked into "
                            "the shards)" % sources[0])
        has_weights = (schema.weight_idx >= 0
                       or (len(sources) == 1
                           and os.path.isfile(sources[0] + ".weight")))
        plan = Manifest(
            num_rows=n_total, num_features=len(schema.bin_mappers),
            num_total_features=schema.ncols,
            label_idx=schema.label_idx, fmt=schema.fmt,
            dtype=schema.dtype, shard_rows=shard_rows,
            shard_row_counts=_shard_counts(n_total, shard_rows),
            feature_names=list(schema.names), has_weights=has_weights,
            has_query=(qcounts is not None or schema.group_idx >= 0),
            config_fp=cfg_fp, source_fp=src_fp,
            sources=list(sources), complete=False)
        pack = {"packed_mappers": pack_bin_mappers(schema.bin_mappers,
                                                   config.max_bin),
                "used_feature_map": schema.used_feature_map,
                "real_feature_index": schema.real_feature_index,
                "weight_idx": np.int64(schema.weight_idx),
                "group_idx": np.int64(schema.group_idx)}
        if qcounts is not None:
            pack["qcounts"] = qcounts
        write_npz(os.path.join(out_dir, BINS_NAME), pack)
        save_manifest(out_dir, plan, PLAN_NAME)

    _bin_pass(sources, config, schema, plan, out_dir, first_shard,
              chunk_bytes, workers)
    plan.complete = True
    save_manifest(out_dir, plan, MANIFEST_NAME)
    try:
        os.remove(os.path.join(out_dir, PLAN_NAME))
    except OSError:
        pass
    log.info("Ingested %d rows x %d features into %s (%d shards, "
             "%s bins)" % (plan.num_rows, plan.num_features, out_dir,
                           plan.num_shards, plan.dtype))
    return plan


def _schema_from_plan(out_dir: str, plan: Manifest,
                      config: Config) -> Optional[_Schema]:
    """Rebuild the resolved schema of a killed ingest from plan +
    bins.npz (no sample-pass replay).  None when the pack is missing
    or corrupt — the caller falls back to a fresh ingest."""
    from ..resilience.atomic import IntegrityError, read_npz
    from ..io.binning import unpack_bin_mappers
    try:
        with read_npz(os.path.join(out_dir, BINS_NAME)) as z:
            s = _Schema()
            s.bin_mappers = unpack_bin_mappers(
                np.asarray(z["packed_mappers"]))
            s.used_feature_map = np.asarray(z["used_feature_map"],
                                            dtype=np.int32)
            s.real_feature_index = np.asarray(z["real_feature_index"],
                                              dtype=np.int32)
            s.weight_idx = int(z["weight_idx"])
            s.group_idx = int(z["group_idx"])
    except (OSError, IntegrityError, KeyError, ValueError) as ex:
        log.warning("Ingest plan under %s has no usable bins.npz "
                    "(%s); restarting the sample pass" % (out_dir, ex))
        return None
    s.names = list(plan.feature_names)
    s.fmt = plan.fmt
    s.label_idx = plan.label_idx
    s.ncols = plan.num_total_features
    s.n_total = plan.num_rows
    s.dtype = plan.dtype
    return s


def run_ingest_cli(config: Config) -> None:
    """task=ingest entry: data=<file>[,<file>...] ingest_dir=<dir>."""
    sources = source_list(config.data)
    out = config.ingest_dir or (sources[0] + ".shards")
    m = ingest(sources, out, config)
    log.info("Ingest complete: %s (%d rows, %d shards; train with "
             "data=%s)" % (out, m.num_rows, m.num_shards, out))


__all__ = ["ingest", "run_ingest_cli", "source_list", "FindBinsFn"]
