"""Synthetic Criteo-class row generator (ingest benchmarking/tests).

Criteo-1TB rows are a click label + 13 skewed numeric counters + 26
hashed categoricals.  This pipeline is numeric (categorical splits are
a ROADMAP item), so the generator emits the numeric shape of that
workload: a binary label, heavy-tailed integer counters, and dense
floats with a configurable zero rate (sparse-ish columns), as TSV or
LibSVM.  Generation tiles one deterministic block (content variety
only matters to bin finding, which samples anyway), so multi-GB files
write at IO speed with O(block) memory.

Not a parity path: rows are synthetic by definition (np.random is the
deliberate choice here; the parity-load-bearing ingest modules stay on
utils/mt19937)."""

from __future__ import annotations

__jax_free__ = True

import os
from typing import Optional

import numpy as np

from ..resilience.atomic import atomic_writer

#: Criteo-like numeric schema: 13 counters + 15 dense floats
N_COUNTERS = 13
N_DENSE = 15
NUM_FEATURES = N_COUNTERS + N_DENSE


def _block(rows: int, seed: int, zero_rate: float) -> np.ndarray:
    rng = np.random.RandomState(seed)
    counters = np.floor(
        rng.lognormal(mean=1.5, sigma=1.8,
                      size=(rows, N_COUNTERS))).astype(np.float64)
    dense = rng.randn(rows, N_DENSE)
    x = np.concatenate([counters, dense], axis=1)
    x[rng.rand(rows, NUM_FEATURES) < zero_rate] = 0.0
    logit = (0.8 * np.log1p(x[:, 0]) + 0.5 * x[:, N_COUNTERS]
             - 0.3 * x[:, N_COUNTERS + 1] - 1.0)
    y = (logit + rng.logistic(size=rows) > 0).astype(np.int64)
    return np.concatenate([y[:, None].astype(np.float64), x], axis=1)


def _format_block(block: np.ndarray, fmt: str) -> bytes:
    lines = []
    for row in block:
        label = "%d" % int(row[0])
        if fmt == "libsvm":
            toks = [label] + ["%d:%.6g" % (j, v)
                              for j, v in enumerate(row[1:]) if v != 0.0]
            lines.append(" ".join(toks))
        else:
            lines.append("\t".join([label] + ["%.6g" % v
                                              for v in row[1:]]))
    return ("\n".join(lines) + "\n").encode()


def generate(path: str, target_bytes: int = 0, rows: int = 0,
             fmt: str = "tsv", seed: int = 0, zero_rate: float = 0.25,
             block_rows: int = 20000) -> int:
    """Write a synthetic data file of at least `target_bytes` bytes (or
    exactly `rows` rows when given).  Returns the row count.  The write
    is atomic — a partial generation never masquerades as a complete
    benchmark input."""
    assert fmt in ("tsv", "libsvm"), fmt
    blocks = []
    for i in range(4):   # 4 distinct blocks tile with some variety
        blocks.append(_format_block(
            _block(block_rows, seed * 31 + i, zero_rate), fmt))
    written_rows = 0
    with atomic_writer(path, checksum=False) as f:
        if rows > 0:
            left = rows
            i = 0
            while left > 0:
                if left >= block_rows:
                    f.write(blocks[i % len(blocks)])
                    left -= block_rows
                else:
                    b = _format_block(
                        _block(left, seed * 31 + i % 4, zero_rate), fmt)
                    f.write(b)
                    left = 0
                i += 1
            written_rows = rows
        else:
            written = 0
            i = 0
            while written < target_bytes:
                b = blocks[i % len(blocks)]
                f.write(b)
                written += len(b)
                written_rows += block_rows
                i += 1
    return written_rows


def cached_file(cache_dir: str, target_bytes: int, fmt: str = "tsv",
                seed: int = 0) -> Optional[str]:
    """Benchmark convenience: generate-once-and-reuse by size under
    `cache_dir`."""
    os.makedirs(cache_dir, exist_ok=True)
    path = os.path.join(cache_dir, "synth_%s_%d.%s"
                        % (fmt, target_bytes,
                           "libsvm" if fmt == "libsvm" else "tsv"))
    if not (os.path.isfile(path)
            and os.path.getsize(path) >= target_bytes):
        generate(path, target_bytes=target_bytes, fmt=fmt, seed=seed)
    return path


__all__ = ["generate", "cached_file", "NUM_FEATURES"]
