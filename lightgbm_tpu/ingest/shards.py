"""mmap-backed shard files and the ShardedDataset they serve.

A shard file is one column-oriented block of the binned matrix:

    LGTSHRD1 | u32 version | u32 dtype (1=u8, 2=u16) | i64 F | i64 rows
    payload: F * rows bytes (times itemsize), feature-major C order
    40-byte sha256 integrity footer (resilience/atomic)

ShardedDataset satisfies the training-side `Dataset` interface while
keeping the bin matrix ON DISK: `iter_bin_windows()` yields one
bounded [F, rows] window per shard (an mmap view, or a copy of just
this rank's lottery-kept columns), and GBDT device_puts each window
without ever assembling the full matrix on the host.  Metadata
(labels, weights, query ids) is O(N) scalars and loads eagerly from
the per-shard sidecars.

Multi-rank (`tree_learner=data`, num_machines > 1): every rank replays
the reference's seeded row lottery over the manifest's global row
order (one NextInt(0, num_machines) draw per row, or per query — the
exact stream `io/dataset.py` replays for text files), so a rank reads
only its manifest slice: the kept columns of each shard.  The outcome
is cached in a `rank_rNofM.rows.npz` sidecar next to the manifest,
validated the same way the `.bin` cache sidecars are (seed,
granularity, config fingerprint) before reuse.
"""

from __future__ import annotations

__jax_free__ = True

import os
import queue
import threading
from typing import Iterator, List, Optional

import numpy as np

from ..config import Config
from ..io.binning import BinMapper, unpack_bin_mappers
from ..io.dataset import (Dataset, Metadata, _check_lottery_query_counts,
                          _load_sidecar)
from ..resilience.atomic import (IntegrityError, atomic_writer, read_npz,
                                 verify_file, write_npz)
from ..utils import log
from .manifest import (BINS_NAME, Manifest, config_fingerprint,
                       fingerprint_diff, load_manifest, manifest_dir,
                       shard_meta_name, shard_name, source_fingerprint)

SHARD_MAGIC = b"LGTSHRD1"
SHARD_HEADER_LEN = 32
_DTYPE_CODES = {"uint8": 1, "uint16": 2}

META_MAGIC = b"LGTSMET1"
_META_W = 1
_META_Q = 2


def write_shard_meta(path: str, label: np.ndarray,
                     weights: Optional[np.ndarray],
                     qid: Optional[np.ndarray]) -> None:
    """Per-shard label/weight/qid sidecar in a DETERMINISTIC flat
    binary layout (npz embeds zip timestamps, and a resumed ingest
    must reproduce a byte-identical shard directory):

        LGTSMET1 | u32 ver | u32 flags | i64 rows |
        label f32[rows] | weights f32[rows]? | qid i64[rows]? | footer
    """
    flags = (_META_W if weights is not None else 0) \
        | (_META_Q if qid is not None else 0)
    rows = len(label)
    with atomic_writer(path, checksum=True) as f:
        f.write(META_MAGIC + np.uint32(1).tobytes()
                + np.uint32(flags).tobytes() + np.int64(rows).tobytes())
        f.write(np.ascontiguousarray(label, dtype=np.float32).tobytes())
        if weights is not None:
            f.write(np.ascontiguousarray(weights,
                                         dtype=np.float32).tobytes())
        if qid is not None:
            f.write(np.ascontiguousarray(qid, dtype=np.int64).tobytes())


def read_shard_meta(path: str):
    """(label f32, weights f32 | None, qid i64 | None), checksum-
    verified (IntegrityError on damage)."""
    from ..resilience.atomic import read_verified
    payload = read_verified(path)
    if payload[:8] != META_MAGIC:
        raise IntegrityError("%s: not a shard meta file" % path)
    flags = int(np.frombuffer(payload, np.uint32, 1, 12)[0])
    rows = int(np.frombuffer(payload, np.int64, 1, 16)[0])
    o = 24
    label = np.frombuffer(payload, np.float32, rows, o).copy()
    o += 4 * rows
    weights = None
    if flags & _META_W:
        weights = np.frombuffer(payload, np.float32, rows, o).copy()
        o += 4 * rows
    qid = None
    if flags & _META_Q:
        qid = np.frombuffer(payload, np.int64, rows, o).copy()
    return label, weights, qid


def shard_file_size(num_features: int, rows: int, dtype: str) -> int:
    """Expected on-disk size of a complete shard (header + payload +
    integrity footer) — the cheap completeness probe."""
    from ..resilience.atomic import FOOTER_LEN
    return (SHARD_HEADER_LEN
            + num_features * rows * np.dtype(dtype).itemsize
            + FOOTER_LEN)


def write_shard(path: str, block: np.ndarray) -> None:
    """Durable shard write: header + feature-major payload, streamed
    through the hashing atomic writer (a SIGKILL at any byte leaves no
    file under the final name)."""
    f_cnt, rows = block.shape
    code = _DTYPE_CODES[str(block.dtype)]
    header = (SHARD_MAGIC
              + np.uint32(1).tobytes() + np.uint32(code).tobytes()
              + np.int64(f_cnt).tobytes() + np.int64(rows).tobytes())
    assert len(header) == SHARD_HEADER_LEN
    block = np.ascontiguousarray(block)
    with atomic_writer(path, checksum=True) as f:
        f.write(header)
        f.write(memoryview(block).cast("B"))


def open_shard(path: str, num_features: int, rows: int,
               dtype: str) -> np.ndarray:
    """mmap view [F, rows] of a shard's payload.  Header fields are
    validated against the manifest; payload bytes are verified only by
    the resume scan (hashing every shard on every open would re-read
    the whole dataset per training run)."""
    with open(path, "rb") as f:
        head = f.read(SHARD_HEADER_LEN)
    if len(head) != SHARD_HEADER_LEN or head[:8] != SHARD_MAGIC:
        raise IntegrityError("%s: not a shard file" % path)
    code = int(np.frombuffer(head, np.uint32, 1, 12)[0])
    f_cnt = int(np.frombuffer(head, np.int64, 1, 16)[0])
    r = int(np.frombuffer(head, np.int64, 1, 24)[0])
    if (code != _DTYPE_CODES[dtype] or f_cnt != num_features
            or r != rows):
        raise IntegrityError(
            "%s: header (F=%d rows=%d dtype=%d) does not match the "
            "manifest (F=%d rows=%d dtype=%s)"
            % (path, f_cnt, r, code, num_features, rows, dtype))
    return np.memmap(path, dtype=np.dtype(dtype), mode="r",
                     offset=SHARD_HEADER_LEN,
                     shape=(num_features, rows))


def shard_is_valid(dirpath: str, m: Manifest, index: int,
                   deep: bool = False) -> bool:
    """Completeness probe for shard `index`: expected size + readable
    meta sidecar; `deep` additionally streams the sha256 of the shard
    payload (the resume scan — external damage must not survive)."""
    p = os.path.join(dirpath, shard_name(index))
    rows = m.shard_row_counts[index]
    try:
        if os.path.getsize(p) != shard_file_size(m.num_features, rows,
                                                 m.dtype):
            return False
    except OSError:
        return False
    if deep and verify_file(p) != "ok":
        return False
    meta = os.path.join(dirpath, shard_meta_name(index))
    try:
        label, _, _ = read_shard_meta(meta)
        if len(label) != rows:
            return False
    except Exception:
        return False
    return True


# ---------------------------------------------------------------------------
# ShardedDataset
# ---------------------------------------------------------------------------

class ShardedDataset(Dataset):
    """A `Dataset` whose bin matrix lives in shard files.

    The training path feeds from `iter_bin_windows()` (one bounded
    window at a time); the `bins` property still materializes the full
    local matrix for the few legacy paths that need host bins (custom-
    gradient excursions, checkpoint restore with a row re-sort, query-
    granular layouts) — with a log line, because those paths forfeit
    the out-of-core property."""

    is_shard_backed = True

    def __init__(self, dirpath: str, manifest: Manifest,
                 bin_mappers: List[BinMapper],
                 used_feature_map: np.ndarray,
                 real_feature_index: np.ndarray,
                 metadata: Metadata, label_idx: int,
                 local_rows: Optional[np.ndarray],
                 shard_keeps: Optional[List[np.ndarray]]):
        # deliberately NOT the dataclass __init__: `bins` is a property
        self.dir = dirpath
        self.manifest = manifest
        self.bin_mappers = bin_mappers
        self.used_feature_map = used_feature_map
        self.real_feature_index = real_feature_index
        self.num_total_features = manifest.num_total_features
        self.feature_names = list(manifest.feature_names)
        self.metadata = metadata
        self.label_idx = label_idx
        self.local_rows = local_rows
        #: per-shard kept-column indices (None = every row kept)
        self._shard_keeps = shard_keeps
        self._n_local = (len(metadata.label))
        self._warned_materialize = False
        self._bins_cache: Optional[np.ndarray] = None

    # -- Dataset interface overrides (no bins attribute) ---------------
    @property
    def num_data(self) -> int:
        return self._n_local

    @property
    def num_features(self) -> int:
        return self.manifest.num_features

    @property
    def bin_dtype(self) -> np.dtype:
        return np.dtype(self.manifest.dtype)

    @property
    def bins(self) -> np.ndarray:
        """Materialized [F, n_local] matrix — legacy-path fallback ONLY
        (it exists so ordered-partition restores and general-path
        excursions still work); the fed training path never calls it.
        Cached after the first access: the out-of-core property is
        already forfeited then, and repeat accessors (general-path
        excursions re-place bins per excursion) must not pay a full
        shard-directory disk read each time."""
        if self._bins_cache is None:
            self._warned_materialize = True
            log.info("ShardedDataset: materializing the full [%d, %d] "
                     "bin matrix on the host (a non-streaming code "
                     "path asked for Dataset.bins; cached from here "
                     "on)" % (self.num_features, self._n_local))
            self._bins_cache = self.local_bins_matrix()
        return self._bins_cache

    # -- streaming access ----------------------------------------------
    def iter_bin_windows(self) -> Iterator[np.ndarray]:
        """Yield one [F, k] window per shard, in global row order:
        an mmap view when every row is kept, else a copy of just this
        rank's kept columns.  Peak host memory is one window."""
        m = self.manifest
        for i in range(m.num_shards):
            mm = open_shard(os.path.join(self.dir, shard_name(i)),
                            m.num_features, m.shard_row_counts[i],
                            m.dtype)
            if self._shard_keeps is None:
                yield mm
            else:
                idx = self._shard_keeps[i]
                if len(idx):
                    yield np.ascontiguousarray(mm[:, idx])
            del mm

    def local_bins_matrix(self) -> np.ndarray:
        """[F, n_local] host matrix of this rank's kept rows (the
        multi-host assembly block — 1/R of the data per rank).
        Deliberately synchronous: the consumer does no per-window work
        (append + one concatenate), so a prefetch thread here would
        only inflate the staged-window footprint on the very path
        sized against ingest_memory_budget_mb — overlap belongs to the
        per-window device_put feeds (models/gbdt.py)."""
        parts = [np.asarray(w) for w in self.iter_bin_windows()]
        if not parts:
            return np.zeros((self.num_features, 0),
                            dtype=self.bin_dtype)
        return np.ascontiguousarray(np.concatenate(parts, axis=1))


# ---------------------------------------------------------------------------
# IO/compute-overlapped window staging (round 16)
# ---------------------------------------------------------------------------

class _PrefetchDone:
    """Queue sentinel (a class, not object(), so type checks read well)."""


def prefetch_windows(windows: Iterator[np.ndarray],
                     depth: int) -> Iterator[np.ndarray]:
    """Bounded background staging of shard windows.

    A daemon thread runs the `windows` iterator — open_shard + the
    materializing copy, i.e. the disk page-in — and parks at most
    `depth` staged windows in a bounded queue, so the NEXT shard reads
    from disk while the consumer is still busy with the previous one
    (for the training feed: while the previous window's async
    device_put transfer is in flight).  Peak host memory is therefore
    2 + depth windows: `depth` queued, plus the one the producer has
    already materialized while blocked on a full queue, plus the one
    the consumer holds.  depth <= 0 degrades to the synchronous
    in-order iteration (the oracle: the consumer sees the IDENTICAL
    window sequence either way, so shard-fed models are byte-identical
    with overlap on or off).

    Exceptions raised by the iterator (a damaged shard, a vanished
    file) re-raise in the consumer at the position they occurred.  An
    abandoned consumer (generator closed early) releases the thread via
    the stop event — no orphaned producer blocks on a full queue.
    """
    if depth <= 0:
        for w in windows:
            yield np.ascontiguousarray(w)
        return

    q: "queue.Queue" = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def _put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _stage() -> None:
        try:
            for w in windows:
                if not _put(np.ascontiguousarray(w)):
                    return
            _put(_PrefetchDone)
        except BaseException as ex:  # noqa: BLE001 - re-raised consumer-side
            _put(ex)

    t = threading.Thread(target=_stage, name="lgbm-window-prefetch",
                         daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _PrefetchDone:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        t.join()


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def _load_bins_pack(dirpath: str):
    """(mappers, used_feature_map, real_index, qcounts-or-None) from
    the checksummed bins.npz pack."""
    with read_npz(os.path.join(dirpath, BINS_NAME)) as z:
        mappers = unpack_bin_mappers(np.asarray(z["packed_mappers"]))
        ufm = np.asarray(z["used_feature_map"], dtype=np.int32)
        real = np.asarray(z["real_feature_index"], dtype=np.int32)
        qcounts = (np.asarray(z["qcounts"], dtype=np.int64)
                   if "qcounts" in z.files else None)
    return mappers, ufm, real, qcounts


def _rank_sidecar_path(dirpath: str, rank: int, num_shards: int) -> str:
    return os.path.join(dirpath, "rank_r%dof%d.rows.npz"
                        % (rank, num_shards))


def _load_rank_sidecar(dirpath: str, m: Manifest, config: Config,
                       rank: int, num_shards: int,
                       want_query: bool) -> Optional[np.ndarray]:
    """Cached lottery outcome for this rank, or None when absent/stale.
    Stale = different seed, granularity, config fingerprint or row
    count — the same never-silently-reuse rule as _rank_cache_matches
    (a stale partition would desync the cluster's row sets)."""
    path = _rank_sidecar_path(dirpath, rank, num_shards)
    if not os.path.isfile(path):
        return None
    try:
        with read_npz(path) as z:
            if ("seed" not in z.files or "query_lottery" not in z.files
                    or "config_fp" not in z.files
                    or "n_global" not in z.files):
                return None
            if (int(z["seed"]) != int(config.data_random_seed)
                    or bool(int(z["query_lottery"])) != want_query
                    or int(z["n_global"]) != m.num_rows):
                return None
            fp = bytes(np.asarray(z["config_fp"]).tobytes()).decode(
                "utf-8", "replace")
            if fp != m.config_fp:
                return None
            return np.asarray(z["rows"], dtype=np.int64)
    except Exception:
        return None


def _lottery_keep(m: Manifest, qcounts: Optional[np.ndarray],
                  qid_all: Optional[np.ndarray], config: Config,
                  rank: int, num_shards: int) -> np.ndarray:
    """[num_rows] bool keep mask from the reference's seeded row
    lottery — row-granular, or query-granular when the manifest
    carries query structure (whole queries stay on one rank)."""
    from .. import native
    n = m.num_rows
    lot = native.ShardLottery(config.data_random_seed, num_shards,
                              rank, -1)
    heads = None
    if qcounts is not None:
        _check_lottery_query_counts(qcounts, m.sources[0] + ".query")
        heads = np.zeros(n, dtype=np.uint8)
        heads[np.concatenate([[0], np.cumsum(qcounts)[:-1]])
              .astype(np.int64)] = 1
    elif qid_all is not None:
        heads = np.empty(n, dtype=np.uint8)
        heads[0] = 1
        heads[1:] = (np.diff(qid_all) != 0).astype(np.uint8)
    keep, _ = lot.chunk(n, heads)
    if not keep.any():
        log.fatal("Rank %d's row-lottery shard of %s is empty "
                  "(%d rows over %d machines); use fewer machines "
                  "or pre-partitioned shard directories"
                  % (rank, m.sources[0], n, num_shards))
    return keep


def load_sharded_dataset(path: str, config: Config, rank: int = 0,
                         num_shards: int = 1) -> ShardedDataset:
    """Load an ingest directory as a training Dataset.

    The manifest's CONFIG fingerprint must match the run's (max_bin,
    column specs, seed, ... — manifest.FP_KEYS); on mismatch the
    loader re-ingests from the recorded sources when they still exist
    (warning naming the moved keys, the snapshot `resume_fp` pattern),
    and fatals naming them when they do not."""
    dirpath = manifest_dir(path)
    m = load_manifest(dirpath)
    if m is None:
        log.fatal("No manifest.json under %s (not an ingest directory, "
                  "or a killed ingest that never finished — re-run "
                  "task=ingest)" % dirpath)
    run_fp = config_fingerprint(config)
    why = None
    if m.config_fp != run_fp:
        why = ("config mismatch: "
               + fingerprint_diff(m.config_fp, run_fp))
    elif verify_file(os.path.join(dirpath, BINS_NAME)) != "ok":
        why = "missing/corrupt bins.npz mapper pack"
    elif all(os.path.isfile(s) for s in m.sources):
        # sources still present: an edited data file (or baked
        # .weight/.query sidecar) must not serve stale shards.  GONE
        # sources are fine — the manifest is a standalone artifact,
        # same rule as the .bin caches.
        run_src = source_fingerprint(m.sources)
        if m.source_fp != run_src:
            why = ("source drift: "
                   + fingerprint_diff(m.source_fp, run_src))
    if why is not None:
        if all(os.path.isfile(s) for s in m.sources):
            log.warning("Ingest manifest %s does not match this run "
                        "(%s): re-ingesting from %s"
                        % (dirpath, why, ",".join(m.sources)))
            from .writer import ingest
            m = ingest(m.sources, dirpath, config)
        else:
            log.fatal("Ingest manifest %s is unusable (%s) and its "
                      "sources are gone — cannot re-ingest"
                      % (dirpath, why))

    mappers, ufm, real, qcounts = _load_bins_pack(dirpath)
    if len(mappers) != m.num_features:
        log.fatal("bins.npz pack (%d mappers) does not match manifest "
                  "(%d features) under %s"
                  % (len(mappers), m.num_features, dirpath))

    # per-shard metadata sidecars -> global arrays (O(N) scalars)
    labels, weights, qids = [], [], []
    for i in range(m.num_shards):
        lab, w, q = read_shard_meta(
            os.path.join(dirpath, shard_meta_name(i)))
        labels.append(lab)
        if w is not None:
            weights.append(w)
        if q is not None:
            qids.append(q)
    label_all = (np.concatenate(labels) if labels
                 else np.zeros(0, np.float32))
    if len(label_all) != m.num_rows:
        log.fatal("Shard metadata rows (%d) do not match manifest "
                  "row count (%d) under %s"
                  % (len(label_all), m.num_rows, dirpath))
    weights_all = np.concatenate(weights) if weights else None
    qid_all = np.concatenate(qids) if qids else None

    sharding = num_shards > 1 and not config.is_pre_partition
    keep = local_rows = shard_keeps = None
    if sharding:
        want_query = qcounts is not None or qid_all is not None
        local_rows = _load_rank_sidecar(dirpath, m, config, rank,
                                        num_shards, want_query)
        if local_rows is not None:
            keep = np.zeros(m.num_rows, dtype=bool)
            keep[local_rows] = True
        else:
            keep = _lottery_keep(m, qcounts, qid_all, config, rank,
                                 num_shards)
            local_rows = np.nonzero(keep)[0].astype(np.int64)
            try:
                write_npz(_rank_sidecar_path(dirpath, rank, num_shards),
                          dict(rows=local_rows,
                               n_global=np.int64(m.num_rows),
                               seed=np.int64(config.data_random_seed),
                               query_lottery=np.int64(want_query),
                               config_fp=np.frombuffer(
                                   m.config_fp.encode("utf-8"),
                                   dtype=np.uint8).copy()))
            except OSError as ex:   # read-only shard dir: lottery is cheap
                log.warning("Could not cache rank partition sidecar "
                            "under %s: %s" % (dirpath, ex))
        shard_keeps = []
        row0 = 0
        for rows in m.shard_row_counts:
            shard_keeps.append(
                np.flatnonzero(keep[row0:row0 + rows]).astype(np.int64))
            row0 += rows

    # query boundaries (local rows): whole queries survive the lottery
    # together, so boundaries rebuild from kept heads / kept counts
    qb = None
    if qcounts is not None:
        if keep is not None:
            hpos = np.concatenate([[0], np.cumsum(qcounts)[:-1]]) \
                .astype(np.int64)
            qsel = keep[hpos]
            qb = np.concatenate(
                [[0], np.cumsum(qcounts[qsel])]).astype(np.int32)
        else:
            qb = np.concatenate(
                [[0], np.cumsum(qcounts)]).astype(np.int32)
    elif qid_all is not None:
        q = qid_all[keep] if keep is not None else qid_all
        if keep is not None:
            heads = np.empty(m.num_rows, dtype=bool)
            heads[0] = True
            heads[1:] = np.diff(qid_all) != 0
            kept_heads = heads[keep]
            qb = np.concatenate(
                [np.flatnonzero(kept_heads), [len(q)]]).astype(np.int32)
        else:
            change = np.nonzero(np.diff(q))[0] + 1
            qb = np.concatenate([[0], change, [len(q)]]).astype(np.int32)

    if keep is not None:
        label_all = label_all[keep]
        if weights_all is not None:
            weights_all = weights_all[keep]

    # .init sidecar of the ORIGINAL source still applies (it is row-
    # aligned with the global order the shards preserve)
    init = _load_sidecar(m.sources[0] + ".init") \
        if len(m.sources) == 1 else None
    if init is not None and keep is not None:
        if len(init) % m.num_rows:
            log.warning("Ignoring init score file: %d values do not "
                        "tile %d rows" % (len(init), m.num_rows))
            init = None
        else:
            kcls = len(init) // m.num_rows
            init = np.ascontiguousarray(
                np.asarray(init).reshape(kcls, m.num_rows)[:, keep]
            ).reshape(-1)

    metadata = Metadata(label=label_all, weights=weights_all,
                        query_boundaries=qb, init_score=init)
    metadata.finish_queries()
    ds = ShardedDataset(dirpath, m, mappers, ufm, real, metadata,
                        m.label_idx, local_rows, shard_keeps)
    log.info("Loaded ingest manifest %s: %d features, %d/%d rows "
             "(%d shards)" % (dirpath, ds.num_features, ds.num_data,
                              m.num_rows, m.num_shards))
    return ds


__all__ = ["SHARD_MAGIC", "SHARD_HEADER_LEN", "ShardedDataset",
           "write_shard", "open_shard", "shard_is_valid",
           "shard_file_size", "load_sharded_dataset",
           "prefetch_windows"]
