"""Ingest manifest: the durable index of a shard directory.

A completed ingest directory holds

    manifest.json            this file — the COMMIT point (written last)
    bins.npz                 bin-mapper pack + schema (checksummed npz)
    shard_00000.bins         column-oriented [F, rows] binned payloads
    shard_00000.meta.npz     per-shard label / weight / qid sidecars
    ...

The manifest records per-shard row ranges, the source fingerprint
(path, size, mtime) and the config fingerprint (every key that changes
bins or row semantics), mirroring the PR 7 snapshot `resume_fp`
pattern: fingerprints are readable k=v strings, not digests, so a
rejected manifest names WHICH keys moved.  A directory with bins.npz +
shards but no manifest.json is a killed ingest — the writer resumes it
at the first missing/corrupt shard.
"""

from __future__ import annotations

__jax_free__ = True

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..analysis.contracts import contract
from ..config import Config
from ..resilience.atomic import atomic_write_bytes

MANIFEST_NAME = "manifest.json"
#: pre-commit plan (sample pass done, shards in flight) — same schema
#: as the manifest minus completion; lets a killed ingest resume with
#: the ALREADY-FOUND bins instead of replaying the sample pass
PLAN_NAME = "ingest_plan.json"
BINS_NAME = "bins.npz"
MANIFEST_VERSION = 1

#: config keys that change the binned representation or the row/label
#: semantics of the shards — any drift forces a re-ingest (the analog
#: of snapshot.FP_KEYS for datasets)
FP_KEYS = ("max_bin", "bin_construct_sample_cnt", "data_random_seed",
           "label_column", "weight_column", "group_column",
           "ignore_column", "has_header")


class ManifestError(RuntimeError):
    """A manifest/plan file is missing, malformed, or incomplete."""


def shard_name(index: int) -> str:
    return "shard_%05d.bins" % index


def shard_meta_name(index: int) -> str:
    return "shard_%05d.meta" % index


def config_fingerprint(config: Config) -> str:
    """Readable k=v fingerprint of the bin-affecting config keys."""
    return ";".join("%s=%r" % (k, getattr(config, k)) for k in FP_KEYS)


def source_fingerprint(paths: Sequence[str]) -> str:
    """Readable fingerprint of the source file list: per-file basename,
    byte size and mtime (whole seconds: sub-second precision differs
    across filesystems and copies, while a real edit moves the clock).
    The `.weight`/`.query` metadata sidecars are fingerprinted too —
    their values are BAKED into shard metas / `.bin` caches, so an
    edited sidecar must invalidate exactly like an edited data file
    (`.init` is not: it applies at training time, never baked)."""
    parts = []
    for p in paths:
        for f in (p, p + ".weight", p + ".query"):
            if f is not p and not os.path.isfile(f):
                continue
            st = os.stat(f)
            parts.append("%s=size:%d,mtime:%d"
                         % (os.path.basename(f), st.st_size,
                            int(st.st_mtime)))
    return ";".join(parts)


def fingerprint_diff(have: str, want: str) -> str:
    """Key-by-key diff of two k=v fingerprint strings (the rejection
    message must NAME the moved keys, snapshot.fingerprint_diff's
    contract)."""
    h = dict(p.split("=", 1) for p in have.split(";") if "=" in p)
    w = dict(p.split("=", 1) for p in want.split(";") if "=" in p)
    keys = sorted(k for k in set(h) | set(w) if h.get(k) != w.get(k))
    return ", ".join("%s: manifest %s vs run %s"
                     % (k, h.get(k, "<absent>"), w.get(k, "<absent>"))
                     for k in keys)


@dataclasses.dataclass
class Manifest:
    """Schema + shard index of one ingested dataset."""
    num_rows: int
    num_features: int          # used (non-trivial) features == shard F
    num_total_features: int
    label_idx: int
    fmt: str                   # tsv | csv | libsvm
    dtype: str                 # uint8 | uint16
    shard_rows: int            # rows per full shard (last may be short)
    shard_row_counts: List[int]
    feature_names: List[str]
    has_weights: bool
    has_query: bool
    config_fp: str
    source_fp: str
    sources: List[str]
    version: int = MANIFEST_VERSION
    complete: bool = True

    @property
    def num_shards(self) -> int:
        return len(self.shard_row_counts)

    def shard_row0(self, index: int) -> int:
        return sum(self.shard_row_counts[:index])

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=1,
                          sort_keys=True)

    @staticmethod
    def from_json(text: str) -> "Manifest":
        try:
            d: Dict[str, Any] = json.loads(text)
        except ValueError as ex:
            raise ManifestError("malformed manifest JSON: %s" % ex)
        fields = {f.name for f in dataclasses.fields(Manifest)}
        missing = sorted(fields - set(d))
        if missing:
            raise ManifestError("manifest missing keys: %s"
                                % ", ".join(missing))
        return Manifest(**{k: v for k, v in d.items() if k in fields})


def save_manifest(dirpath: str, m: Manifest,
                  name: str = MANIFEST_NAME) -> None:
    """Atomic JSON write (tmp+fsync+replace): a SIGKILL at any byte
    leaves the previous manifest or none — never a truncated one."""
    atomic_write_bytes(os.path.join(dirpath, name),
                       m.to_json().encode("utf-8"), checksum=False)


def load_manifest(dirpath: str,
                  name: str = MANIFEST_NAME) -> Optional[Manifest]:
    """The parsed manifest/plan, or None when absent.  Malformed files
    raise ManifestError (callers decide between fatal and re-ingest)."""
    path = os.path.join(dirpath, name)
    if not os.path.isfile(path):
        return None
    with open(path, "rb") as f:
        return Manifest.from_json(f.read().decode("utf-8", "replace"))


@contract.rank_uniform
def is_manifest_path(path: str) -> bool:
    """True when `path` names an ingest directory (or its manifest.json
    directly) — the load_dataset routing probe.  A directory holding
    only plan/pack artifacts (a KILLED ingest that never committed its
    manifest) routes here too, so the loader's 're-run task=ingest'
    diagnostic fires instead of the text parser choking on a
    directory.

    @contract.rank_uniform: the probe answers off the shared dataset
    artifact every rank points data= at — ranks disagreeing would mean
    ranks were handed different datasets, which the config fingerprint
    cannot catch but the bin-mapper agreement would."""
    if os.path.basename(path) == MANIFEST_NAME:
        return os.path.isfile(path)
    if not os.path.isdir(path):
        return False
    return any(os.path.isfile(os.path.join(path, n))
               for n in (MANIFEST_NAME, PLAN_NAME, BINS_NAME))


def manifest_dir(path: str) -> str:
    """Normalize a manifest path (dir or dir/manifest.json) to the dir."""
    if os.path.basename(path) == MANIFEST_NAME:
        return os.path.dirname(path) or "."
    return path


#: file suffixes snapshot_sources treats as candidate training data —
#: the formats the text parser sniffs (io/parser) plus the generic ones
SOURCE_SUFFIXES: Tuple[str, ...] = (".tsv", ".csv", ".txt", ".data",
                                    ".svm", ".libsvm")


def snapshot_sources(dirpath: str,
                     suffixes: Sequence[str] = SOURCE_SUFFIXES
                     ) -> Dict[str, Tuple[int, int]]:
    """One (size, mtime_ns) stat snapshot of the candidate data files
    directly under `dirpath` — the drop-directory watch primitive the
    refresh agent polls (the same identity per file that
    source_fingerprint bakes into manifests, at ns precision).  The
    watcher offers a file downstream only once its entry holds STILL
    across two consecutive snapshots: a writer mid-copy keeps moving
    size/mtime, so half-written drops are never ingested.  Dotfiles
    and non-data suffixes are invisible (work/state files live
    alongside drops without triggering cycles)."""
    out: Dict[str, Tuple[int, int]] = {}
    try:
        names = sorted(os.listdir(dirpath))
    except OSError:
        return out
    for name in names:
        if name.startswith(".") \
                or not any(name.endswith(s) for s in suffixes):
            continue
        path = os.path.join(dirpath, name)
        try:
            st = os.stat(path)
        except OSError:
            continue          # raced a delete: absent next snapshot too
        if not os.path.isfile(path):
            continue
        out[path] = (st.st_size, st.st_mtime_ns)
    return out


__all__ = ["MANIFEST_NAME", "PLAN_NAME", "BINS_NAME", "FP_KEYS",
           "SOURCE_SUFFIXES", "Manifest", "ManifestError",
           "config_fingerprint", "source_fingerprint",
           "fingerprint_diff", "shard_name", "shard_meta_name",
           "save_manifest", "load_manifest", "is_manifest_path",
           "manifest_dir", "snapshot_sources"]
