"""Out-of-core ingestion subsystem: chunked text -> binned shard
directories under a memory budget, with resumable manifests and an
mmap-backed ShardedDataset that feeds training per-shard.

Everything here is jax-free (graftcheck GC002/GC007): ingest is host
preprocessing, and the parse/shard-write paths must run in jax-free
lanes (CLI task=ingest, parse worker processes)."""

from __future__ import annotations

__jax_free__ = True

from .manifest import (Manifest, ManifestError, is_manifest_path,
                       manifest_dir)
from .shards import ShardedDataset, load_sharded_dataset
from .writer import ingest, run_ingest_cli

__all__ = ["Manifest", "ManifestError", "is_manifest_path",
           "manifest_dir", "ShardedDataset", "load_sharded_dataset",
           "ingest", "run_ingest_cli"]
