"""Configuration system.

Re-implements the reference's key=value config surface (same keys, same
~60-entry alias table, same defaults and conflict checks) so the reference
`examples/*/train.conf` files run unchanged:
  - key list + defaults: reference include/LightGBM/config.h:89-245
  - alias table:         reference include/LightGBM/config.h:303-378
  - conflict checks:     reference src/io/config.cpp:129-177
  - CLI/config-file precedence (CLI wins, `#` comments):
                         reference src/application/application.cpp:46-104

TPU-specific additions (not in the reference) are grouped at the bottom of
Config; they control the JAX mesh instead of the socket/MPI bootstrap.
"""

from __future__ import annotations

__jax_free__ = True

import dataclasses
from typing import Dict, List, Optional, Tuple

from .utils import log

NO_LIMIT = -1

ALIAS_TABLE: Dict[str, str] = {
    "config": "config_file",
    "nthread": "num_threads",
    "num_thread": "num_threads",
    "boosting": "boosting_type",
    "boost": "boosting_type",
    "application": "objective",
    "app": "objective",
    "train_data": "data",
    "train": "data",
    "model_output": "output_model",
    "model_out": "output_model",
    "model_input": "input_model",
    "model_in": "input_model",
    "predict_result": "output_result",
    "prediction_result": "output_result",
    "valid": "valid_data",
    "test_data": "valid_data",
    "test": "valid_data",
    "is_sparse": "is_enable_sparse",
    "tranining_metric": "is_training_metric",
    "train_metric": "is_training_metric",
    "ndcg_at": "ndcg_eval_at",
    "min_data_per_leaf": "min_data_in_leaf",
    "min_data": "min_data_in_leaf",
    "min_sum_hessian_per_leaf": "min_sum_hessian_in_leaf",
    "min_sum_hessian": "min_sum_hessian_in_leaf",
    "min_hessian": "min_sum_hessian_in_leaf",
    "num_leaf": "num_leaves",
    "sub_feature": "feature_fraction",
    "num_iteration": "num_iterations",
    "num_tree": "num_iterations",
    "num_round": "num_iterations",
    "num_trees": "num_iterations",
    "num_rounds": "num_iterations",
    "sub_row": "bagging_fraction",
    "shrinkage_rate": "learning_rate",
    "tree": "tree_learner",
    "topk": "top_k",
    "num_machine": "num_machines",
    "local_port": "local_listen_port",
    "two_round_loading": "use_two_round_loading",
    "two_round": "use_two_round_loading",
    "mlist": "machine_list_file",
    "is_save_binary": "is_save_binary_file",
    "save_binary": "is_save_binary_file",
    "early_stopping_rounds": "early_stopping_round",
    "early_stopping": "early_stopping_round",
    "verbosity": "verbose",
    "header": "has_header",
    "label": "label_column",
    "weight": "weight_column",
    "group": "group_column",
    "query": "group_column",
    "query_column": "group_column",
    "ignore_feature": "ignore_column",
    "blacklist": "ignore_column",
    "predict_raw_score": "is_predict_raw_score",
    "predict_leaf_index": "is_predict_leaf_index",
    "num_classes": "num_class",
}


def _parse_bool(v: str) -> bool:
    # reference ConfigBase::GetBool accepts false/-/0 as false, true/+/1 as true
    s = v.strip().lower()
    if s in ("false", "-", "0"):
        return False
    if s in ("true", "+", "1"):
        return True
    log.fatal("Parameter value should be \"true\"/\"+\"/\"1\" or \"false\"/\"-\"/\"0\", got \"%s\"" % v)


@dataclasses.dataclass
class Config:
    """All hyper-parameters, flattened (the reference nests them in
    OverallConfig{IO,Boosting{Tree},Objective,Metric,Network}Config; a flat
    dataclass is the idiomatic Python equivalent)."""

    # -- task / top-level ------------------------------------------------
    task: str = "train"                   # train | predict | serve |
    #                                       ingest | refresh
    num_threads: int = 0
    boosting_type: str = "gbdt"           # gbdt | dart
    objective: str = "regression"         # regression | binary | multiclass | lambdarank
    metric: List[str] = dataclasses.field(default_factory=list)
    tree_learner: str = "serial"          # serial | feature | data | voting
    top_k: int = 20                       # voting-parallel votes per shard
    is_parallel: bool = False
    is_parallel_find_bin: bool = False

    # -- IO --------------------------------------------------------------
    max_bin: int = 256
    num_class: int = 1
    data_random_seed: int = 1
    data: str = ""
    valid_data: List[str] = dataclasses.field(default_factory=list)
    output_model: str = "LightGBM_model.txt"
    output_result: str = "LightGBM_predict_result.txt"
    input_model: str = ""
    verbose: int = 1
    num_model_predict: int = NO_LIMIT
    is_pre_partition: bool = False
    is_enable_sparse: bool = True
    use_two_round_loading: bool = False
    is_save_binary_file: bool = False
    enable_load_from_binary_file: bool = True
    bin_construct_sample_cnt: int = 50000
    is_predict_leaf_index: bool = False
    is_predict_raw_score: bool = False
    has_header: bool = False
    label_column: str = ""
    weight_column: str = ""
    group_column: str = ""
    ignore_column: str = ""

    # -- objective -------------------------------------------------------
    sigmoid: float = 1.0
    label_gain: List[float] = dataclasses.field(default_factory=list)
    max_position: int = 20
    is_unbalance: bool = False

    # -- metric ----------------------------------------------------------
    ndcg_eval_at: List[int] = dataclasses.field(default_factory=lambda: [1, 2, 3, 4, 5])

    # -- tree ------------------------------------------------------------
    min_data_in_leaf: int = 100
    min_sum_hessian_in_leaf: float = 10.0
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    num_leaves: int = 127
    feature_fraction_seed: int = 2
    feature_fraction: float = 1.0
    histogram_pool_size: float = NO_LIMIT
    max_depth: int = NO_LIMIT

    # -- boosting --------------------------------------------------------
    metric_freq: int = 1                  # reference BoostingConfig::output_freq
    is_training_metric: bool = False
    num_iterations: int = 10
    learning_rate: float = 0.1
    bagging_fraction: float = 1.0
    bagging_seed: int = 3
    bagging_freq: int = 0
    early_stopping_round: int = 0
    drop_rate: float = 0.01
    drop_seed: int = 4

    # -- network (reference socket/MPI keys, accepted for config-file
    #    compatibility; the JAX process bootstrap replaces their function) --
    num_machines: int = 1
    local_listen_port: int = 12400
    time_out: int = 120
    machine_list_file: str = ""

    # -- TPU-native additions --------------------------------------------
    num_shards: int = 0                   # 0 = all visible devices when tree_learner=data
    hist_dtype: str = "float32"           # histogram accumulator dtype
    hist_impl: str = "auto"               # auto | xla | pallas
    hist_agg: str = "psum"                # psum | scatter (tree_learner=data)
    rank_impl: str = "device"             # device | native (lambdarank gradients)
    hist_compact: str = "off"             # on | off (small-leaf row compaction;
    #                                       EXPERIMENTAL: measured slower on
    #                                       current TPUs — XLA gather/scatter
    #                                       row selection costs more than the
    #                                       90%-MXU full sweep it avoids)
    hist_ordered: str = "auto"            # auto | off: ordered-partition mode —
    #                                       block-list histogram sweeps + rows
    #                                       re-sorted by the previous tree's
    #                                       leaves every hist_reorder_every
    #                                       trees (serial pallas learner)
    hist_reorder_every: int = 16          # trees between row re-sorts
    hist_fused: str = "auto"              # auto | on | off: fused Pallas
    #                                       histogram+gain kernel — the
    #                                       per-split children sweep runs
    #                                       the best-split threshold scan
    #                                       in-register on the VMEM-
    #                                       resident accumulators instead
    #                                       of a separate XLA pass over
    #                                       the [F, B, 3] tensor.  auto
    #                                       engages with hist_impl=pallas
    #                                       (serial learner; other
    #                                       learners keep the two-op
    #                                       path); off IS the retained
    #                                       two-op oracle — fused on is
    #                                       bit-parity with it (the
    #                                       kernel runs the oracle's
    #                                       exact scan ops)
    hist_acc: str = "f32"                 # f32 | bf16 | i32: Pallas
    #                                       histogram accumulator mode.
    #                                       f32 is the parity default;
    #                                       bf16 streams gh2/one-hots in
    #                                       bfloat16 (half the VMEM and
    #                                       gh2 bandwidth, f32 MXU
    #                                       accumulate); i32 accumulates
    #                                       overflow-safe fixed-point
    #                                       integers (order-independent,
    #                                       exact counts).  bf16/i32
    #                                       round the inputs, so both are
    #                                       OPT-IN behind the f32 parity
    #                                       gate (serial pallas learner
    #                                       only)
    bag_compact: str = "auto"             # auto | on | off: bag-compacted fused
    #                                       training — in-bag rows arranged into
    #                                       a contiguous static window at every
    #                                       re-bagging so histogram/grow work
    #                                       scales with bagging_fraction; auto
    #                                       engages when bagging is on,
    #                                       bagging_fraction <= 0.8 and
    #                                       hist_dtype=float32 (the f64 parity
    #                                       configuration keeps the masked
    #                                       full-sweep oracle)
    iter_batch: str = "auto"              # auto | N | 1: boosting iterations
    #                                       scanned per device dispatch
    #                                       (models/gbdt.py train_segment).
    #                                       Segments end at every metric /
    #                                       early-stop / re-bagging / re-sort
    #                                       boundary, so observable behavior
    #                                       is unchanged and K>1 is bit-parity
    #                                       with the per-iteration oracle
    #                                       (iter_batch=1); auto picks a K
    #                                       that divides metric_freq on
    #                                       accelerators and 1 on CPU (local
    #                                       dispatch is cheap; the K-scan
    #                                       exists to kill remote-attached
    #                                       dispatch round-trips)
    donate_buffers: bool = True
    device_type: str = ""                 # "" = default JAX platform | cpu | tpu

    # -- online serving (task=serve; serving/) ---------------------------
    serve_host: str = "127.0.0.1"
    serve_port: int = 8080                # 0 = pick a free port
    serve_max_batch_rows: int = 8192      # rows per coalesced dispatch
    serve_batch_timeout_ms: float = 2.0   # micro-batching window
    serve_backend: str = "auto"           # auto | jax | native
    serve_max_inflight_rows: int = 65536  # admission control: rows in
    #                                       flight before new requests
    #                                       get a fast 503 + Retry-After
    #                                       instead of unbounded queueing
    serve_breaker_threshold: int = 3      # consecutive device-dispatch
    #                                       failures before the circuit
    #                                       breaker pins serving to the
    #                                       JAX-free native predictor
    serve_retry_after_s: float = 1.0      # Retry-After on overload 503s
    serve_workers: int = 1                # SO_REUSEPORT worker processes
    #                                       (serving/frontend.py): N
    #                                       processes share one listen
    #                                       port, each with its own warm
    #                                       forest; 1 = the in-process
    #                                       single server
    serve_matmul: str = "auto"            # auto | on | off: route serve
    #                                       batches >= serve_matmul_min_rows
    #                                       through the device matmul
    #                                       predictor (ops/predict.
    #                                       predict_leaf_matmul) instead
    #                                       of the stacked descent; auto
    #                                       engages on accelerators only
    #                                       (CPU descent wins there), on
    #                                       forces (tests/CPU parity)
    serve_matmul_min_rows: int = 1024     # row threshold for the matmul
    #                                       route (below it the descent
    #                                       dispatch is cheaper)
    serve_models: str = ""                # comma-separated extra model
    #                                       paths registered in the
    #                                       multi-model fleet at startup
    #                                       (serving/fleet.py); reachable
    #                                       via /predict?model=<path>
    serve_fleet_max_models: int = 64      # warm-pool capacity: at most
    #                                       this many forests stay warm
    #                                       (LRU + age eviction below);
    #                                       registered models past it
    #                                       re-warm on demand.  Cold
    #                                       fleet loads warm LAZILY
    #                                       (flat table + host packs
    #                                       only), so the pool scales
    #                                       toward thousands of
    #                                       per-tenant models
    serve_fleet_evict_age_s: float = 0.0  # age-based fleet eviction:
    #                                       warm non-default models idle
    #                                       longer than this drop from
    #                                       the pool (they stay
    #                                       registered and re-warm on
    #                                       the next hit); 0 = LRU
    #                                       capacity only
    serve_low_latency: str = "auto"       # auto | on | off: the
    #                                       latency-class admission lane
    #                                       — requests of at most
    #                                       serve_low_latency_max_rows
    #                                       rows skip the micro-batcher's
    #                                       coalescing window and
    #                                       dispatch synchronously on
    #                                       the jax-free flat-table
    #                                       engine (serving/flatforest).
    #                                       auto clamps the row bound
    #                                       below serve_matmul_min_rows;
    #                                       on fatals on that
    #                                       contradiction instead
    serve_low_latency_max_rows: int = 16  # largest request (rows) the
    #                                       fast lane admits; bigger
    #                                       requests ride the coalesced
    #                                       batch path

    # -- out-of-core ingestion (ingest/) ---------------------------------
    ingest_dir: str = ""                  # task=ingest output directory
    #                                       ("" = <data>.shards); training
    #                                       accepts data=<ingest_dir>
    ingest_memory_budget_mb: int = 1024   # hard host-memory budget for
    #                                       the chunked text->shard bin
    #                                       pass (bounds chunk size,
    #                                       in-flight worker results and
    #                                       the shard assembly buffer)
    ingest_shard_rows: int = 0            # rows per shard file (0 = auto
    #                                       from the memory budget)
    ingest_workers: int = 0               # parallel parse worker
    #                                       processes (0 = auto, 1 =
    #                                       inline single-process)
    ingest_prefetch: int = 2              # shard windows staged ahead by
    #                                       the background prefetch
    #                                       thread when training feeds
    #                                       from an ingest directory:
    #                                       the NEXT window pages in from
    #                                       disk while the previous
    #                                       device_put's transfer is in
    #                                       flight (bounded queue; host
    #                                       memory holds at most
    #                                       2 + ingest_prefetch windows —
    #                                       queued + producer-staged +
    #                                       consumer-held).
    #                                       0 = synchronous (the oracle:
    #                                       byte-identical models either
    #                                       way)

    # -- continuous refresh (task=refresh; refresh/agent.py) -------------
    refresh_drop_dir: str = ""            # watched drop directory: new
    #                                       training text files landing
    #                                       here trigger retrain cycles
    refresh_work_dir: str = ""            # agent scratch/state dir
    #                                       ("" = <drop_dir>/.refresh)
    refresh_serve_url: str = ""           # base URL of the serving
    #                                       fleet the agent deploys to
    #                                       (e.g. http://127.0.0.1:8080)
    refresh_eval_data: str = ""           # held-out labeled rows
    #                                       (task=predict data format)
    #                                       mirrored to champion AND
    #                                       challenger for shadow eval
    refresh_period_s: float = 30.0        # min seconds between cycles
    refresh_poll_s: float = 0.5           # drop-dir scan cadence; a
    #                                       file is offered only once
    #                                       its (size, mtime) held
    #                                       still across two scans
    refresh_rounds: int = 0               # boosting rounds per retrain
    #                                       (0 = num_iterations)
    refresh_min_gain: float = 0.0         # challenger must beat the
    #                                       champion's shadow-eval loss
    #                                       by more than this to be
    #                                       promoted (ties reject)
    refresh_deadline_s: float = 120.0     # per-step overall deadline
    #                                       (train / push / eval /
    #                                       promote each retry with
    #                                       backoff under it)
    refresh_breaker_threshold: int = 3    # consecutive failed cycles
    #                                       before the agent's circuit
    #                                       breaker opens (champion
    #                                       keeps serving)
    refresh_cooldown_s: float = 30.0      # how long an open breaker
    #                                       skips cycles before the
    #                                       next (half-open) attempt
    refresh_max_cycles: int = 0           # exit after N completed
    #                                       cycle attempts (0 = run
    #                                       until SIGTERM — production;
    #                                       N is for smokes/tests)
    refresh_train_args: str = ""          # extra space-separated
    #                                       key=value args forwarded to
    #                                       the retrain subprocess
    refresh_ingest: bool = False          # route each cycle's drop
    #                                       data through task=ingest
    #                                       and retrain from the shard
    #                                       directory (out-of-core
    #                                       lane) instead of the text
    #                                       file directly
    refresh_status_port: int = 0          # agent /metrics + /healthz
    #                                       port (0 = pick a free port,
    #                                       -1 = disabled)

    # -- fault tolerance (resilience/) -----------------------------------
    snapshot_period: int = 0              # snapshot every N iterations
    #                                       (0 = off); requires
    #                                       snapshot_dir
    snapshot_dir: str = ""                # where snapshots live
    snapshot_keep: int = 4                # newest snapshots retained per
    #                                       rank (0 = keep everything)
    resume: str = "off"                   # off | auto | <snapshot path>:
    #                                       auto picks the latest VALID
    #                                       snapshot in snapshot_dir,
    #                                       skipping corrupt ones
    faults: str = ""                      # fault-injection schedule
    #                                       (resilience/faults.py; also
    #                                       env LGBM_TPU_FAULTS)
    dist_connect_deadline_s: float = 120.0  # overall deadline for the
    #                                         distributed-runtime connect
    #                                         retry loop
    dist_timeout_s: float = 600.0         # per-collective deadline; a
    #                                       dead peer raises NetworkError
    #                                       instead of hanging (0 = wait
    #                                       forever)

    # ---------------------------------------------------------------------
    @staticmethod
    def from_params(params: Dict[str, str]) -> "Config":
        params = apply_aliases(params)
        c = Config()
        getp = params.get

        def set_int(key: str, attr: Optional[str] = None) -> None:
            if key in params:
                setattr(c, attr or key, int(params[key]))

        def set_float(key: str, attr: Optional[str] = None) -> None:
            if key in params:
                setattr(c, attr or key, float(params[key]))

        def set_bool(key: str, attr: Optional[str] = None) -> None:
            if key in params:
                setattr(c, attr or key, _parse_bool(params[key]))

        def set_str(key: str, attr: Optional[str] = None) -> None:
            if key in params:
                setattr(c, attr or key, params[key].strip())

        # top-level
        set_int("num_threads")
        if "task" in params:
            t = getp("task").lower()
            if t in ("train", "training"):
                c.task = "train"
            elif t in ("predict", "prediction", "test"):
                c.task = "predict"
            elif t in ("serve", "serving"):
                c.task = "serve"
            elif t in ("ingest", "ingestion"):
                c.task = "ingest"
            elif t == "refresh":
                c.task = "refresh"
            else:
                log.fatal("Unknown task type %s" % t)
        if "boosting_type" in params:
            b = getp("boosting_type").lower()
            if b in ("gbdt", "gbrt"):
                c.boosting_type = "gbdt"
            elif b == "dart":
                c.boosting_type = "dart"
            else:
                log.fatal("Unknown boosting type %s" % b)
        if "objective" in params:
            c.objective = getp("objective").lower()
        if "metric" in params:
            seen = []
            for m in getp("metric").lower().split(","):
                m = m.strip()
                if m and m not in seen:
                    seen.append(m)
            c.metric = seen
        if "tree_learner" in params:
            tl = getp("tree_learner").lower()
            if tl in ("serial", "feature", "data", "voting"):
                c.tree_learner = tl
            elif tl in ("feature_parallel",):
                c.tree_learner = "feature"
            elif tl in ("data_parallel",):
                c.tree_learner = "data"
            elif tl in ("voting_parallel",):
                c.tree_learner = "voting"
            else:
                log.fatal("Unknown tree learner type %s" % tl)

        # IO
        set_int("max_bin")
        set_int("data_random_seed")
        set_str("data")
        if "valid_data" in params:
            c.valid_data = [s.strip() for s in getp("valid_data").split(",") if s.strip()]
        set_str("output_model")
        set_str("output_result")
        set_str("input_model")
        set_int("verbose")
        set_int("num_model_predict")
        set_bool("is_pre_partition")
        set_bool("is_enable_sparse")
        set_bool("use_two_round_loading")
        set_bool("is_save_binary_file")
        set_bool("enable_load_from_binary_file")
        set_int("bin_construct_sample_cnt")
        set_bool("is_predict_leaf_index")
        set_bool("is_predict_raw_score")
        set_bool("has_header")
        set_str("label_column")
        set_str("weight_column")
        set_str("group_column")
        set_str("ignore_column")

        # objective / metric
        set_float("sigmoid")
        if "label_gain" in params:
            c.label_gain = [float(x) for x in getp("label_gain").split(",") if x.strip()]
        set_int("max_position")
        set_bool("is_unbalance")
        set_int("num_class")
        if "ndcg_eval_at" in params:
            c.ndcg_eval_at = [int(x) for x in getp("ndcg_eval_at").split(",") if x.strip()]

        # tree
        set_int("min_data_in_leaf")
        set_float("min_sum_hessian_in_leaf")
        set_float("lambda_l1")
        set_float("lambda_l2")
        set_float("min_gain_to_split")
        set_int("num_leaves")
        set_int("feature_fraction_seed")
        set_float("feature_fraction")
        set_float("histogram_pool_size")
        set_int("max_depth")

        # boosting
        set_int("metric_freq")
        set_bool("is_training_metric")
        set_int("num_iterations")
        set_float("learning_rate")
        set_float("bagging_fraction")
        set_int("bagging_seed")
        set_int("bagging_freq")
        set_int("early_stopping_round")
        set_float("drop_rate")
        set_int("drop_seed")

        # network
        set_int("num_machines")
        set_int("local_listen_port")
        set_int("time_out")
        set_str("machine_list_file")

        # tpu
        set_int("num_shards")
        set_int("top_k")
        set_str("hist_dtype")
        set_str("hist_impl")
        set_str("hist_agg")
        set_str("rank_impl")
        set_str("hist_compact")
        set_str("hist_ordered")
        set_int("hist_reorder_every")
        set_str("hist_fused")
        set_str("hist_acc")
        set_str("bag_compact")
        set_str("iter_batch")
        set_bool("donate_buffers")
        set_str("device_type")
        set_str("serve_host")
        set_int("serve_port")
        set_int("serve_max_batch_rows")
        set_float("serve_batch_timeout_ms")
        set_str("serve_backend")
        set_int("serve_max_inflight_rows")
        set_int("serve_breaker_threshold")
        set_float("serve_retry_after_s")
        set_int("serve_workers")
        set_str("serve_matmul")
        set_int("serve_matmul_min_rows")
        set_str("serve_models")
        set_int("serve_fleet_max_models")
        set_float("serve_fleet_evict_age_s")
        set_str("serve_low_latency")
        set_int("serve_low_latency_max_rows")
        set_str("ingest_dir")
        set_int("ingest_memory_budget_mb")
        set_int("ingest_shard_rows")
        set_int("ingest_workers")
        set_int("ingest_prefetch")
        set_str("refresh_drop_dir")
        set_str("refresh_work_dir")
        set_str("refresh_serve_url")
        set_str("refresh_eval_data")
        set_float("refresh_period_s")
        set_float("refresh_poll_s")
        set_int("refresh_rounds")
        set_float("refresh_min_gain")
        set_float("refresh_deadline_s")
        set_int("refresh_breaker_threshold")
        set_float("refresh_cooldown_s")
        set_int("refresh_max_cycles")
        set_str("refresh_train_args")
        set_bool("refresh_ingest")
        set_int("refresh_status_port")
        set_int("snapshot_period")
        set_str("snapshot_dir")
        set_int("snapshot_keep")
        set_str("resume")
        set_str("faults")
        set_float("dist_connect_deadline_s")
        set_float("dist_timeout_s")
        if c.serve_backend not in ("auto", "jax", "native"):
            log.fatal("Unknown serve_backend %s (expect auto|jax|native)"
                      % c.serve_backend)
        if c.serve_max_batch_rows < 1:
            log.fatal("serve_max_batch_rows must be >= 1")
        if c.serve_batch_timeout_ms < 0:
            log.fatal("serve_batch_timeout_ms must be >= 0")
        if c.serve_max_inflight_rows < 1:
            log.fatal("serve_max_inflight_rows must be >= 1")
        if c.serve_breaker_threshold < 1:
            log.fatal("serve_breaker_threshold must be >= 1")
        if c.serve_retry_after_s < 0:
            log.fatal("serve_retry_after_s must be >= 0")
        if c.serve_workers < 1:
            log.fatal("serve_workers must be >= 1")
        if c.serve_matmul not in ("auto", "on", "off"):
            log.fatal("Unknown serve_matmul %s (expect auto|on|off)"
                      % c.serve_matmul)
        if c.serve_matmul_min_rows < 1:
            log.fatal("serve_matmul_min_rows must be >= 1")
        if c.serve_fleet_max_models < 1:
            log.fatal("serve_fleet_max_models must be >= 1")
        if c.serve_fleet_evict_age_s < 0:
            log.fatal("serve_fleet_evict_age_s must be >= 0")
        if c.serve_low_latency not in ("auto", "on", "off"):
            log.fatal("Unknown serve_low_latency %s (expect auto|on|off)"
                      % c.serve_low_latency)
        if c.serve_low_latency_max_rows < 1:
            log.fatal("serve_low_latency_max_rows must be >= 1")
        if c.serve_low_latency == "on" \
                and c.serve_low_latency_max_rows \
                >= c.serve_matmul_min_rows:
            # contradictory routing: the forced-on fast lane would eat
            # batches the matmul route is configured to serve.  auto
            # resolves this by clamping the lane bound below the
            # threshold; forcing both is a config error, not a silent
            # precedence pick
            log.fatal("serve_low_latency_max_rows (%d) must be below "
                      "serve_matmul_min_rows (%d) with "
                      "serve_low_latency=on; lower the lane bound or "
                      "use serve_low_latency=auto (it clamps)"
                      % (c.serve_low_latency_max_rows,
                         c.serve_matmul_min_rows))
        if c.ingest_memory_budget_mb < 8:
            log.fatal("ingest_memory_budget_mb must be >= 8")
        if c.ingest_shard_rows < 0:
            log.fatal("ingest_shard_rows must be >= 0 (0 = auto)")
        if c.ingest_workers < 0:
            log.fatal("ingest_workers must be >= 0 (0 = auto)")
        if c.refresh_period_s < 0:
            log.fatal("refresh_period_s must be >= 0")
        if c.refresh_poll_s <= 0:
            log.fatal("refresh_poll_s must be > 0")
        if c.refresh_rounds < 0:
            log.fatal("refresh_rounds must be >= 0 (0 = num_iterations)")
        if c.refresh_min_gain < 0:
            # a negative tolerance would promote a challenger whose
            # shadow loss is strictly WORSE — violating the invariant
            # that a losing challenger is never made default
            log.fatal("refresh_min_gain must be >= 0")
        if c.refresh_deadline_s <= 0:
            log.fatal("refresh_deadline_s must be > 0")
        if c.refresh_breaker_threshold < 1:
            log.fatal("refresh_breaker_threshold must be >= 1")
        if c.refresh_cooldown_s < 0:
            log.fatal("refresh_cooldown_s must be >= 0")
        if c.refresh_max_cycles < 0:
            log.fatal("refresh_max_cycles must be >= 0 (0 = forever)")
        if c.refresh_status_port < -1:
            log.fatal("refresh_status_port must be >= -1 "
                      "(-1 = disabled, 0 = pick a free port)")
        if c.task == "refresh":
            if not c.refresh_drop_dir:
                log.fatal("task=refresh requires refresh_drop_dir")
            if not c.refresh_serve_url:
                log.fatal("task=refresh requires refresh_serve_url")
            if not c.refresh_eval_data:
                log.fatal("task=refresh requires refresh_eval_data "
                          "(held-out rows for shadow eval)")
            if not c.input_model:
                log.fatal("task=refresh requires input_model (the "
                          "starting champion)")
        if c.snapshot_period < 0:
            log.fatal("snapshot_period must be >= 0")
        if c.snapshot_keep < 0:
            log.fatal("snapshot_keep must be >= 0")
        if c.snapshot_period > 0 and not c.snapshot_dir:
            log.fatal("snapshot_period requires snapshot_dir")
        if c.resume == "auto" and not c.snapshot_dir:
            log.fatal("resume=auto requires snapshot_dir")
        if c.device_type not in ("", "cpu", "tpu"):
            log.fatal("Unknown device_type %s (expect cpu|tpu)"
                      % c.device_type)
        if c.hist_impl not in ("auto", "xla", "pallas"):
            log.fatal("Unknown hist_impl %s (expect auto|xla|pallas)"
                      % c.hist_impl)
        if c.hist_agg not in ("psum", "scatter"):
            log.fatal("Unknown hist_agg %s (expect psum|scatter)"
                      % c.hist_agg)
        if c.rank_impl not in ("device", "native"):
            log.fatal("Unknown rank_impl %s (expect device|native)"
                      % c.rank_impl)
        if c.hist_compact not in ("on", "off"):
            log.fatal("Unknown hist_compact %s (expect on|off)"
                      % c.hist_compact)
        if c.hist_ordered not in ("auto", "off"):
            log.fatal("Unknown hist_ordered %s (expect auto|off)"
                      % c.hist_ordered)
        if c.hist_fused not in ("auto", "on", "off"):
            log.fatal("Unknown hist_fused %s (expect auto|on|off)"
                      % c.hist_fused)
        if c.hist_acc not in ("f32", "bf16", "i32"):
            log.fatal("Unknown hist_acc %s (expect f32|bf16|i32)"
                      % c.hist_acc)
        if c.hist_acc != "f32" and c.hist_impl == "xla":
            log.fatal("hist_acc=%s requires the Pallas histogram kernel "
                      "(hist_impl=xla was forced)" % c.hist_acc)
        if c.hist_fused == "on" and c.hist_impl == "xla":
            log.fatal("hist_fused=on requires the Pallas histogram "
                      "kernel (hist_impl=xla was forced)")
        if c.ingest_prefetch < 0:
            log.fatal("ingest_prefetch must be >= 0 (0 = synchronous)")
        if c.bag_compact not in ("auto", "on", "off"):
            log.fatal("Unknown bag_compact %s (expect auto|on|off)"
                      % c.bag_compact)
        if c.iter_batch != "auto":
            try:
                ib = int(c.iter_batch)
            except ValueError:
                ib = 0
            if ib < 1:
                log.fatal("iter_batch must be 'auto' or an integer >= 1 "
                          "(got %s)" % c.iter_batch)
        if c.hist_dtype not in ("float32", "float64"):
            log.fatal("Unknown hist_dtype %s (expect float32|float64)"
                      % c.hist_dtype)

        c.check_param_conflict()
        log.set_level_from_verbosity(c.verbose)
        return c

    def check_param_conflict(self) -> None:
        # mirrors reference src/io/config.cpp:129-177
        multiclass = self.objective == "multiclass"
        if multiclass:
            if self.num_class <= 1:
                log.fatal("Number of classes should be specified and greater than 1 for multiclass training")
        else:
            if self.task == "train" and self.num_class != 1:
                log.fatal("Number of classes must be 1 for non-multiclass training")
        for m in self.metric:
            m_multi = m in ("multi_logloss", "multi_error")
            if (multiclass and not m_multi) or (not multiclass and m_multi):
                log.fatal("Objective and metrics don't match")
        # In the reference, num_machines>1 selects distributed training; on
        # TPU a "machine" is a mesh shard, so num_machines>1 with serial
        # learner collapses to serial (exactly as the reference does).
        if self.num_machines > 1:
            self.is_parallel = True
        else:
            self.is_parallel = False
        if self.tree_learner == "serial":
            self.is_parallel = False
            self.num_machines = 1
            self.is_parallel_find_bin = False
        elif self.tree_learner == "feature":
            self.is_parallel_find_bin = False
        elif self.tree_learner == "data":
            self.is_parallel = True
            self.is_parallel_find_bin = True
            if self.histogram_pool_size >= 0:
                log.warning(
                    "Histogram LRU queue was enabled (histogram_pool_size=%f). "
                    "Will disable this to reduce communication costs" % self.histogram_pool_size)
                self.histogram_pool_size = NO_LIMIT
        elif self.tree_learner == "voting":
            self.is_parallel = True
            self.is_parallel_find_bin = True
            if self.top_k <= 0:
                log.fatal("top_k must be positive for voting-parallel")


def apply_aliases(params: Dict[str, str]) -> Dict[str, str]:
    out = dict(params)
    for k, v in params.items():
        canonical = ALIAS_TABLE.get(k)
        if canonical is not None and canonical not in out:
            out[canonical] = v
    return out


def parse_kv_line(line: str) -> Optional[Tuple[str, str]]:
    line = line.split("#", 1)[0].strip()
    if not line:
        return None
    parts = line.split("=", 1)
    if len(parts) != 2:
        return None
    key = parts[0].strip().strip('"').strip("'")
    val = parts[1].strip().strip('"').strip("'")
    if not key:
        return None
    return key, val


def load_parameters(argv: List[str]) -> Dict[str, str]:
    """CLI args + optional config file; CLI wins.
    Mirrors Application::LoadParameters (reference src/application/application.cpp:46-104)."""
    cli: Dict[str, str] = {}
    for arg in argv:
        kv = parse_kv_line(arg)
        if kv is None:
            log.warning("Unknown parameter %s" % arg)
            continue
        cli[kv[0]] = kv[1]
    params: Dict[str, str] = {}
    config_file = cli.get("config") or cli.get("config_file")
    if config_file:
        with open(config_file, "r") as f:
            for line in f:
                kv = parse_kv_line(line)
                if kv is not None:
                    params.setdefault(kv[0], kv[1])
    # CLI priority
    params.update(cli)
    return params
