"""Crash-safe durable writes: tmp file + fsync + os.replace, with an
optional sha256 integrity footer.

Every durable artifact the project writes — training snapshots, `.bin`
dataset caches and their `.rows.npz` sidecars, model text files,
predict result files — goes through this module.  A SIGKILL at ANY
byte of the write leaves either the previous complete file or no file;
it can never leave a truncated file under the final name (the bare
`open(path, "wb")` it replaces could, and a truncated cache/snapshot
poisons every later run).  graftcheck rule GC008 enforces the routing:
a bare `open(.., "wb")` / `np.savez` outside a function contracted
@contract.durable_write is a finding.

Integrity footer (binary artifacts only — text formats the reference
parses must stay byte-identical): 40 trailing bytes appended to the
payload,

    payload .. | b"LGTPUSUM" (8) | sha256(payload) (32)

Readers that know the format (`read_verified`, `read_npz`,
`verify_file`) strip + verify it; the reference-format `.bin` reader
ignores trailing bytes by construction (it reads declared section
sizes), so footered caches stay loadable by format-only readers.  A
file WITHOUT the footer is "legacy": accepted, but it gets no
corruption protection beyond its own parser.
"""

from __future__ import annotations

__jax_free__ = True

import contextlib
import hashlib
import io
import os
import time
from typing import IO, Any, Iterator, Mapping, Optional, Tuple, Union

import numpy as np

from ..analysis.contracts import contract

#: 8-byte magic opening the 40-byte integrity footer
FOOTER_MAGIC = b"LGTPUSUM"
FOOTER_LEN = len(FOOTER_MAGIC) + 32


class IntegrityError(RuntimeError):
    """A checksummed artifact failed verification (truncated write,
    bit flip, partial copy): the file must not be trusted."""


def _fsync_dir(path: str) -> None:
    """fsync the directory so the os.replace rename itself is durable
    (best effort: not every filesystem supports directory fds)."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _tmp_path(path: str) -> str:
    """Sibling tmp name (same directory: os.replace must not cross
    filesystems).  pid-tagged so concurrent writers (multi-host ranks
    on a shared filesystem) cannot truncate each other's tmp."""
    return "%s.%d.lgtmp" % (path, os.getpid())


#: a foreign `.lgtmp` must look abandoned for this long before the
#: sweep may reap it (live writers refresh mtime with every chunk /
#: segment append; a preempted run's tmp goes quiet immediately)
STALE_TMP_S = 900.0


def _pid_alive(pid: int) -> bool:
    """Is `pid` a live process ON THIS HOST?  PermissionError means
    alive-but-not-ours; only ESRCH proves absence."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True
    return True


def reap_if_abandoned(path: str, writer_pid: int) -> bool:
    """Remove a pid-tagged `.lgtmp` iff its writer is ABANDONED: the
    one safety predicate behind every tmp sweep (here and the snapshot
    directory's).  A different pid alone does NOT prove a dead writer
    — multi-host ranks on a shared filesystem may write the same
    target concurrently, and two runs may share a snapshot_dir — so a
    tmp is reaped only when its writer is provably dead on this host
    AND the file has been quiet past STALE_TMP_S (a cross-host writer,
    whose pid cannot be probed here, keeps its tmp alive by writing to
    it).  Returns True when the tmp was removed."""
    try:
        quiet = time.time() - os.path.getmtime(path) > STALE_TMP_S
    except OSError:
        return False
    if not quiet or _pid_alive(writer_pid):
        return False
    try:
        os.remove(path)
    except OSError:
        return False
    return True


def _sweep_stale_tmps(path: str) -> None:
    """Remove abandoned `.lgtmp` siblings for this target.  A SIGKILL
    mid-write — the subsystem's core scenario — orphans one pid-tagged
    tmp per crash, and every resume runs under a fresh pid, so without
    a sweep a preemptible pool leaks one tmp (dataset-sized for `.bin`
    caches) per preemption.  Reaping rides reap_if_abandoned's
    dead-AND-quiet guard: live concurrent writers keep their tmps."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    prefix = os.path.basename(path) + "."
    try:
        names = os.listdir(d)
    except OSError:
        return
    pid = os.getpid()
    for name in names:
        if not (name.startswith(prefix) and name.endswith(".lgtmp")):
            continue
        mid = name[len(prefix):-len(".lgtmp")]
        if not mid.isdigit() or int(mid) == pid:
            continue
        reap_if_abandoned(os.path.join(d, name), int(mid))


def _footer(digest: bytes) -> bytes:
    return FOOTER_MAGIC + digest


def split_footer(data: bytes) -> Tuple[bytes, Optional[bytes]]:
    """(payload, sha256-from-footer or None when no footer present)."""
    if len(data) >= FOOTER_LEN \
            and data[-FOOTER_LEN:-32] == FOOTER_MAGIC:
        return data[:-FOOTER_LEN], data[-32:]
    return data, None


class _HashingFile:
    """File wrapper that feeds every written byte to a sha256 — so
    streaming writers (the `.bin` cache) get a footer without a second
    pass over the data."""

    def __init__(self, f: IO[bytes]):
        self._f = f
        self._sha = hashlib.sha256()

    def write(self, b: Union[bytes, memoryview]) -> int:
        self._sha.update(b)
        return self._f.write(b)

    def flush(self) -> None:
        self._f.flush()

    def read(self, *args: Any) -> bytes:
        # present (but unusable) so numpy's zipfile_factory treats this
        # as a file object instead of os.fspath()-coercing it; zipfile
        # never reads in mode "w".  No seek/tell ON PURPOSE: zipfile
        # then writes in stream mode (data descriptors, no seek-back),
        # keeping the hash consistent with the bytes on disk.
        raise io.UnsupportedOperation("write-only handle")

    def digest(self) -> bytes:
        return self._sha.digest()


@contract.durable_write
@contextlib.contextmanager
def atomic_writer(path: str, checksum: bool = False
                  ) -> Iterator[Union[IO[bytes], _HashingFile]]:
    """Stream a durable binary artifact: yields a write()-able handle
    over a sibling tmp file; on clean exit appends the sha256 footer
    (when `checksum`), fsyncs and os.replace()s into place.  On ANY
    exception the tmp is removed and the final path is untouched.
    Without `checksum` the raw file is yielded — large footer-less
    artifacts (streamed predict results) must not pay a discarded
    sha256 pass."""
    _sweep_stale_tmps(path)
    tmp = _tmp_path(path)
    f = open(tmp, "wb")
    hf = _HashingFile(f) if checksum else None
    try:
        yield hf if hf is not None else f
        if hf is not None:
            f.write(_footer(hf.digest()))
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, path)
        _fsync_dir(path)
    except BaseException:
        f.close()
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise


def atomic_write_bytes(path: str, payload: Union[bytes, memoryview],
                       checksum: bool = True) -> None:
    """One-shot atomic write of `payload` (+ integrity footer)."""
    with atomic_writer(path, checksum=checksum) as f:
        f.write(payload)


class AtomicTextFile:
    """Incremental text writer with atomic commit — the model-file
    save cadence (GBDT.save_model_to_file appends trees across
    segments, finalizing once).  Writes stream to a sibling tmp;
    close() fsyncs and renames into place, so a crash at any point
    leaves the previous complete model file (or nothing), never a
    truncated one.  abort() discards the tmp."""

    def __init__(self, path: str):
        self.path = path
        _sweep_stale_tmps(path)
        self._tmp = _tmp_path(path)
        self._f: Optional[IO[str]] = open(self._tmp, "w")

    def write(self, s: str) -> int:
        assert self._f is not None, "write after close/abort"
        return self._f.write(s)

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        """Commit: fsync + os.replace under the final name."""
        if self._f is None:
            return
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._f = None
        os.replace(self._tmp, self.path)
        _fsync_dir(self.path)

    def abort(self) -> None:
        if self._f is None:
            return
        self._f.close()
        self._f = None
        with contextlib.suppress(OSError):
            os.remove(self._tmp)


@contract.durable_write
def text_writer(path: str) -> AtomicTextFile:
    """Open an incremental atomic text writer (model files)."""
    return AtomicTextFile(path)


# ---------------------------------------------------------------------------
# verified readers
# ---------------------------------------------------------------------------

def read_verified(path: str) -> bytes:
    """Read a durable artifact, verify + strip its integrity footer.
    Raises IntegrityError on checksum mismatch; a footer-less file is
    returned as-is (legacy)."""
    with open(path, "rb") as f:
        data = f.read()
    payload, want = split_footer(data)
    if want is not None:
        got = hashlib.sha256(payload).digest()
        if got != want:
            raise IntegrityError(
                "%s failed sha256 verification (truncated or corrupt "
                "write: %d payload bytes)" % (path, len(payload)))
    return payload


def verify_file(path: str) -> str:
    """'ok' (footer verified) | 'legacy' (no footer) | 'corrupt: <why>'
    — never raises (validation probes must not).  Streams the hash in
    1 MiB chunks: large `.bin` caches stay within the loader's memory
    budget."""
    try:
        size = os.path.getsize(path)
        if size == 0:
            return "corrupt: zero-length file"
        with open(path, "rb") as f:
            if size < FOOTER_LEN:
                return "legacy"
            f.seek(size - FOOTER_LEN)
            tail = f.read(FOOTER_LEN)
            if tail[:len(FOOTER_MAGIC)] != FOOTER_MAGIC:
                return "legacy"
            want = tail[len(FOOTER_MAGIC):]
            f.seek(0)
            sha = hashlib.sha256()
            remaining = size - FOOTER_LEN
            while remaining > 0:
                chunk = f.read(min(1 << 20, remaining))
                if not chunk:
                    return "corrupt: short read"
                sha.update(chunk)
                remaining -= len(chunk)
    except OSError as ex:
        return "corrupt: unreadable (%s)" % ex
    if sha.digest() != want:
        return "corrupt: sha256 mismatch (truncated or bit-flipped)"
    return "ok"


# ---------------------------------------------------------------------------
# npz convenience (snapshots, .rows.npz sidecars)
# ---------------------------------------------------------------------------

@contract.durable_write
def write_npz(path: str, arrays: Mapping[str, Any],
              checksum: bool = True) -> None:
    """Atomic + checksummed np.savez, streamed: the archive goes
    straight to the tmp file (hashed as it is written), never
    materialized in RAM — snapshots carry the whole scores matrix, and
    an archive-sized transient spike per snapshot_period is real
    money.  Keeps the exact `path` (a direct np.savez would append
    .npz to a bare name, and a crash mid-write would leave a truncated
    archive under the final name)."""
    with atomic_writer(path, checksum=checksum) as f:
        np.savez(f, **arrays)


def read_npz(path: str) -> Any:
    """Lazy np.load over a verified file (IntegrityError on checksum
    mismatch; footer-less legacy archives load directly).  The hash is
    streamed in chunks and arrays decompress on access — the file
    bytes are never held whole in RAM.  np.load reads the archive in
    place: zipfile locates the central directory by signature, so the
    trailing 40-byte footer is ignored.  Returns the NpzFile
    (context-manager + mapping, like np.load)."""
    os.stat(path)           # a missing file stays OSError, not corrupt
    status = verify_file(path)
    if status.startswith("corrupt"):
        raise IntegrityError("%s failed verification (%s)"
                             % (path, status))
    return np.load(path)


__all__ = ["IntegrityError", "FOOTER_MAGIC", "FOOTER_LEN",
           "atomic_writer", "atomic_write_bytes", "AtomicTextFile",
           "text_writer", "split_footer", "read_verified",
           "verify_file", "write_npz", "read_npz",
           "reap_if_abandoned"]
