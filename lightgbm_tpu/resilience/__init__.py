"""Fault-tolerance subsystem: crash-safe durable writes, training
snapshots with auto-resume, deterministic fault injection, and hardened
network failure paths.

The production posture (ROADMAP north star; the Clipper-style serving
notes in serving/batcher.py) is degrade-don't-die: a SIGKILL mid-run
must never poison a durable artifact, a dead peer must produce a typed
error instead of a hang, and overload must shed load instead of
queueing without bound.  This package carries the pieces every layer
shares:

  atomic.py    the atomic-write helper under ALL durable artifacts
               (tmp file + fsync + os.replace, optional sha256
               integrity footer).  graftcheck rule GC008 forbids bare
               `open(.., "wb")` / `np.savez` writes outside functions
               contracted @contract.durable_write — this module is
               where those functions live.
  snapshot.py  periodic training snapshots (config: snapshot_period /
               snapshot_dir / resume) over GBDT.save_checkpoint's
               bit-exact state, with corrupt-snapshot skipping and
               multi-host rank-agreement sync on resume.
  faults.py    deterministic, seeded fault injection: named faultpoints
               at the real seams (checkpoint write, deferred flush,
               dist connect/send/recv, serving dispatch, /reload
               parse), driven by schedules from config/env so chaos
               tests are reproducible bit-for-bit.
  net.py       typed NetworkError, connect retry with exponential
               backoff under an overall deadline, and a thread-based
               call deadline so a dead peer cannot hang a collective
               forever.

Everything here is jax-free (stdlib + numpy): the serving fallback, the
CLI fast paths and the jax-free lint lanes all import it.
"""

from __future__ import annotations

__jax_free__ = True

__all__ = ["atomic", "faults", "net", "snapshot"]
