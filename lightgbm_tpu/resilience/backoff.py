"""One exponential-backoff curve for every retry loop in the tree.

Before this module three copies of the same idea had drifted apart:
the distributed connect loop (resilience/net.connect_with_retry), the
serving front-end's worker respawn throttle (serving/frontend.py) and
the refresh agent's deploy retries each re-derived "double the delay,
cap it" with their own constants and their own edge cases.  One curve,
declared once:

    delay(attempt) = min(base * factor**(attempt-1), cap)   (attempt >= 1)

plus an optional SEEDED full-jitter term — randomness, where wanted,
comes from the project's own mt19937 stream so a chaos schedule that
kills attempt N kills attempt N on every run (no ambient RNG, no wall
clock in the curve itself; GL005's rule).  Jitter defaults OFF: the
deterministic curve is the parity-friendly default.

`retry_with_backoff` is the loop shape net.connect_with_retry
established (and now shares): retry under an overall deadline, give up
when the NEXT sleep would cross it, chain the last error.
"""

from __future__ import annotations

__jax_free__ = True

import time
from typing import Any, Callable, Optional, Tuple, Type

from ..utils import log
from ..utils.mt19937 import Mt19937Random


class Backoff:
    """Deterministic exponential backoff curve with bounded delays.

    delay(attempt) for attempt = 1, 2, 3, ... walks base, base*factor,
    base*factor^2, ... capped at `cap_s`.  With `jitter` in (0, 1] the
    delay keeps a (1 - jitter) deterministic floor and draws the rest
    from a SEEDED mt19937 stream (full jitter at jitter=1.0) — seeded
    so retry storms decorrelate across processes (seed on the rank/pid)
    while any single process replays the exact same delays run to run.
    """

    def __init__(self, base_s: float = 0.5, cap_s: float = 30.0,
                 factor: float = 2.0, jitter: float = 0.0,
                 seed: int = 0):
        if base_s <= 0:
            raise ValueError("base_s must be > 0")
        if cap_s < base_s:
            raise ValueError("cap_s must be >= base_s")
        if factor < 1.0:
            raise ValueError("factor must be >= 1")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self.base_s = float(base_s)
        self.cap_s = float(cap_s)
        self.factor = float(factor)
        self.jitter = float(jitter)
        self._rng: Optional[Mt19937Random] = (
            Mt19937Random(seed) if jitter > 0.0 else None)

    def delay(self, attempt: int) -> float:
        """Seconds to wait after the `attempt`-th failure (1-based).
        attempt < 1 clamps to 1 so callers can feed raw counters."""
        n = max(1, int(attempt))
        # cap the exponent first: factor**n overflows floats long
        # before any real retry loop gets there
        d = self.base_s
        for _ in range(n - 1):
            d *= self.factor
            if d >= self.cap_s:
                d = self.cap_s
                break
        if self._rng is not None and d > 0:
            # full-jitter fraction from the seeded stream: floor +
            # uniform draw over the jittered remainder
            floor = d * (1.0 - self.jitter)
            frac = self._rng.next_double()
            d = floor + (d - floor) * frac
        return d


def retry_with_backoff(fn: Callable[[], Any], what: str,
                       deadline_s: float = 120.0,
                       base_s: float = 0.5, cap_s: float = 8.0,
                       factor: float = 2.0,
                       retry_on: Tuple[Type[BaseException], ...]
                       = (Exception,),
                       give_up_on: Tuple[Type[BaseException], ...]
                       = (),
                       sleep: Callable[[float], None] = time.sleep,
                       ) -> Any:
    """Run `fn()` until it succeeds or the overall deadline expires.

    The loop shape shared by connect_with_retry and the refresh agent:
    each failure sleeps the Backoff curve's next delay, giving up (and
    re-raising the LAST error, chained) when elapsed + next-delay would
    cross `deadline_s`.  Exceptions outside `retry_on` — or inside
    `give_up_on`, which wins (injected chaos faults, typed client
    errors) — propagate immediately: a "this can never succeed" error
    must not burn the deadline retrying."""
    curve = Backoff(base_s=base_s, cap_s=cap_s, factor=factor)
    t0 = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            return fn()
        except retry_on as ex:
            if give_up_on and isinstance(ex, give_up_on):
                raise
            last = ex
        delay = curve.delay(attempt)
        elapsed = time.monotonic() - t0
        if elapsed + delay > deadline_s:
            raise RetryDeadline(
                "%s failed after %d attempt(s) over %.1fs (deadline "
                "%.1fs): %s" % (what, attempt, elapsed, deadline_s,
                                last)) from last
        log.warning("%s attempt %d failed (%s); retrying in %.1fs"
                    % (what, attempt, last, delay))
        sleep(delay)


class RetryDeadline(RuntimeError):
    """retry_with_backoff exhausted its overall deadline (the last
    attempt's error is chained as __cause__)."""


__all__ = ["Backoff", "RetryDeadline", "retry_with_backoff"]
