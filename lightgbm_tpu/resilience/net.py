"""Hardened network failure paths: typed errors, bounded retries,
bounded waits.

The reference's socket linker at least retries connects in a loop
(linkers_socket.cpp:24-45); the JAX-distributed bootstrap we replaced
it with would, unwrapped, either fail on the first refused connect
(coordinator not up yet — the common race at cluster start) or block
forever inside a collective when a peer dies mid-run.  This module
provides the two missing behaviors for parallel/dist.py (which is
parity-scoped and may not touch the clock itself):

  connect_with_retry   exponential backoff under an overall deadline;
                       raises NetworkError naming the last error.
  call_with_deadline   run a blocking call on a worker thread with a
                       timeout; on expiry raise NetworkError instead of
                       hanging the trainer forever.  The abandoned
                       worker thread is daemonic — the process is about
                       to abort on the error anyway, which is exactly
                       the degrade-don't-hang contract.
"""

from __future__ import annotations

__jax_free__ = True

import threading
import time
from typing import Any, Callable, List, Tuple

from ..utils import log
from .backoff import Backoff
from .faults import FaultInjected, faultpoint


class NetworkError(RuntimeError):
    """A distributed-transport operation failed or timed out (typed so
    callers can distinguish a dead peer from a training bug)."""


def connect_with_retry(connect: Callable[[], Any], what: str,
                       deadline_s: float = 120.0,
                       base_delay_s: float = 0.5,
                       max_delay_s: float = 8.0) -> Any:
    """Run `connect()` with exponential backoff (the shared
    resilience/backoff.Backoff curve) until it succeeds or the overall
    deadline expires (NetworkError, chaining the last attempt's
    error).  Every attempt passes the `dist.connect` faultpoint first,
    so chaos schedules can fail exact attempts."""
    curve = Backoff(base_s=base_delay_s, cap_s=max_delay_s)
    t0 = time.monotonic()
    attempt = 0
    while True:
        attempt += 1
        try:
            faultpoint("dist.connect")
            return connect()
        except FaultInjected as ex:
            last: BaseException = ex
        except Exception as ex:
            last = ex
        delay = curve.delay(attempt)
        elapsed = time.monotonic() - t0
        if elapsed + delay > deadline_s:
            raise NetworkError(
                "%s failed after %d attempt(s) over %.1fs (deadline "
                "%.1fs): %s" % (what, attempt, elapsed, deadline_s,
                                last)) from last
        log.warning("%s attempt %d failed (%s); retrying in %.1fs"
                    % (what, attempt, last, delay))
        time.sleep(delay)


def call_with_deadline(fn: Callable[[], Any], timeout_s: float,
                       what: str) -> Any:
    """Run `fn()` and return its result, but give up after `timeout_s`
    seconds with a NetworkError instead of blocking forever (a dead
    peer leaves XLA collectives waiting indefinitely).  timeout_s <= 0
    disables the deadline (direct call)."""
    if timeout_s <= 0:
        return fn()
    box: List[Tuple[str, Any]] = []
    done = threading.Event()

    def run() -> None:
        try:
            box.append(("ok", fn()))
        except BaseException as ex:
            box.append(("err", ex))
        finally:
            done.set()

    t = threading.Thread(target=run, name="net-deadline", daemon=True)
    t.start()
    if not done.wait(timeout_s):
        raise NetworkError(
            "%s did not complete within %.1fs — peer dead or "
            "partitioned (aborting instead of hanging)"
            % (what, timeout_s))
    kind, val = box[0]
    if kind == "err":
        raise val
    return val


__all__ = ["NetworkError", "connect_with_retry", "call_with_deadline"]
