"""Deterministic fault injection: named faultpoints at the real seams.

Chaos tests must be REPRODUCIBLE: "SIGKILL the trainer at a random
iteration" is only a regression test if the same seed kills at the
same iteration every run.  This module gives every failure seam a
name, counts hits, and fires configured actions on exact hit numbers
or on a seeded mt19937 Bernoulli draw — no wall clock, no ambient RNG.

Faultpoints (the registry is closed: a faultpoint() call with an
unknown name is a programming error, so the chaos suite can prove it
exercised every seam):

    checkpoint.write    entering a snapshot write (before any bytes)
    checkpoint.commit   a snapshot is durable (after os.replace)
    flush.device_get    the deferred tree flush, before its device_get
    dist.connect        each distributed-runtime connect attempt
    dist.send           entering a cross-process collective
    dist.recv           a cross-process collective completed
    serve.dispatch      the serving forest's device dispatch
    reload.parse        /reload, before parsing the new model
    frontend.spawn      each front-end worker (re)spawn attempt
    ingest.shard_write  out-of-core ingest, before each shard commit
    refresh.train_spawn each refresh-agent retrain subprocess spawn
    refresh.eval        entering a shadow-eval pass (refresh agent)
    deploy.push         each push of a challenger into the fleet
    deploy.promote      each default-swap promotion attempt

Schedule spec (config key `faults=...` or env LGBM_TPU_FAULTS;
';'-separated entries):

    <name>@<N>=<action>     fire on the Nth hit of <name> (1-based)
    <name>@<N>+=<action>    fire on every hit from the Nth on
    <name>%<M>=<action>     seeded Bernoulli: fire when the next
                            mt19937 draw < M/1000 (per hit)
    seed=<S>                mt19937 seed for the %-rules (default 0)

Actions: `kill` (SIGKILL self — the preemption simulator), `exit:<C>`
(os._exit(C)), `raise` / `raise:<msg>` (raise FaultInjected).  Example:
LGBM_TPU_FAULTS="checkpoint.commit@3=kill" SIGKILLs the training
process the instant its third snapshot becomes durable.
"""

from __future__ import annotations

__jax_free__ = True

import os
import signal
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import log
from ..utils.mt19937 import Mt19937Random

#: every failure seam wired through faultpoint() — closed registry
KNOWN_FAULTPOINTS: Tuple[str, ...] = (
    "checkpoint.write", "checkpoint.commit", "flush.device_get",
    "dist.connect", "dist.send", "dist.recv",
    "serve.dispatch", "reload.parse", "frontend.spawn",
    "ingest.shard_write",
    "refresh.train_spawn", "refresh.eval", "deploy.push",
    "deploy.promote",
)

ENV_VAR = "LGBM_TPU_FAULTS"


class FaultInjected(RuntimeError):
    """An injected fault fired at a named faultpoint."""


class _Rule:
    def __init__(self, name: str, action: str, arg: str,
                 at: Optional[int] = None, sticky: bool = False,
                 permille: Optional[int] = None):
        self.name = name
        self.action = action     # kill | exit | raise
        self.arg = arg
        self.at = at             # exact hit number (1-based)
        self.sticky = sticky     # fire on every hit >= at
        self.permille = permille

    def fires(self, hit: int, draw: Optional[int]) -> bool:
        if self.permille is not None:
            return draw is not None and draw < self.permille
        assert self.at is not None
        return hit >= self.at if self.sticky else hit == self.at


class _Registry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._rules: Dict[str, List[_Rule]] = {}
        self._hits: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        self._rng: Optional[Mt19937Random] = None
        self._configured = False
        self._env_checked = False


_REG = _Registry()


def _parse_action(text: str) -> Tuple[str, str]:
    action, _, arg = text.partition(":")
    action = action.strip().lower()
    if action not in ("kill", "exit", "raise"):
        raise ValueError("unknown fault action %r (expect kill|"
                         "exit[:code]|raise[:message])" % text)
    return action, arg.strip()


def _parse_entry(entry: str) -> Tuple[Optional[int], _Rule]:
    """One spec entry -> (seed or None, rule or None-for-seed)."""
    lhs, sep, rhs = entry.partition("=")
    if not sep:
        raise ValueError("invalid fault entry %r (missing '=')" % entry)
    lhs = lhs.strip()
    if lhs == "seed":
        return int(rhs.strip()), _Rule("", "raise", "")
    sticky = False
    if lhs.endswith("+"):
        sticky = True
        lhs = lhs[:-1]
    action, arg = _parse_action(rhs.strip())
    if "@" in lhs:
        name, _, n = lhs.partition("@")
        name = name.strip()
        rule = _Rule(name, action, arg, at=int(n), sticky=sticky)
    elif "%" in lhs:
        name, _, m = lhs.partition("%")
        name = name.strip()
        rule = _Rule(name, action, arg, permille=int(m))
    else:
        raise ValueError("invalid fault entry %r (expect name@N=action "
                         "or name%%M=action)" % entry)
    if rule.name not in KNOWN_FAULTPOINTS:
        raise ValueError("unknown faultpoint %r (known: %s)"
                         % (rule.name, ", ".join(KNOWN_FAULTPOINTS)))
    return None, rule


def configure(spec: str) -> None:
    """Install a fault schedule (replaces any previous one and resets
    the hit counters).  Empty spec = clear."""
    seed = 0
    rules: Dict[str, List[_Rule]] = {}
    n_rules = 0
    for entry in (spec or "").split(";"):
        entry = entry.strip()
        if not entry:
            continue
        got_seed, rule = _parse_entry(entry)
        if got_seed is not None:
            seed = got_seed
            continue
        rules.setdefault(rule.name, []).append(rule)
        n_rules += 1
    with _REG._lock:
        _REG._rules = rules
        _REG._hits = {}
        _REG._fired = {}
        _REG._rng = Mt19937Random(seed)
        _REG._configured = True
        _REG._env_checked = True
    if n_rules:
        log.info("Fault injection armed: %s" % spec)


def reset() -> None:
    """Clear the schedule and counters (tests)."""
    with _REG._lock:
        _REG._rules = {}
        _REG._hits = {}
        _REG._fired = {}
        _REG._rng = None
        _REG._configured = False
        _REG._env_checked = True


def hits(name: str) -> int:
    """How many times the named faultpoint was reached."""
    with _REG._lock:
        return _REG._hits.get(name, 0)


def fired(name: str) -> int:
    """How many times a rule FIRED at the named faultpoint (kill/exit
    firings are unobservable from the same process, by design)."""
    with _REG._lock:
        return _REG._fired.get(name, 0)


def _ensure_env() -> None:
    if _REG._env_checked:
        return
    spec = os.environ.get(ENV_VAR, "")
    if spec:
        configure(spec)
    else:
        with _REG._lock:
            _REG._env_checked = True


def faultpoint(name: str) -> None:
    """Mark a failure seam.  A no-op (one dict lookup under a lock)
    unless a schedule armed a rule for `name`."""
    if name not in KNOWN_FAULTPOINTS:
        # explicit raise, not assert: the closed-registry guarantee
        # (chaos suites prove every seam exercised) must survive -O
        raise ValueError("unregistered faultpoint %r — add it to "
                         "KNOWN_FAULTPOINTS" % name)
    _ensure_env()
    with _REG._lock:
        hit = _REG._hits.get(name, 0) + 1
        _REG._hits[name] = hit
        rules = _REG._rules.get(name)
        if not rules:
            return
        to_fire: Optional[_Rule] = None
        for rule in rules:
            draw = None
            if rule.permille is not None and _REG._rng is not None:
                draw = int(_REG._rng.next_ints(
                    np.array([1000], dtype=np.int64))[0])
            if rule.fires(hit, draw):
                to_fire = rule
                break
        if to_fire is None:
            return
        _REG._fired[name] = _REG._fired.get(name, 0) + 1
    _fire(name, hit, to_fire)


def _fire(name: str, hit: int, rule: _Rule) -> None:
    if rule.action == "kill":
        log.warning("faultpoint %s hit %d: SIGKILL (injected)"
                    % (name, hit))
        os.kill(os.getpid(), signal.SIGKILL)
    elif rule.action == "exit":
        code = int(rule.arg) if rule.arg else 42
        log.warning("faultpoint %s hit %d: os._exit(%d) (injected)"
                    % (name, hit, code))
        os._exit(code)
    raise FaultInjected(rule.arg or "injected fault at %s (hit %d)"
                        % (name, hit))


__all__ = ["KNOWN_FAULTPOINTS", "ENV_VAR", "FaultInjected",
           "configure", "reset", "hits", "fired", "faultpoint"]
