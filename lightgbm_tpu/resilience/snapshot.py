"""Periodic crash-safe training snapshots with auto-resume.

A TPU pool is preemptible by design: a SIGKILL mid-run must cost at
most `snapshot_period` iterations, not the job.  The manager rides
GBDT.save_checkpoint — the existing bit-exact full-state snapshot
(trees, scores, bag windows, DART banks, ordered-partition layouts,
mt19937 stream positions) — and adds the operational layer:

  * cadence: a snapshot at every `snapshot_period`-iteration boundary
    the segment loop crosses, written atomically with a sha256 footer
    (resilience/atomic), so a crash mid-write can never leave a
    poisoned snapshot under a valid name;
  * retention: the newest `snapshot_keep` snapshots per rank (0 = keep
    everything);
  * resume: `resume=auto` picks the latest snapshot that VALIDATES
    (checksum + archive + required keys), skipping corrupt/truncated
    ones with a warning naming the file and the reason; `resume=<path>`
    requires that exact snapshot to validate; `resume=off` ignores
    snapshots;
  * multi-host agreement: every rank writes its own rank-tagged file;
    on resume the ranks allgather their valid iteration sets and load
    the newest COMMON iteration — or abort with a clear error when no
    common iteration exists (ranks must never silently resume from
    different iterations: the SPMD streams would diverge).

Graceful preemption: cli.train converts SIGTERM into a final snapshot
at the next segment boundary and a clean exit.
"""

from __future__ import annotations

__jax_free__ = True

import os
import re
from typing import Any, List, Optional, Tuple

import numpy as np

from ..analysis.contracts import contract
from ..utils import log
from . import atomic
from .faults import faultpoint

#: snapshot archives must carry these keys to count as valid
REQUIRED_KEYS = ("iter", "num_trees", "scores")

_NAME_RE = re.compile(r"^snapshot_r(\d+)_iter(\d+)\.lgts$")

#: orphaned atomic-write tmps (resilience/atomic._tmp_path): a SIGKILL
#: mid-snapshot — the subsystem's core scenario — leaves one behind
_TMP_RE = re.compile(r"^snapshot_r(\d+)_iter\d+\.lgts\.(\d+)\.lgtmp$")

#: how many of a rank's newest snapshots resume-agreement considers
SYNC_WINDOW = 16

#: config keys bound into every snapshot (resume_fingerprint): resuming
#: under a config that disagrees on ANY of these would silently continue
#: the OLD run under the NEW name — the snapshot is rejected instead.
#: Deliberately excludes num_iterations / early_stopping_round
#: (extending or re-capping a run and resuming it is the one legitimate
#: config change), metric/printing keys (they shape output, not state),
#: and all paths/ports (they legitimately differ per rank / per move).
FP_KEYS = ("objective", "boosting_type", "tree_learner", "num_class",
           "num_leaves", "max_depth", "max_bin", "min_data_in_leaf",
           "min_sum_hessian_in_leaf", "learning_rate", "lambda_l1",
           "lambda_l2", "min_gain_to_split", "feature_fraction",
           "feature_fraction_seed", "bagging_fraction", "bagging_freq",
           "bagging_seed", "data_random_seed", "drop_rate", "drop_seed",
           "sigmoid", "top_k", "hist_dtype", "hist_impl", "hist_agg",
           "num_shards", "num_machines")


def resume_fingerprint(booster: Any) -> str:
    """Config + dataset binding for a snapshot, as a readable k=v
    string (not a digest: a rejected resume must say WHICH keys
    moved).  Dataset identity rides shape — num_data/num_features
    catch a swapped data file without binding to a path."""
    cfg = getattr(booster, "config", None)
    parts = ["%s=%r" % (k, getattr(cfg, k, None)) for k in FP_KEYS]
    td = getattr(booster, "train_data", None)
    parts.append("num_data=%r" % getattr(booster, "num_data", None))
    parts.append("num_features=%r"
                 % getattr(td, "num_total_features", None))
    return ";".join(parts)


def fingerprint_diff(snap_fp: str, run_fp: str) -> str:
    """Human-readable key-by-key diff of two fingerprint strings."""
    snap = dict(p.split("=", 1) for p in snap_fp.split(";") if "=" in p)
    run = dict(p.split("=", 1) for p in run_fp.split(";") if "=" in p)
    keys = sorted(k for k in set(snap) | set(run)
                  if snap.get(k) != run.get(k))
    return ", ".join("%s: snapshot %s vs run %s"
                     % (k, snap.get(k, "<absent>"), run.get(k, "<absent>"))
                     for k in keys)


def snapshot_name(iteration: int, rank: int = 0) -> str:
    return "snapshot_r%d_iter%08d.lgts" % (rank, iteration)


@contract.rank_uniform
def is_checkpoint_file(path: str) -> bool:
    """True when `path` holds a trainer checkpoint archive (the
    save_checkpoint npz/zip format, sha-footered or not) rather than a
    model TEXT file.  init_model/input_model warm starts route on this
    probe: a checkpoint takes the bit-exact load_checkpoint path, a
    text model takes the reference's re-boost-from-scores path (model
    text starts with its boosting-type line, never zip magic).

    @contract.rank_uniform: the probe answers off the shared
    input_model artifact every rank points at (the is_manifest_path
    argument) — ranks disagreeing would mean ranks were handed
    different base models, which the config fingerprint already
    forbids for the path and the checkpoint fingerprint for the
    content."""
    try:
        with open(path, "rb") as f:
            return f.read(4) == b"PK\x03\x04"
    except OSError:
        return False


def _probe_snapshot(path: str, expect_fp: Optional[str] = None
                    ) -> Tuple[Optional[str], int]:
    """(rejection reason or None, snapshot iteration) with ONE
    verified read — the explicit-resume path needs the iteration too,
    and snapshots carry the whole scores matrix, so a second
    full-file hash just to read `iter` is real money."""
    try:
        if os.path.getsize(path) == 0:
            return "corrupt: zero-length file", 0
    except OSError as ex:
        return "corrupt: unreadable (%s)" % ex, 0
    try:
        with atomic.read_npz(path) as z:
            missing = [k for k in REQUIRED_KEYS if k not in z.files]
            fp = (str(z["resume_fp"]) if "resume_fp" in z.files
                  else None)
            it = 0 if "iter" in missing else int(z["iter"])
        if missing:
            return "corrupt: missing key(s) %s" % ", ".join(missing), 0
    except atomic.IntegrityError as ex:
        return "corrupt: %s" % ex, 0
    except Exception as ex:
        # a truncated/garbled zip raises zipfile.BadZipFile or
        # ValueError depending on where the damage landed
        return "corrupt: unreadable archive (%s)" % ex, 0
    if expect_fp is not None and fp is not None and fp != expect_fp:
        # fp=None is a pre-fingerprint snapshot: accepted (legacy),
        # load_checkpoint has no stronger information either
        return ("stale: written by a different config/dataset (%s)"
                % fingerprint_diff(fp, expect_fp)), it
    return None, it


def validate_snapshot(path: str,
                      expect_fp: Optional[str] = None) -> Optional[str]:
    """None when the snapshot is loadable, else a human-readable
    rejection reason (zero-length, checksum mismatch, unreadable
    archive, missing keys, config/dataset fingerprint mismatch when
    `expect_fp` is given).  ONE streamed hash per candidate (read_npz
    verifies in place and loads arrays lazily): resume=auto probes up
    to SYNC_WINDOW of them."""
    return _probe_snapshot(path, expect_fp)[0]


class SnapshotManager:
    """Cadenced snapshot writes + resume for one training process."""

    def __init__(self, directory: str, period: int, resume: str,
                 keep: int = 4, rank: int = 0,
                 num_machines: int = 1, max_iteration: int = 0):
        self.directory = directory
        self.period = int(period)
        self.resume = resume
        self.keep = int(keep)
        self.rank = int(rank)
        self.num_machines = int(num_machines)
        # resume must never hand back MORE iterations than this run
        # asked for (0 = uncapped): a snapshot past the cap would skip
        # the training loop and silently save an oversized model
        self.max_iteration = int(max_iteration)
        self._last = 0          # iteration of the newest snapshot/resume

    @staticmethod
    def from_config(cfg: Any, rank: int = 0, num_machines: int = 1,
                    max_iteration: Optional[int] = None
                    ) -> Optional["SnapshotManager"]:
        period = int(cfg.snapshot_period)
        resume = (cfg.resume or "off").strip()
        if period <= 0 and resume == "off":
            return None
        if period > 0 and not cfg.snapshot_dir:
            log.fatal("snapshot_period=%d requires snapshot_dir" % period)
        if resume == "auto" and not cfg.snapshot_dir:
            log.fatal("resume=auto requires snapshot_dir")
        if max_iteration is None:
            max_iteration = int(cfg.num_iterations)
        return SnapshotManager(cfg.snapshot_dir, period, resume,
                               keep=int(cfg.snapshot_keep), rank=rank,
                               num_machines=num_machines,
                               max_iteration=max_iteration)

    # -- write cadence --------------------------------------------------
    @contract.rank_uniform
    def due(self, iteration: int) -> bool:
        """True when the segment loop crossed a period boundary since
        the last snapshot (segments may advance several iterations at
        once)."""
        if self.period <= 0:
            return False
        return iteration // self.period > self._last // self.period

    def write(self, booster: Any) -> str:
        """Snapshot the booster's full state (atomic + checksummed).
        The `checkpoint.write` faultpoint fires before any bytes exist,
        `checkpoint.commit` the instant the snapshot is durable."""
        iteration = int(booster.iter)
        faultpoint("checkpoint.write")
        os.makedirs(self.directory, exist_ok=True)
        path = os.path.join(self.directory,
                            snapshot_name(iteration, self.rank))
        booster.save_checkpoint(path)
        faultpoint("checkpoint.commit")
        self._last = iteration
        self._prune()
        log.info("Snapshot written: %s (iteration %d)"
                 % (path, iteration))
        return path

    def _prune(self) -> None:
        self._sweep_orphan_tmps()
        if self.keep <= 0:
            return
        for iteration, path in self._candidates()[self.keep:]:
            try:
                os.remove(path)
            except OSError:
                pass

    def _sweep_orphan_tmps(self) -> None:
        """Remove THIS rank's `.lgtmp` leftovers from dead writers (a
        SIGKILL mid-snapshot orphans one per crash; retention never
        matches them, so a preemptible pool would otherwise grow them
        without bound).  Reaping rides atomic.reap_if_abandoned's
        dead-AND-quiet guard — a second live run sharing snapshot_dir
        keeps its mid-write tmp — and other RANKS' tmps on a shared
        filesystem are not ours to touch."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        pid = os.getpid()
        for name in names:
            m = _TMP_RE.match(name)
            if m is None or int(m.group(1)) != self.rank \
                    or int(m.group(2)) == pid:
                continue
            atomic.reap_if_abandoned(os.path.join(self.directory, name),
                                     int(m.group(2)))

    # -- discovery ------------------------------------------------------
    def _candidates(self) -> List[Tuple[int, str]]:
        """This rank's snapshots, newest first."""
        out: List[Tuple[int, str]] = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return out
        for name in names:
            m = _NAME_RE.match(name)
            if m is not None and int(m.group(1)) == self.rank:
                out.append((int(m.group(2)),
                            os.path.join(self.directory, name)))
        out.sort(reverse=True)
        return out

    def valid_iters(self, limit: int = SYNC_WINDOW,
                    expect_fp: Optional[str] = None) -> List[int]:
        """Iterations with a VALID snapshot for this rank, newest
        first; corrupt/stale files are skipped with a warning naming
        the file and the reason."""
        out: List[int] = []
        for iteration, path in self._candidates():
            if len(out) >= limit:
                break
            if self.max_iteration > 0 and iteration > self.max_iteration:
                # a longer earlier run left snapshots past this run's
                # cap: resuming one would skip the training loop and
                # save a model with MORE iterations than requested
                log.warning("Skipping snapshot %s: iteration %d is "
                            "beyond this run's num_iterations=%d"
                            % (path, iteration, self.max_iteration))
                continue
            reason = validate_snapshot(path, expect_fp=expect_fp)
            if reason is None:
                out.append(iteration)
            else:
                log.warning("Skipping snapshot %s: %s" % (path, reason))
        return out

    # -- resume ---------------------------------------------------------
    @contract.rank_uniform
    def maybe_resume(self, booster: Any) -> int:
        """Restore the booster per the `resume` policy; returns the
        resumed iteration (0 = fresh start).  Multi-host: all ranks
        agree on ONE common iteration or training aborts."""
        if self.resume == "off":
            return 0
        expect_fp = resume_fingerprint(booster)
        if self.resume == "auto":
            iters = self.valid_iters(expect_fp=expect_fp)
        else:
            # explicit path: that exact snapshot must validate — and in
            # multi-host mode it must belong to THIS rank (a shared conf
            # naming rank 0's file would pass _agree's iteration check
            # while loading another rank's shard scores/bag windows/RNG
            # streams: exactly the silent SPMD divergence to abort on)
            m = _NAME_RE.match(os.path.basename(self.resume))
            if self.num_machines > 1 and m is not None \
                    and int(m.group(1)) != self.rank:
                log.fatal("resume=%s names rank %s's snapshot, but this "
                          "is rank %d: every rank must restore ITS OWN "
                          "shard state (use resume=auto or a per-rank "
                          "path)" % (self.resume, m.group(1), self.rank))
            reason, it = _probe_snapshot(self.resume,
                                         expect_fp=expect_fp)
            if reason is not None:
                log.fatal("resume=%s: snapshot rejected: %s"
                          % (self.resume, reason))
            if self.max_iteration > 0 and it > self.max_iteration:
                log.fatal("resume=%s: snapshot iteration %d is beyond "
                          "this run's num_iterations=%d — the model "
                          "would silently contain more iterations than "
                          "requested" % (self.resume, it,
                                         self.max_iteration))
            self._agree(it)
            booster.load_checkpoint(self.resume)
            self._last = it
            log.info("Resumed from snapshot %s (iteration %d)"
                     % (self.resume, it))
            return it
        target = self._agree_latest(iters)
        if target <= 0:
            log.info("resume=auto: no valid snapshot in %s — starting "
                     "fresh" % self.directory)
            return 0
        path = os.path.join(self.directory,
                            snapshot_name(target, self.rank))
        booster.load_checkpoint(path)
        self._last = target
        log.info("Resumed from snapshot %s (iteration %d)"
                 % (path, target))
        return target

    def _agree(self, iteration: int) -> None:
        """Multi-host: every rank must resume the SAME iteration."""
        if self.num_machines <= 1:
            return
        from ..parallel.dist import process_allgather
        alls = process_allgather(
            np.array([iteration], dtype=np.int64)).reshape(-1)
        if not (alls == alls[0]).all():
            log.fatal("Ranks disagree on the resume iteration (%s): "
                      "every rank must restore the same snapshot "
                      "iteration or the SPMD streams diverge"
                      % alls.tolist())

    @contract.rank_uniform
    def _agree_latest(self, iters: List[int]) -> int:
        """resume=auto agreement: the newest iteration EVERY rank holds
        a valid snapshot for.  -1 entries pad the gathered window."""
        if self.num_machines <= 1:
            return iters[0] if iters else 0
        from ..parallel.dist import process_allgather
        pad = np.full(SYNC_WINDOW, -1, dtype=np.int64)
        pad[:min(len(iters), SYNC_WINDOW)] = iters[:SYNC_WINDOW]
        alls = process_allgather(pad)            # [P, SYNC_WINDOW]
        sets = [set(int(v) for v in row if v >= 0) for row in alls]
        common = set.intersection(*sets) if sets else set()
        if common:
            return max(common)
        if not any(sets):
            return 0          # no rank has anything: fresh start
        log.fatal("resume=auto: no snapshot iteration is valid on "
                  "EVERY rank (per-rank valid iterations: %s) — "
                  "restore the missing/corrupt snapshot files or "
                  "restart with resume=off"
                  % [sorted(s) for s in sets])

    @contract.rank_uniform
    def sync_flag(self, flag: bool) -> bool:
        """OR a per-rank boolean across ranks (preemption agreement:
        one rank's SIGTERM must stop every rank at the same segment
        boundary)."""
        if self.num_machines <= 1:
            return flag
        from ..parallel.dist import vote_any
        return vote_any(flag)


__all__ = ["SnapshotManager", "snapshot_name", "is_checkpoint_file",
           "validate_snapshot", "resume_fingerprint",
           "fingerprint_diff", "REQUIRED_KEYS", "FP_KEYS"]
