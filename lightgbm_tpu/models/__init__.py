"""lightgbm_tpu.models"""

__jax_free__ = True
