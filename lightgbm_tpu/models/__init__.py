"""lightgbm_tpu.models"""
