"""GBDT boosting driver.

Python/JAX host loop replacing the reference's GBDT class
(src/boosting/gbdt.cpp): per-iteration flow is gradients -> per-class tree
growth (one fused device call per tree, ops/grow.py) -> score updates ->
metrics/early-stopping.  Model text format is byte-compatible with
GBDT::SaveModelToFile / LoadModelFromString (gbdt.cpp:351-456).

Bagging (row- and query-granular reservoir sampling, gbdt.cpp:109-160) and
feature_fraction (serial_tree_learner.cpp:140-147) reproduce the reference's
mt19937 draw streams bit-exactly (utils/mt19937.py), enabling tree-identity
parity tests with bagging enabled.
"""

from __future__ import annotations

__jax_free__ = False  # the boosting driver traces jits

from collections import OrderedDict
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.contracts import contract
from ..config import Config
from ..io.dataset import Dataset
from ..metrics import Metric
from ..objectives import Objective
from ..ops.grow import grow_tree, grow_tree_bagged
from ..ops.predict import predict_leaf_binned
from ..ops.split import SplitParams
from ..resilience.atomic import read_npz, text_writer, write_npz
from ..resilience.snapshot import fingerprint_diff, resume_fingerprint
from ..resilience.faults import faultpoint
from ..utils import log
from ..utils.mt19937 import Mt19937Random
from .tree import Tree

NO_LIMIT = -1


class _PendingTree:
    """A trained tree still packed in device buffers; GBDT._flush_pending
    stacks every pending tree's buffers and pulls them host-side in one
    transfer, then unpacks them into host Trees — the per-iteration
    dispatch pipeline never blocks on a device->host roundtrip.

    Invariant: every pending ints/floats pair in one booster has the
    SAME shape — _pack_tree pads to the config-fixed leaf count, and
    the flush's jnp.stack relies on it (asserted there)."""

    __slots__ = ("ints", "floats", "lr", "gated")

    def __init__(self, ints, floats, lr, gated=False):
        # gated: produced by the fused step, whose device stopped-flag
        # already suppressed this tree's score updates if it came after
        # a stump — _flush_pending must NOT subtract it again
        self.ints = ints
        self.floats = floats
        self.lr = lr
        self.gated = gated


@jax.jit
def _pack_tree(dev_tree):
    """TreeArrays -> (int32 buffer, float buffer): two flat arrays so a
    whole tree ships device->host in two async copies instead of eleven.
    The trailing dummy slots (grow.py TreeArrays) are trimmed here, so the
    wire layout stays [1 + 4*(L-1) + 3*L | (L-1) + L + (L-1)]."""
    ints = jnp.concatenate([
        dev_tree.num_leaves.reshape(1), dev_tree.split_feature[:-1],
        dev_tree.threshold_bin[:-1], dev_tree.left_child[:-1],
        dev_tree.right_child[:-1], dev_tree.leaf_parent[:-1],
        dev_tree.leaf_depth[:-1], dev_tree.leaf_count[:-1],
    ]).astype(jnp.int32)
    floats = jnp.concatenate([dev_tree.split_gain[:-1],
                              dev_tree.leaf_value[:-1],
                              dev_tree.internal_value[:-1]])
    return ints, floats


# Shared fused-iteration executables, keyed by everything static that
# shapes the computation (objective fused_key, lr, dtype, grow params,
# valid-set count).  Bins, labels and scores are jit ARGUMENTS, so the
# executable embeds no dataset constants: it stays small (MBs, not 100s
# of MBs), the persistent compilation cache entry is shape-keyed and
# reusable across processes, and a warm-up booster and the real booster
# share one compilation.  LRU-bounded so a long hyper-parameter sweep
# doesn't accumulate executables forever (evicted entries recompile via
# the persistent disk cache, which is cheap).
_FUSED_STEPS = OrderedDict()
_FUSED_STEPS_MAX = 8


def _get_fused_step(key, make):
    """LRU lookup of a fused executable; `make()` builds it on miss."""
    fn = _FUSED_STEPS.get(key)
    if fn is None:
        fn = make()
        _FUSED_STEPS[key] = fn
        if len(_FUSED_STEPS) > _FUSED_STEPS_MAX:
            _FUSED_STEPS.popitem(last=False)
    else:
        _FUSED_STEPS.move_to_end(key)
    return fn


def _unpack_bag(bag_mask, n_pad):
    """Bag masks upload as packed bits ([n_pad/8] u8, np.packbits big-
    endian bit order) — 8x less host->device traffic per re-bagging,
    which matters on remote-attached TPUs.  Bool masks pass through."""
    if bag_mask.dtype == jnp.uint8:
        bits = (bag_mask[:, None]
                >> (jnp.uint8(7) - jnp.arange(8, dtype=jnp.uint8))) \
            & jnp.uint8(1)
        return bits.reshape(-1)[:n_pad].astype(bool)
    return bag_mask


_unpack_bag_jit = jax.jit(_unpack_bag, static_argnums=1)


@jax.jit
def _permute_packed_bag(packed: jax.Array, row_order: jax.Array):
    """File-order packed bag bits -> ordered-space bool mask."""
    return jnp.take(_unpack_bag(packed, row_order.shape[0]), row_order)


# -- iteration batching (config.iter_batch) ---------------------------------
#
# One GENERIC wrapper turns any fused step body into a K-iteration body:
# an outer lax.scan whose carry is the cross-iteration device state
# (scores, valid scores, the stopped flag, and — on the reorder variants —
# bins/bag/gstate/row order), whose xs are the per-iteration HOST inputs
# (feature masks; DART adds drop lists, shrinkage factors and bank rows),
# and whose ys are the K packed trees, stacked [K, T_ints]/[K, T_floats]
# and pulled host-side in one transfer by the usual deferred flush.  The
# wrapped body keeps the original positional signature, so the jit/
# shard_map plumbing (donation positions, partition specs) is untouched —
# replicated specs (P()) hold for the [K, ...] xs/ys regardless of rank.
#
# A spec is (carry (in_pos, out_pos) pairs, xs in positions, ys out
# positions, output arity); everything else is segment-constant and stays
# closed over via the outer args.

_SCAN_PLAIN = (((0, 0), (1, 1), (7, 4)), (3,), (2, 3), 5)
_SCAN_REORDER = (((0, 0), (1, 1), (2, 5), (4, 4), (6, 6), (7, 7), (8, 8)),
                 (3,), (2, 3), 9)
_SCAN_MULTI = (((0, 0), (1, 1), (7, 4)), (3,), (2, 3), 5)
_SCAN_MULTI_REORDER = (((0, 0), (1, 1), (2, 6), (4, 5), (6, 7), (7, 4),
                        (8, 8)), (3,), (2, 3), 9)
_SCAN_DART = (((0, 0), (1, 1), (2, 2), (3, 3), (4, 4), (5, 5), (15, 8)),
              (6, 7, 8, 9, 11, 16), (6, 7), 9)


@contract.parity_oracle("K=1 returns the body UNCHANGED — the "
                        "per-iteration oracle executes the very same "
                        "closure, so K>1 is bit-parity by construction")
def _batch_iters(body, spec, k):
    """Wrap a fused step body in an outer lax.scan over `k` boosting
    iterations.  k == 1 returns the body unchanged — the per-iteration
    oracle executes the very same closure, so K>1 is bit-parity with it
    by construction (same ops, same order, iterated by the scan)."""
    if k <= 1:
        return body
    carry_map, xs_pos, ys_pos, n_out = spec

    def batched(*args):
        carry0 = tuple(args[i] for i, _ in carry_map)
        xs = tuple(args[i] for i in xs_pos)

        def scan_body(carry, x):
            call = list(args)
            for (i, _), v in zip(carry_map, carry):
                call[i] = v
            for i, v in zip(xs_pos, x):
                call[i] = v
            outs = body(*call)
            return (tuple(outs[o] for _, o in carry_map),
                    tuple(outs[o] for o in ys_pos))

        carry, ys = jax.lax.scan(scan_body, carry0, xs)
        out = [None] * n_out
        for (_, o), v in zip(carry_map, carry):
            out[o] = v
        for o, v in zip(ys_pos, ys):
            out[o] = v
        return tuple(out)
    return batched


# Device-dispatch accounting for bench.py (dispatches_per_tree): every
# training-path executable invocation notes itself here.  A host counter,
# not a guard — analysis/guards.py counts the transfers.
_DISPATCHES = 0


def _note_dispatch() -> None:
    global _DISPATCHES
    _DISPATCHES += 1


def dispatch_count() -> int:
    """Total training-path device dispatches this process has issued."""
    return _DISPATCHES


@contract.traced_pure
@contract.parity_oracle("the plain fused body: bag_compact=off / "
                        "masked-bagging oracle (PARITY.md §2.3)")
def _fused_step_body(grad_fn, grow_kw, lr, dtype, compact_rows=0):
    def step(scores, valid_scores, bag_mask, fmask, bins, valid_bins,
             gstate, stopped):
        bag = _unpack_bag(bag_mask, bins.shape[1])
        grad, hess = grad_fn(scores[0], gstate)
        dev_tree, leaf_id = grow_tree_bagged(
            bins, grad.astype(dtype), hess.astype(dtype),
            bag, fmask, bag_rows=compact_rows, **grow_kw)
        # deferred stump stop: once any tree fails to split, every later
        # step no-ops its score updates, so a late host flush truncates
        # at the exact reference stop point (gbdt.cpp:186) with scores
        # untouched past it — no per-iteration host sync needed even
        # with bagging/feature_fraction
        live = jnp.logical_not(stopped)
        stopped = stopped | (dev_tree.num_leaves <= 1)
        leaf_vals = jnp.where(live, dev_tree.leaf_value * lr,
                              0.0).astype(jnp.float32)
        scores = scores.at[0].add(leaf_vals[leaf_id])
        new_valid = []
        for vs, vbins in zip(valid_scores, valid_bins):
            vleaf = predict_leaf_binned(
                dev_tree.split_feature, dev_tree.threshold_bin,
                dev_tree.left_child, dev_tree.right_child, vbins)
            new_valid.append(vs.at[0].add(leaf_vals[vleaf]))
        ints, floats = _pack_tree(dev_tree)
        return scores, new_valid, ints, floats, stopped
    return step


@contract.traced_pure
@contract.fused_body(collectives=("all_gather", "axis_index", "pmax",
                                  "psum", "psum_scatter"))
def _make_fused_step(grad_fn, grow_kw, lr, dtype, compact_rows=0,
                     k_iters=1):
    body = _batch_iters(_fused_step_body(grad_fn, grow_kw, lr, dtype,
                                         compact_rows),
                        _SCAN_PLAIN, k_iters)
    return jax.jit(body, donate_argnums=(0, 1))


@contract.traced_pure
def _permute_window_rows(rel_w, m, n, bufs):
    """Window-local re-sort of row-major buffers (rows on the LAST
    axis) under bag compaction: gather positions [:m] by rel_w and keep
    the OOB tail as a contiguous copy — the tail-stays-in-place
    invariant that _bag_arrange_body and grow_tree_bagged rely on (tail
    rows never enter histograms, so their clustering is irrelevant and
    their gathers would be pure waste).  Returns (full-length rel for
    gstate permutation, permuted buffers)."""
    rel = jnp.concatenate([rel_w, jnp.arange(m, n, dtype=jnp.int32)])
    out = [jnp.concatenate([jnp.take(b[..., :m], rel_w, axis=-1),
                            b[..., m:]], axis=-1) for b in bufs]
    return rel, out


@contract.traced_pure
def _fused_step_body_reorder(grad_fn, grow_kw, lr, dtype,
                             permute_state=None, compact_rows=0):
    """The fused step PLUS the ordered-partition row re-sort: after the
    tree lands, rows are stably re-sorted by its leaf assignment so later
    trees' leaves stay block-clustered and the block-list sweeps
    (ops/grow.py ranged mode) touch few blocks.  Everything per-row
    (bins, scores, bag mask, objective state, the composed row order)
    comes back permuted in the SAME dispatch; valid sets and tree output
    are row-order-free.

    `permute_state` is the objective's make_permute_fn (how its
    grad_state follows the permutation — default: every leaf per-row on
    its last axis; lambdarank remaps its doc_idx row positions).

    `compact_rows` (bag compaction): only the static in-bag window
    re-sorts — its gathers scale with the bag, and the out-of-bag tail
    keeps its positions (tail rows never enter histograms, so their
    clustering is irrelevant)."""
    if permute_state is None:
        def permute_state(gstate, rel):
            return jax.tree_util.tree_map(
                lambda a: jnp.take(a, rel, axis=-1), gstate)

    def step(scores, valid_scores, bag_mask, fmask, bins, valid_bins,
             gstate, row_order, stopped):
        bag = _unpack_bag(bag_mask, bins.shape[1])
        grad, hess = grad_fn(scores[0], gstate)
        dev_tree, leaf_id = grow_tree_bagged(
            bins, grad.astype(dtype), hess.astype(dtype),
            bag, fmask, bag_rows=compact_rows, **grow_kw)
        live = jnp.logical_not(stopped)
        stopped = stopped | (dev_tree.num_leaves <= 1)
        leaf_vals = jnp.where(live, dev_tree.leaf_value * lr,
                              0.0).astype(jnp.float32)
        scores = scores.at[0].add(leaf_vals[leaf_id])
        new_valid = []
        for vs, vbins in zip(valid_scores, valid_bins):
            vleaf = predict_leaf_binned(
                dev_tree.split_feature, dev_tree.threshold_bin,
                dev_tree.left_child, dev_tree.right_child, vbins)
            new_valid.append(vs.at[0].add(leaf_vals[vleaf]))
        ints, floats = _pack_tree(dev_tree)
        n = bins.shape[1]
        if 0 < compact_rows < n:
            # window-local stable sort; the OOB tail stays in place and
            # every gather below touches only the window
            m = compact_rows
            rel_w = jnp.argsort(leaf_id[:m], stable=True).astype(jnp.int32)
            rel, (bins_new, scores, bag_new, order_new) = \
                _permute_window_rows(rel_w, m, n,
                                     [bins, scores, bag, row_order])
        else:
            # stable sort by this tree's leaves; padded rows ride along
            # via their tracked leaf_id and stay permanently out-of-bag
            # through the permuted bag mask
            rel = jnp.argsort(leaf_id, stable=True).astype(jnp.int32)
            bins_new = jnp.take(bins, rel, axis=1)
            scores = jnp.take(scores, rel, axis=1)
            bag_new = jnp.take(bag, rel)
            order_new = jnp.take(row_order, rel)
        gstate_new = permute_state(gstate, rel)
        return (scores, new_valid, ints, floats, bins_new, bag_new,
                gstate_new, order_new, stopped)
    return step


@contract.traced_pure
@contract.fused_body(extras=("order",),
                     collectives=("all_gather", "axis_index", "pmax",
                                  "psum", "psum_scatter"))
def _make_fused_step_reorder(grad_fn, grow_kw, lr, dtype,
                             permute_state=None, compact_rows=0,
                             k_iters=1):
    # gstate is NOT donated: on the first re-sort it aliases the
    # objective's own arrays, which must stay valid for metrics/restarts
    body = _batch_iters(_fused_step_body_reorder(grad_fn, grow_kw, lr,
                                                 dtype, permute_state,
                                                 compact_rows),
                        _SCAN_REORDER, k_iters)
    return jax.jit(body, donate_argnums=(0, 1, 2, 4, 7))


def _dart_layout(L):
    """Packed-row slice offsets for the DART device bank (the _pack_tree
    wire layout): int row [1 | sf | tb | lc | rc | lp | ld | lcnt],
    float row [sg | leaf_value | iv]."""
    SF0 = 1
    TB0 = SF0 + (L - 1)
    LC0 = TB0 + (L - 1)
    RC0 = LC0 + (L - 1)
    RC1 = RC0 + (L - 1)
    LV0, LV1 = L - 1, 2 * L - 1
    return SF0, TB0, LC0, RC0, RC1, LV0, LV1


@contract.traced_pure
@contract.fused_body(extras=("bank", "dart"),
                     collectives=("all_gather", "axis_index", "pmax",
                                  "psum", "psum_scatter"))
def _make_fused_step_dart(grad_fn, grow_kw, dtype, max_leaves,
                          compact_rows=0, k_iters=1):
    """Fused DART iteration over a DEVICE-RESIDENT tree bank (VERDICT r3
    weak #5: DART previously paid ~6 host dispatches + a blocking tree
    flush per iteration for its drop/normalize score surgery).  The bank
    holds every trained tree's packed int/float rows on device; one
    dispatch per iteration performs, in the reference's exact order
    (dart.hpp:86-129):

      1. drop phase — for each dropped tree (ascending): shrinkage(-1)
         persisted in the bank + train-score add;
      2. gradients from the dropped scores, grow the new tree with the
         iteration's 1/(1+k) shrinkage (a TRACED scalar, so every drop
         count shares this executable), score/valid updates, bank append;
      3. normalize — per dropped tree: shrinkage(rate) + VALID add, then
         shrinkage(-k) + TRAIN add, both persisted.

    The in-bank value mutations run in the histogram dtype: bit-exact
    under the float64 parity configuration; under f32 they feed SCORE
    updates only within the usual f32-ulp policy — the MODEL's leaf
    values are reproduced on the host by replaying each tree's recorded
    drop-factor chain in float64 (DART._materialize_bank), exactly the
    host/reference tree->Shrinkage sequence, so long drop histories
    cannot drift the saved model.

    The drop list pads to a FIXED cap with lax.cond-skipped slots, so
    one executable serves every drop count (a shape-per-count design
    measured 3 mid-loop recompiles per bench run).  The device `stopped`
    flag gates every phase, so deferred host flushes truncate at the
    exact reference stop point.

    Leaf assignments are CACHED per tree (leaf_bank / per-valid-set
    vbanks) at training time: tree structure never changes after
    training, so the drop/normalize adds gather a [L] value table by the
    cached ids instead of re-descending every row per dropped tree —
    the descent's per-level [N] gathers measured ~6x the gather-only
    cost on TPU (r3 memory: gathers dominate; reformulate)."""
    L = max_leaves
    SF0, TB0, LC0, RC0, RC1, LV0, LV1 = _dart_layout(L)

    def step(scores, valid_scores, bank_i, bank_f, leaf_bank, vbanks,
             drop_idx, drop_mask, lr, kf, bag_mask, fmask, bins,
             valid_bins, gstate, stopped, t_row):
        live = jnp.logical_not(stopped)

        def drop_body(carry, xs):
            sc, bf = carry
            j, m = xs

            def do(sc, bf):
                v1 = -bf[j, LV0:LV1]
                leaf = leaf_bank[j].astype(jnp.int32)
                sc = sc.at[0].add(v1.astype(jnp.float32)[leaf])
                return sc, bf.at[j, LV0:LV1].set(v1)

            sc, bf = jax.lax.cond(m & live, do, lambda sc, bf: (sc, bf),
                                  sc, bf)
            return (sc, bf), None

        (scores, bank_f), _ = jax.lax.scan(drop_body, (scores, bank_f),
                                           (drop_idx, drop_mask))

        bag = _unpack_bag(bag_mask, bins.shape[1])
        grad, hess = grad_fn(scores[0], gstate)
        dev_tree, leaf_id = grow_tree_bagged(bins, grad.astype(dtype),
                                             hess.astype(dtype), bag,
                                             fmask,
                                             bag_rows=compact_rows,
                                             **grow_kw)
        stopped = stopped | (dev_tree.num_leaves <= 1)
        leaf_vals = jnp.where(live, dev_tree.leaf_value * lr,
                              0.0).astype(jnp.float32)
        scores = scores.at[0].add(leaf_vals[leaf_id])
        wrow = jnp.where(live, t_row, bank_i.shape[0] - 1)  # dead -> dummy
        new_valid = []
        new_vbanks = []
        for vs, vbins, vb in zip(valid_scores, valid_bins, vbanks):
            vleaf = predict_leaf_binned(
                dev_tree.split_feature, dev_tree.threshold_bin,
                dev_tree.left_child, dev_tree.right_child, vbins)
            new_valid.append(vs.at[0].add(leaf_vals[vleaf]))
            new_vbanks.append(vb.at[wrow].set(
                vleaf.astype(leaf_bank.dtype)))
        ints, floats = _pack_tree(dev_tree)
        # the bank row holds the tree's CURRENT (shrunk) leaf values,
        # like the reference's in-memory trees; the RETURNED floats stay
        # raw — the host applies the iteration's shrinkage in f64 like
        # every other fused path, so materialized models carry no extra
        # device-dtype rounding
        bank_row_f = floats.at[LV0:LV1].set(dev_tree.leaf_value[:-1] * lr)
        bank_i = bank_i.at[wrow].set(ints)
        bank_f = bank_f.at[wrow].set(bank_row_f)
        leaf_bank = leaf_bank.at[wrow].set(leaf_id.astype(leaf_bank.dtype))

        def norm_body(carry, xs):
            sc, vss, bf = carry
            j, m = xs

            def do(sc, vss, bf):
                v2 = bf[j, LV0:LV1] * lr
                new_vss = []
                for vs, vb in zip(vss, new_vbanks):
                    vleaf = vb[j].astype(jnp.int32)
                    new_vss.append(
                        vs.at[0].add(v2.astype(jnp.float32)[vleaf]))
                v3 = v2 * (-kf)
                leaf = leaf_bank[j].astype(jnp.int32)
                sc = sc.at[0].add(v3.astype(jnp.float32)[leaf])
                return sc, tuple(new_vss), bf.at[j, LV0:LV1].set(v3)

            sc, vss, bf = jax.lax.cond(
                m & live, do, lambda sc, vss, bf: (sc, vss, bf),
                sc, vss, bf)
            return (sc, vss, bf), None

        (scores, vss, bank_f), _ = jax.lax.scan(
            norm_body, (scores, tuple(new_valid), bank_f),
            (drop_idx, drop_mask))
        # ints/floats (the AS-TRAINED packed tree, before any later drop
        # mutation) also return to the host: materialization needs the
        # pristine values for the f64 factor replay, with no bank pull
        return (scores, list(vss), bank_i, bank_f, leaf_bank,
                list(new_vbanks), ints, floats, stopped)
    return jax.jit(_batch_iters(step, _SCAN_DART, k_iters),
                   donate_argnums=(0, 1, 2, 3, 4, 5))


@contract.traced_pure
def _fused_step_multi_body(grad_fn, grow_kw, lr, dtype, reorder=False,
                           permute_state=None, compact_rows=0):
    """Fused MULTICLASS iteration (VERDICT r3 #4): gradients for all K
    classes from the pre-iteration scores, then a class-wise lax.scan
    grows the K per-iteration trees in ONE dispatch — the reference's
    per-class tree loop (gbdt.cpp:177-197) without K host round trips or
    the per-iteration flush.  The scanned `stopped` flag no-ops score
    updates after the first 1-leaf stump (including LATER CLASSES of the
    same iteration), so a deferred host flush truncates at the exact
    reference stop point with scores untouched past it — the multiclass
    extension of the single-class deferral argument.

    bag_masks [K, N] bool and fmasks [K, F] bool are per-class (each
    class draws its own mt19937 masks, one TreeLearner per class in the
    reference, gbdt.cpp:38-45).

    `reorder` (round 4) extends the ordered-partition growth to
    multiclass with ONE shared row order sorted by the JOINT leaf key —
    a stable lexicographic sort over all K of this iteration's leaf
    assignments.  The K trees differ, but they are correlated (they
    model the same data), so the joint cells are homogeneous in EVERY
    class: measured at the 1M x 28 bench, the joint order cuts
    block-sweeps ~10x for every class — better even than giving each
    class its own order, and it needs no per-iteration gathers (a
    per-class-orders prototype spent more on [F, N] gathers than the
    clustered sweeps saved; gathers run ~100x off HBM bandwidth on
    TPU).  All per-row state (scores [K, N], bins, bag masks, the
    objective's onehot/weights, the composed row order) permutes in
    the SAME dispatch, exactly like the single-class reorder step."""
    def step(scores, valid_scores, bag_masks, fmasks, bins, valid_bins,
             gstate, stopped, *row_order):
        grad, hess = grad_fn(scores, gstate)            # [K, N] each
        num_class = grad.shape[0]

        def body(carry, xs):
            sc, vss, stop = carry
            cls, g, h, bag, fm = xs
            dev_tree, leaf_id = grow_tree_bagged(
                bins, g.astype(dtype), h.astype(dtype), bag, fm,
                bag_rows=compact_rows, **grow_kw)
            live = jnp.logical_not(stop)
            stop = stop | (dev_tree.num_leaves <= 1)
            leaf_vals = jnp.where(live, dev_tree.leaf_value * lr,
                                  0.0).astype(jnp.float32)
            sc = sc.at[cls].add(leaf_vals[leaf_id])
            new_vss = []
            for vs, vbins in zip(vss, valid_bins):
                vleaf = predict_leaf_binned(
                    dev_tree.split_feature, dev_tree.threshold_bin,
                    dev_tree.left_child, dev_tree.right_child, vbins)
                new_vss.append(vs.at[cls].add(leaf_vals[vleaf]))
            ints, floats = _pack_tree(dev_tree)
            ys = ((ints, floats, leaf_id) if reorder else (ints, floats))
            return (sc, tuple(new_vss), stop), ys

        (scores, vss, stopped), ys = jax.lax.scan(
            body, (scores, tuple(valid_scores), stopped),
            (jnp.arange(num_class, dtype=jnp.int32), grad, hess,
             bag_masks, fmasks))
        if not reorder:
            ints_k, floats_k = ys
            return scores, list(vss), ints_k, floats_k, stopped
        ints_k, floats_k, leaf_k = ys                   # leaf_k [K, N]
        # stable lexicographic sort, class 0 primary: chained stable
        # argsorts from the least-significant class up (np.lexsort's
        # construction), composing the relative permutation.  Under bag
        # compaction only the static union window re-sorts; the OOB
        # tail keeps its positions (it never enters histograms)
        n = bins.shape[1]
        m = compact_rows if 0 < compact_rows < n else n
        rel = jnp.argsort(leaf_k[num_class - 1, :m],
                          stable=True).astype(jnp.int32)
        for k in range(num_class - 2, -1, -1):
            keys = jnp.take(leaf_k[k, :m], rel)
            rel = jnp.take(rel, jnp.argsort(keys,
                                            stable=True).astype(jnp.int32))
        if m < n:
            # window-local gathers + contiguous tail copy, like the
            # single-class reorder branch — only gstate needs the
            # composed full-length permutation (doc_idx remaps etc.)
            rel, (bins_new, scores, bag_new, order_new) = \
                _permute_window_rows(rel, m, n, [bins, scores, bag_masks,
                                                 row_order[0]])
        else:
            bins_new = jnp.take(bins, rel, axis=1)
            scores = jnp.take(scores, rel, axis=1)
            bag_new = jnp.take(bag_masks, rel, axis=1)
            order_new = jnp.take(row_order[0], rel)
        gstate_new = (permute_state(gstate, rel) if permute_state
                      is not None else jax.tree_util.tree_map(
                          lambda a: jnp.take(a, rel, axis=-1), gstate))
        return (scores, list(vss), ints_k, floats_k, stopped,
                bins_new, bag_new, gstate_new, order_new)
    return step


@contract.traced_pure
@contract.fused_body(extras=("order",),
                     collectives=("all_gather", "axis_index", "pmax",
                                  "psum", "psum_scatter"))
def _make_fused_step_multi(grad_fn, grow_kw, lr, dtype, reorder=False,
                           permute_state=None, compact_rows=0, k_iters=1):
    # gstate is NOT donated: on the first re-sort it aliases the
    # objective's own arrays (same constraint as the single-class
    # reorder step)
    body = _batch_iters(
        _fused_step_multi_body(grad_fn, grow_kw, lr, dtype, reorder,
                               permute_state, compact_rows),
        _SCAN_MULTI_REORDER if reorder else _SCAN_MULTI, k_iters)
    return jax.jit(body,
                   donate_argnums=(0, 1, 2, 4, 8) if reorder else (0, 1))


@contract.traced_pure
@contract.fused_body(extras=("order",),
                     collectives=("all_gather", "axis_index", "pmax",
                                  "psum", "psum_scatter"))
def _make_fused_step_multi_sharded(grad_fn, grow_kw, lr, dtype, mesh,
                                   n_valid, gstate_specs, reorder,
                                   permute_state=None, compact_rows=0,
                                   k_iters=1):
    """The multiclass fused step under shard_map for single-host
    tree_learner=data (VERDICT r4 #3): the class-wise scan body already
    threads psum_axis through grow_kw, so sharding it is the same
    transform as the single-class _make_fused_step_sharded — per-row
    state ([K, N] scores/bag masks, bins, gradient state, row order)
    shards along the data axis, valid sets and the K packed trees are
    replicated, and the joint-leaf-key re-sort stays SHARD-LOCAL."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    # the scan wraps the BODY, inside shard_map: each shard iterates its
    # rows through the K steps, collectives stay per-step, and the
    # replicated specs (P()) cover the [K, ...] xs/ys at any rank
    body = _batch_iters(
        _fused_step_multi_body(grad_fn, grow_kw, lr, dtype, reorder,
                               permute_state, compact_rows),
        _SCAN_MULTI_REORDER if reorder else _SCAN_MULTI, k_iters)
    row = P(DATA_AXIS)
    row2 = P(None, DATA_AXIS)
    rep = P()
    vrep = [rep] * n_valid
    common_in = (row2, vrep, row2, rep, row2, tuple(vrep), gstate_specs,
                 rep)
    if reorder:
        in_specs = common_in + (row,)
        out_specs = (row2, vrep, rep, rep, rep, row2, row2, gstate_specs,
                     row)
        donate = (0, 1, 2, 4, 8)
    else:
        in_specs = common_in
        out_specs = (row2, vrep, rep, rep, rep)
        donate = (0, 1)
    fn = shard_map(body, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs)
    return jax.jit(fn, donate_argnums=donate)


@contract.traced_pure
@contract.fused_body(extras=("order",),
                     collectives=("all_gather", "axis_index", "pmax",
                                  "psum", "psum_scatter"))
def _make_fused_step_sharded(grad_fn, grow_kw, lr, dtype, mesh,
                             n_valid, gstate_specs, reorder,
                             permute_state=None, compact_rows=0,
                             k_iters=1):
    """The fused step under shard_map for single-host tree_learner=data
    (VERDICT r3 #2): per-row state (scores row, bins, bag mask, gradient
    state, row order) shards along the data axis, valid sets and tree
    outputs are replicated, and the ordered-partition re-sort — when
    `reorder` — stays SHARD-LOCAL (each shard leaf-clusters its own
    rows; grow_tree's psum'd histograms are order-invariant within a
    shard, so the tree is identical to the unordered sharded tree).

    Multi-host keeps the general path: its per-row state is process-
    local and reassembled per tree (models/gbdt.py _train_tree)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    body = (_batch_iters(_fused_step_body_reorder(grad_fn, grow_kw, lr,
                                                  dtype, permute_state,
                                                  compact_rows),
                         _SCAN_REORDER, k_iters)
            if reorder
            else _batch_iters(_fused_step_body(grad_fn, grow_kw, lr,
                                               dtype, compact_rows),
                              _SCAN_PLAIN, k_iters))
    row = P(DATA_AXIS)
    row2 = P(None, DATA_AXIS)
    rep = P()
    vrep = [rep] * n_valid
    common_in = (row2, vrep, row, rep, row2, tuple(vrep), gstate_specs)
    if reorder:
        in_specs = common_in + (row, rep)
        out_specs = (row2, vrep, rep, rep, row2, row, gstate_specs,
                     row, rep)
        donate = (0, 1, 2, 4, 7)
    else:
        in_specs = common_in + (rep,)
        out_specs = (row2, vrep, rep, rep, rep)
        donate = (0, 1)
    fn = shard_map(body, mesh=mesh, in_specs=in_specs,
                   out_specs=out_specs)
    return jax.jit(fn, donate_argnums=donate)


@contract.traced_pure
def _bag_arrange_body(permute_state, multi):
    """In-bag-first stable arrangement of every per-row device buffer —
    the bag-compaction boundary step, ONE dispatch per re-bagging.  The
    arrangement is a plain row permutation (in-bag rows first, relative
    order preserved), so it composes with the ordered-partition
    machinery: the permuted `order` rides the same composed row order
    that metrics inversion, checkpointing and the general-path restore
    already understand.  Multiclass sorts by the UNION of the per-class
    masks (the static window bounds the union; each class still masks
    its own rows inside it)."""
    def arrange(bins, scores, mask, gstate, order, *bank):
        key = mask.any(axis=0) if multi else mask
        rel = jnp.argsort(jnp.logical_not(key),
                          stable=True).astype(jnp.int32)
        bins_new = jnp.take(bins, rel, axis=1)
        scores_new = jnp.take(scores, rel, axis=1)
        mask_new = (jnp.take(mask, rel, axis=1) if multi
                    else jnp.take(mask, rel))
        gstate_new = permute_state(gstate, rel)
        order_new = jnp.take(order, rel)
        out = (bins_new, scores_new, mask_new, gstate_new, order_new)
        for b in bank:   # DART leaf bank [T, N]: per-row on its last axis
            out += (jnp.take(b, rel, axis=1),)
        return out
    return arrange


def _make_bag_arrange(permute_state, multi, with_bank):
    # gstate is NOT donated (first arrangement aliases the objective's
    # own arrays); everything else is replaced by its permuted successor
    donate = (0, 1, 2, 4) + ((5,) if with_bank else ())
    return jax.jit(_bag_arrange_body(permute_state, multi),
                   donate_argnums=donate)


def _make_bag_arrange_sharded(permute_state, multi, mesh, gstate_specs):
    """The arrangement under shard_map: each shard sorts ITS OWN rows
    in-bag-first (rel is computed from the shard-local mask), so shard
    membership never changes and the grow step's psum invariants hold —
    every in-bag row lands in exactly one shard's static window."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, shard_map

    body = _bag_arrange_body(permute_state, multi)
    row = P(DATA_AXIS)
    row2 = P(None, DATA_AXIS)
    mspec = row2 if multi else row
    specs = (row2, row2, mspec, gstate_specs, row)
    fn = shard_map(body, mesh=mesh, in_specs=specs, out_specs=specs)
    return jax.jit(fn, donate_argnums=(0, 1, 2, 4))


class GBDT:
    name = "gbdt"

    def __init__(self, config: Config, train_data: Optional[Dataset],
                 objective: Optional[Objective],
                 training_metrics: Sequence[Metric] = ()):
        self.config = config
        self.train_data = train_data
        self.objective = objective
        self.num_class = config.num_class
        self.iter = 0
        self._models: List = []       # Tree | _PendingTree (see models prop)
        self._stopped = False
        self._fused_sharded = False
        self._mh_fused = False
        self._flush_every = 1   # recomputed below once bagging state is known
        self.num_used_model = 0
        self.early_stopping_round = config.early_stopping_round
        self.shrinkage_rate = config.learning_rate
        self.training_metrics = list(training_metrics)
        self.valid_data: List[Dataset] = []
        self.valid_metrics: List[List[Metric]] = []
        self.valid_bins_dev: List[jax.Array] = []
        self.valid_scores: List[jax.Array] = []
        self.best_iter: List[List[int]] = []
        self.best_score: List[List[float]] = []
        self.saved_upto = -1
        self._model_file = None

        # sigmoid only used for binary output transform (gbdt.cpp:60-65)
        self.sigmoid = -1.0
        if objective is not None and objective.name == "binary":
            self.sigmoid = config.sigmoid

        if train_data is None:
            self.max_feature_idx = 0
            self.label_idx = 0
            return

        n = train_data.num_data
        self.num_data = n
        self.max_feature_idx = train_data.num_total_features - 1
        self.label_idx = train_data.label_idx
        self.dtype = jnp.float64 if config.hist_dtype == "float64" else jnp.float32

        self.params = SplitParams(
            min_data_in_leaf=config.min_data_in_leaf,
            min_sum_hessian_in_leaf=config.min_sum_hessian_in_leaf,
            lambda_l1=config.lambda_l1,
            lambda_l2=config.lambda_l2,
            min_gain_to_split=config.min_gain_to_split)
        self.max_bin = int(train_data.max_num_bin)

        # histogram implementation: the Pallas radix kernel is the TPU fast
        # path (f32, uint8 bins, <=256 bins); XLA one-hot elsewhere
        impl = config.hist_impl
        if impl == "auto":
            on_accel = jax.devices()[0].platform != "cpu"
            impl = ("pallas" if (on_accel and self.max_bin <= 256
                                 and self.dtype == jnp.float32
                                 and train_data.bin_dtype == np.uint8)
                    else "xla")
            if on_accel and impl == "xla":
                # not silent: the parity configuration (hist_dtype=
                # float64) or wide bins forfeit the Pallas fast path
                log.warning(
                    "Histogram fast path (Pallas) disabled on this "
                    "accelerator (max_bin=%d, hist_dtype=%s, bins dtype "
                    "%s); using the slower XLA one-hot path"
                    % (self.max_bin, config.hist_dtype,
                       train_data.bin_dtype))
        self.hist_impl = impl
        row_unit = 1
        if impl == "pallas":
            # import lazily: XLA-only installs never touch Pallas
            from ..ops.hist_pallas import PALLAS_ROW_BLOCK
            if self.max_bin > 256:
                log.fatal("hist_impl=pallas requires max_bin <= 256 "
                          "(got %d); use hist_impl=xla" % self.max_bin)
            if self.dtype != jnp.float32:
                log.fatal("hist_impl=pallas accumulates in float32; "
                          "hist_dtype=%s is incompatible" % config.hist_dtype)
            if train_data.bin_dtype != np.uint8:
                log.fatal("hist_impl=pallas requires uint8 bins")
            row_unit = PALLAS_ROW_BLOCK
        # fused histogram+gain kernel (config.hist_fused) and Pallas
        # accumulator mode (config.hist_acc).  hist_fused=off keeps the
        # two-op oracle; auto rides the Pallas fast path (ops/grow.py
        # additionally gates fusion to the serial child sweeps — the
        # parallel learners must cross shards between build and scan).
        self.hist_acc = config.hist_acc
        if self.hist_acc != "f32":
            if impl != "pallas":
                log.fatal("hist_acc=%s requires the Pallas histogram "
                          "kernel (hist_impl resolved to %s)"
                          % (self.hist_acc, impl))
            if config.tree_learner != "serial":
                log.fatal("hist_acc=%s is serial-learner only (the "
                          "mesh growers keep the f32 parity "
                          "accumulators)" % self.hist_acc)
        if config.hist_fused == "on" and impl != "pallas":
            log.fatal("hist_fused=on requires the Pallas histogram "
                      "kernel (hist_impl resolved to %s)" % impl)
        if config.hist_fused == "on" and config.hist_compact == "on":
            # same perf-expectation class as the learner warning below:
            # the compaction path gathers its own rows and keeps the
            # two-op scan, so forcing fusion next to it does nothing
            log.warning("hist_fused=on: the small-leaf compaction path "
                        "(hist_compact=on) gathers its own row buffers "
                        "and keeps the two-op scan — fusion disengages")
        if config.hist_fused == "on" and config.tree_learner != "serial":
            # a warning, not a fatal (unlike hist_acc): the two-op path
            # the parallel learners keep is BIT-identical to the fused
            # one — only the perf expectation is wrong, not the numbers
            log.warning("hist_fused=on: the fused histogram+gain scan "
                        "is serial-learner only (the parallel learners "
                        "must cross shards between build and scan); "
                        "tree_learner=%s keeps the two-op path"
                        % config.tree_learner)
        self.hist_fused = (config.hist_fused != "off"
                           and impl == "pallas")

        # data-parallel: shard rows over a device mesh (parallel/mesh.py),
        # replacing the reference's socket/MPI histogram reduce-scatter.
        # Rows are padded so each shard's slice is a multiple of the Pallas
        # row block; padded rows are permanently out-of-bag.
        self.grower = None
        self.rows_sharded = False
        self._mh = False
        self._feat_mh = False
        row_unit_base = row_unit   # per-shard row alignment (Pallas block)
        self._row_unit_base = row_unit_base
        if config.tree_learner in ("data", "voting"):
            from ..parallel.mesh import ShardedGrower, make_mesh
            mesh = make_mesh(config.num_shards)
            self.grower = ShardedGrower(
                mesh, max_leaves=max(config.num_leaves, 2),
                max_bin=self.max_bin, params=self.params,
                max_depth=config.max_depth,
                voting_top_k=(config.top_k
                              if config.tree_learner == "voting" else 0),
                hist_impl=impl, hist_agg=config.hist_agg)
            row_unit *= self.grower.num_shards
            self.rows_sharded = True
            # multi-host: every process pads its LOCAL rows to the same
            # length (max local row count) so the global assembly via
            # make_array_from_process_local_data has equal blocks; all
            # other state (scores, objective, metrics, bagging) stays
            # process-local, matching the reference's locality (its
            # metrics/objectives never touch Network:: either)
            self._mh = jax.process_count() > 1
            if self._mh:
                from ..parallel.dist import process_allgather
                all_n = process_allgather(np.asarray([n], dtype=np.int64))
                self._n_pad_base = int(np.max(all_n))
        elif config.tree_learner == "feature":
            # multi-host feature parallel since round 3 (the reference's
            # multi-machine FeatureParallelTreeLearner): every process
            # loads ALL rows (cli.init_train forces row_shards=1), the
            # bin matrix splits along F across all hosts' devices, and
            # the best-split all-gather + argmax crosses hosts over DCN
            from ..parallel.mesh import (FeatureShardedGrower, make_mesh,
                                         FEATURE_AXIS)
            mesh = make_mesh(config.num_shards, FEATURE_AXIS)
            self.grower = FeatureShardedGrower(
                mesh, max_leaves=max(config.num_leaves, 2),
                max_bin=self.max_bin, params=self.params,
                max_depth=config.max_depth, hist_impl=impl)
            self._feat_mh = jax.process_count() > 1
        # bounded histogram working set (the reference HistogramPool's
        # role, feature_histogram.hpp:275-398): translate the MB budget
        # into a slot count of [F, max_bin, 3] leaf histograms for the
        # on-device LRU pool in ops/grow.py.  Parallel learners ignore it
        # (config already reset it, mirroring config.cpp:167-175).
        self.hist_slots = 0
        if config.histogram_pool_size >= 0 and self.grower is None:
            entry = (train_data.num_features * self.max_bin * 3
                     * np.dtype(self.dtype).itemsize)
            k = int(config.histogram_pool_size * 1024 * 1024
                    / max(entry, 1))
            k = max(2, k)   # smaller/larger pair minimum, like the pool's
            if k <= max(config.num_leaves, 2):
                self.hist_slots = k

        # tree_learner=data can run the fused step (and the ordered
        # partition below) under shard_map: every per-row array shards
        # along the data axis and re-sorts stay shard-local
        # (_make_fused_step_sharded).  Since round 5 this includes
        # MULTI-HOST (VERDICT r4 #2): per-row state is assembled into
        # global sharded arrays ONCE (scores/objective state/bag masks),
        # the fused dispatch keeps gradients on device, and per-iteration
        # host traffic drops to O(packed tree) — the per-tree
        # [N_local] grad/hess device->host->device round trip of the
        # general path is gone.  Multi-host additionally needs a
        # row-shardable traceable objective up front (the general path
        # cannot hand its local scores to a global fused step
        # mid-training, so the choice is made here, not per-iteration);
        # voting keeps the general path (its per-split protocol is
        # latency-bound anyway).
        mh_fusible = (type(self) is GBDT
                      and objective is not None
                      and getattr(objective, "jax_traceable", False)
                      and getattr(objective, "row_shardable", False)
                      and objective.fused_key() is not None)
        self._fused_sharded = (self.rows_sharded
                               and config.tree_learner == "data"
                               and (not self._mh or mh_fusible))
        self._mh_fused = self._mh and self._fused_sharded

        # query-granular row layout: an objective whose grad_state is
        # NOT per-row (lambdarank's query blocks) provides its own row
        # placement for the fused sharded step — shard s's contiguous
        # device block holds whole queries padded to a common capacity,
        # so each shard computes its queries' pairwise lambdas locally
        # and only histograms cross shards (the reference's rank + data-
        # parallel locality, data_parallel_tree_learner.cpp:124-187).
        # None for elementwise objectives (default contiguous blocks).
        self._shard_layout = None
        self._layout_active = False
        if (self._fused_sharded and config.tree_learner == "data"
                and objective is not None
                and getattr(objective, "jax_traceable", False)):
            # capacity alignment: the Pallas row block, times the
            # process count under multi-host so every process's local
            # block (cap * local_shards) divides over the GLOBAL device
            # count (shard_bins/_put_sharded equal-block requirement)
            align = row_unit_base * (jax.process_count() if self._mh
                                     else 1)
            self._shard_layout = objective.shard_layout(
                self.grower.local_shard_count(), align, self._mh)
            self._layout_active = self._shard_layout is not None
        self._gstate_specs = None

        if self._shard_layout is not None:
            # local padded rows = per-shard capacity x local shards;
            # every process agrees on the capacity (synced in the
            # layout builder), so multi-host blocks stay equal
            self.n_pad = self._shard_layout.n_pad
        else:
            n_for_pad = self._n_pad_base if self._mh else n
            self.n_pad = ((n_for_pad + row_unit - 1) // row_unit) \
                * row_unit

        # small-leaf row compaction (ops/grow.py hist_small): serial
        # learner only, f32 only — the f64 parity configuration keeps the
        # full-sweep accumulation grouping the golden logs pin.
        # EXPERIMENTAL opt-in: on current TPUs the XLA gather/scatter row
        # selection costs more per split than the near-peak-MXU full
        # sweep it avoids (measured 4.5x slower at 1Mx28 — BASELINE.md)
        self.hist_compact = 0
        if (config.hist_compact == "on" and self.grower is None
                and self.dtype == jnp.float32):
            half = max(self.n_pad // 2, 1)
            self.hist_compact = ((half + row_unit - 1)
                                 // row_unit) * row_unit

        # ordered-partition growth (pallas learner, serial or single-host
        # data-parallel): block-list sweeps are always on (bit-identical
        # to full sweeps for a fixed row order — empty blocks contribute
        # exact zeros); the row re-sort that makes them leaf-proportional
        # additionally needs the fused path and a permutable objective.
        # Bagging composes: the in/out-of-bag draw stays pinned to FILE
        # order (mt19937 parity) and the mask permutes on device per
        # re-bagging (_bag_mask_dev_fused).
        self.hist_ranged = (config.hist_ordered != "off"
                            and impl == "pallas"
                            and (self.grower is None
                                 or self._fused_sharded))
        if config.hist_compact == "on" and self.hist_ranged:
            log.warning("hist_compact=on disables hist_ordered "
                        "(mutually exclusive row-selection strategies)")
            self.hist_ranged = False
        self.reorder_every = max(int(config.hist_reorder_every), 1)
        self._row_order = None        # [n_pad] i32 device; None = identity
        self._inv_order = None        # cached device inverse of the above
        self._gstate_override = None
        self._trees_since_reorder = 0

        # out-of-core ingest (ingest/ShardedDataset): feed the device
        # one shard window at a time — the full [F, N] matrix never
        # exists on the host.  The query-granular layout still needs a
        # host scatter (place()), so it takes the materializing
        # fallback (ShardedDataset.bins logs it); so does the FEATURE-
        # sharded learner, whose grower splits F (every rank holds all
        # rows by that learner's premise — out-of-core row feeding
        # cannot help it).
        streamed = (getattr(train_data, "is_shard_backed", False)
                    and self._shard_layout is None
                    and (self.grower is None or self.rows_sharded))
        bins = None if streamed else train_data.bins
        self.scores = self._init_scores(train_data, n)
        if self._shard_layout is not None:
            # query-granular layout: file rows scatter into per-shard
            # blocks; gap rows (like trailing pad rows) stay permanently
            # out-of-bag and their scores are never read
            bins = self._shard_layout.place(bins)
            self.scores = jnp.asarray(
                self._shard_layout.place(np.asarray(self.scores)))
        elif self.n_pad != n:
            if bins is not None:
                bins = np.pad(bins, ((0, 0), (0, self.n_pad - n)))
            self.scores = jnp.pad(self.scores,
                                  ((0, 0), (0, self.n_pad - n)))
        if self.grower is not None:
            self.bins_dev = (self._put_bins_sharded_streamed(train_data)
                             if streamed
                             else self.grower.shard_bins(bins))
            if self.rows_sharded and not self._mh:
                # single-host: shard scores so the leaf_id gather-add
                # stays on-device
                self.scores = jax.device_put(
                    self.scores, self.grower.row_sharding_2d())
            elif self._mh_fused:
                # multi-host fused: scores become a GLOBAL row-sharded
                # array once — every later iteration touches them only
                # inside the fused dispatch (process p's file rows live
                # at global positions [p*n_pad, (p+1)*n_pad))
                self.scores = self.grower.shard_rows(
                    np.asarray(self.scores), self.n_pad)
        else:
            self.bins_dev = (self._put_bins_streamed(train_data)
                             if streamed else jnp.asarray(bins))
        if objective is not None and self.n_pad != n:
            objective.pad_to(self.n_pad)

        # bagging state (gbdt.cpp:70-79); padded rows stay False forever
        self.bagging_enabled = (config.bagging_fraction < 1.0
                                and config.bagging_freq > 0)
        # 1-leaf-stump stop detection is batched: fetching num_leaves every
        # iteration costs a device->host roundtrip (tens of ms on remote-
        # attached TPUs) that would serialize the async dispatch pipeline.
        # Deferral is only sound when a stump implies every later tree is
        # an identical zero-valued stump (so late truncation at the next
        # flush reproduces the reference's stop point, gbdt.cpp:186, with
        # no numerical difference): single-class, no bagging, no
        # feature_fraction — under those, per-tree masks change and a real
        # tree can follow a stump, so flush every iteration.  DART sets 1
        # too (dropping needs host trees each iteration), and
        # train_one_iter forces a flush when gradients come from a custom
        # objective (their evolution is outside the soundness argument).
        # Since round 3, deferral is sound for bagged/feature-fraction
        # runs too: the fused step carries a DEVICE stopped flag — after
        # the first stump every later step no-ops its score updates, so
        # a late flush truncates at the exact reference stop point with
        # scores untouched past it (the earlier host-sync-per-iteration
        # requirement is gone).  Multiclass still flushes per iteration
        # (general path, per-class trees).
        # The general (non-fused) path has no device flag and still needs
        # the old soundness condition (no bagging / feature_fraction);
        # DART re-forces 1 in its own __init__.
        # Since round 4, the multiclass FUSED path is deferrable too: its
        # class-wise scan carries the same device stopped flag, so score
        # updates stop at the exact stump (including later classes of the
        # stump's iteration) and a late flush truncates correctly.
        deferrable = ((self.num_class == 1
                       and (self._can_fuse()
                            or (not self.bagging_enabled
                                and config.feature_fraction >= 1.0)))
                      or self._can_fuse_multi())
        self._flush_every = 16 if deferrable else 1
        # multi-host fused: every input of the global fused dispatch must
        # be a global array, including the scalar stopped flag
        self._dev_stopped = (self.grower.replicate(np.asarray(False))
                             if self._mh_fused else jnp.asarray(False))
        self.bag_rng = Mt19937Random(config.bagging_seed)
        # bag compaction (config.bag_compact): in-bag rows arranged into
        # a contiguous STATIC window at every re-bagging so the fused
        # step's histogram/grow work scales with bagging_fraction.  The
        # window size is computed lazily on first use (_bag_compact_rows
        # — DART's fusibility check needs its own __init__ to have run);
        # None = not computed yet, 0 = compaction off.
        self._bag_window = None
        self._bag_arranged = False     # device state currently in-bag-first
        self._bag_overflowed = False   # sharded margin overflow -> masked
        self.bag_masks = []
        for _ in range(self.num_class):
            m = np.zeros(self.n_pad, dtype=bool)
            m[:n] = True
            self.bag_masks.append(m)
        # sharded/device bag masks are cached; _bagging invalidates
        self._bag_dev = [None] * self.num_class
        self._bag_dev_packed = [None] * self.num_class
        self._bag_stacked = None    # [K, n_pad] stack (multiclass fused)
        # per-class feature-fraction RNG, all seeded feature_fraction_seed
        # (one TreeLearner per class in the reference, gbdt.cpp:38-45)
        self.feat_rngs = [Mt19937Random(config.feature_fraction_seed)
                          for _ in range(self.num_class)]

    # ------------------------------------------------------------------
    def _init_scores(self, data: Dataset, n: int) -> jax.Array:
        k = self.num_class
        if data.metadata.init_score is not None:
            init = np.asarray(data.metadata.init_score, dtype=np.float32)
            if init.size == n * k:
                return jnp.asarray(init.reshape(k, n))
            log.warning("init score size mismatch, ignoring")
        return jnp.zeros((k, n), dtype=jnp.float32)

    def _put_bins_streamed(self, ds) -> jax.Array:
        """Device bins assembled one shard window at a time (out-of-core
        ingest): each [F, k] window device_puts independently and the
        concatenation happens ON DEVICE, so peak host memory is
        2 + ingest_prefetch windows (queued + producer-staged +
        consumer-held) — the full matrix exists only in device memory,
        where training needs it anyway.

        Double-buffered since round 16 (config.ingest_prefetch): the
        windows arrive through a bounded background prefetch thread
        (ingest/shards.prefetch_windows), so the NEXT shard pages in
        from disk while the previous window's async device_put transfer
        is still in flight — the load phase overlaps host IO with
        host->device copy instead of alternating, and training then
        runs on the same device-resident state as the in-memory path
        (shard-fed steady == in-memory steady).  The prefetcher changes
        WHEN windows are staged, never their order or bytes: shard-fed
        models are byte-identical with overlap on or off (tested)."""
        from ..ingest.shards import prefetch_windows
        parts = [jax.device_put(w)
                 for w in prefetch_windows(ds.iter_bin_windows(),
                                           self.config.ingest_prefetch)]
        pad = self.n_pad - ds.num_data
        if pad > 0:
            parts.append(jnp.zeros((ds.num_features, pad),
                                   dtype=ds.bin_dtype))
        if len(parts) == 1:
            return parts[0]
        return jnp.concatenate(parts, axis=1)

    def _put_bins_sharded_streamed(self, ds) -> jax.Array:
        """Shard-window feeding for the data/voting-parallel growers.
        Multi-host: the global array assembles from this process's
        LOCAL block — the rank's manifest slice, 1/R of the data —
        which is the out-of-core scaling contract (each host pays for
        its slice, never the file).  Single-host: each mesh device's
        row block assembles on the host (peak: ONE block + one
        window) and device_puts straight to ITS device — no device
        ever stages the full matrix, so per-chip HBM holds 1/S of the
        data exactly like the host path's sharded placement.  The
        single-host leg stages its shard reads through the bounded
        background prefetch thread (config.ingest_prefetch) so disk IO
        overlaps the per-device transfers; the mh leg assembles its
        local block synchronously (its consumer does no per-window
        work, so prefetch would only add staged-window footprint —
        see ShardedDataset.local_bins_matrix)."""
        from ..ingest.shards import prefetch_windows
        if self._mh:
            local = ds.local_bins_matrix()
            if local.shape[1] < self.n_pad:
                local = np.pad(
                    local, ((0, 0), (0, self.n_pad - local.shape[1])))
            return self.grower.shard_bins(local)
        sharding = self.grower.bins_sharding()
        devs = list(self.grower.mesh.devices.flat)
        block = self.n_pad // len(devs)   # n_pad is row_unit*S-aligned
        f = ds.num_features
        cur = np.zeros((f, block), dtype=ds.bin_dtype)
        pieces = []
        fill = 0
        for w in prefetch_windows(ds.iter_bin_windows(),
                                  self.config.ingest_prefetch):
            o = 0
            k = w.shape[1]
            while o < k:
                take = min(block - fill, k - o)
                cur[:, fill:fill + take] = w[:, o:o + take]
                fill += take
                o += take
                if fill == block:
                    pieces.append(jax.device_put(cur,
                                                 devs[len(pieces)]))
                    cur = np.zeros((f, block), dtype=ds.bin_dtype)
                    fill = 0
        while len(pieces) < len(devs):   # trailing pad blocks (zeros)
            pieces.append(jax.device_put(cur, devs[len(pieces)]))
            cur = np.zeros((f, block), dtype=ds.bin_dtype)
        return jax.make_array_from_single_device_arrays(
            (f, self.n_pad), sharding, pieces)

    def add_valid_data(self, data: Dataset, metrics: Sequence[Metric]) -> None:
        if self.iter > 0:
            log.fatal("Cannot add validation data after training started")
        self.valid_data.append(data)
        self.valid_metrics.append(list(metrics))
        # multi-host fused: valid arrays enter the global fused dispatch
        # as REPLICATED globals (every process loaded the same valid
        # file, matching the reference's per-machine valid copy)
        put = (self.grower.replicate if self._mh_fused else jnp.asarray)
        self.valid_bins_dev.append(put(data.bins))
        k = self.num_class
        vn = data.num_data
        if (data.metadata.init_score is not None
                and np.asarray(data.metadata.init_score).size == vn * k):
            init = np.asarray(data.metadata.init_score, dtype=np.float32)
            self.valid_scores.append(put(init.reshape(k, vn)))
        else:
            self.valid_scores.append(put(np.zeros((k, vn),
                                                  dtype=np.float32)))
        if self.early_stopping_round > 0:
            self.best_iter.append([0] * len(metrics))
            self.best_score.append([-np.inf] * len(metrics))

    # ------------------------------------------------------------------
    def _bagging(self, it: int, cls: int) -> None:
        """GBDT::Bagging (gbdt.cpp:109-160): row- or query-granular
        reservoir selection, drawing from the shared bagging stream."""
        cfg = self.config
        if not self.bagging_enabled or it % cfg.bagging_freq != 0:
            return
        md = self.train_data.metadata
        n = self.num_data
        if md.query_boundaries is None:
            bag_cnt = int(cfg.bagging_fraction * n)
            mask = self.bag_rng.split_mask(n, bag_cnt)
        else:
            qb = md.query_boundaries
            nq = len(qb) - 1
            bag_query_cnt = int(nq * cfg.bagging_fraction)
            qmask = self.bag_rng.split_mask(nq, bag_query_cnt)
            mask = np.zeros(n, dtype=bool)
            for q in np.nonzero(qmask)[0]:
                mask[qb[q]:qb[q + 1]] = True
        padded = np.zeros(self.n_pad, dtype=bool)
        padded[:n] = mask
        self.bag_masks[cls] = padded
        self._bag_dev[cls] = None
        self._bag_dev_packed[cls] = None
        self._bag_stacked = None
        # a redraw invalidates the in-bag-first arrangement; the next
        # fused dispatch re-arranges (_ensure_bag_arranged)
        self._bag_arranged = False
        log.debug("Re-bagging, using %d data to train" % int(mask.sum()))

    def _feature_mask(self, cls: int) -> np.ndarray:
        f = self.train_data.num_features
        frac = self.config.feature_fraction
        if frac >= 1.0:
            return np.ones(f, dtype=bool)
        used_cnt = int(f * frac)
        idx = self.feat_rngs[cls].sample(f, used_cnt)
        mask = np.zeros(f, dtype=bool)
        mask[idx] = True
        return mask

    # ------------------------------------------------------------------
    def train_one_iter(self, gradients=None, hessians=None,
                       is_eval: bool = True) -> bool:
        """One boosting iteration (gbdt.cpp:169-205). Returns True when
        training must stop."""
        cfg = self.config
        if gradients is None and self._can_fuse():
            # fully-fused iteration: gradients -> grow -> score updates ->
            # tree packing in ONE dispatch with donated score buffers
            self._ensure_layout()
            self._bagging(self.iter, 0)
            self._ensure_bag_arranged()
            fmask = self._feature_mask(0)
            fmask_dev = (self.grower.replicate(fmask) if self._mh_fused
                         else jnp.asarray(fmask))
            self._models.extend(self._run_fused(
                self._bag_mask_dev_fused(0), fmask_dev))
        elif gradients is None and self._can_fuse_multi():
            # multiclass fused iteration: all K per-iteration trees in
            # one dispatch (class-wise scan, _make_fused_step_multi)
            self._models.extend(self._run_fused_multi())
        else:
            # leaving the fused path (custom gradients / objective swap):
            # gradients arrive in FILE order, so per-row state must be
            # restored to file order first or rows and gradients misalign
            self._restore_row_order()
            if gradients is None or hessians is None:
                grad, hess = self.objective.get_gradients(
                    self._score_for_gradients())
                if grad.ndim == 1:
                    grad = grad[None, :]
                    hess = hess[None, :]
            else:
                grad = jnp.asarray(gradients, dtype=jnp.float32).reshape(
                    self.num_class, self.num_data)
                hess = jnp.asarray(hessians, dtype=jnp.float32).reshape(
                    self.num_class, self.num_data)
                if self.n_pad != self.num_data:
                    pad = ((0, 0), (0, self.n_pad - self.num_data))
                    grad = jnp.pad(grad, pad)
                    hess = jnp.pad(hess, pad)
            for cls in range(self.num_class):
                self._bagging(self.iter, cls)
                fmask = self._feature_mask(cls)
                self._models.append(self._train_tree(
                    grad[cls], hess[cls], self._bag_mask_dev(cls), fmask,
                    cls))
        self.iter += 1
        self.num_used_model = len(self._models) // self.num_class
        custom_grads = gradients is not None
        if (custom_grads or self.iter % self._flush_every == 0) \
                and not is_eval:
            # multi-host: the stump stop must be OR-synced on the
            # non-eval flush paths too — a lone rank stopping would
            # leave the others blocked in their next collective.  The
            # eval path defers to eval_and_check_early_stopping, which
            # flushes (and syncs) first thing, so the collective runs
            # exactly once per iteration.
            if self._sync_stop(self._flush_pending()):
                log.info("Stopped training because there are no more leafs "
                         "that meet the split requirements.")
                return True
        if is_eval:
            return self.eval_and_check_early_stopping()
        return False

    def _grow_kw(self) -> dict:
        """The grower configuration shared by every training path (the
        three fused step builders and the general _train_tree); one
        definition so they cannot drift."""
        cfg = self.config
        return dict(max_leaves=max(cfg.num_leaves, 2),
                    max_bin=self.max_bin, params=self.params,
                    max_depth=cfg.max_depth, hist_impl=self.hist_impl,
                    hist_slots=self.hist_slots, compact=self.hist_compact,
                    ranged=self.hist_ranged, fused=self.hist_fused,
                    hist_acc=self.hist_acc)

    def _bag_mask_dev(self, cls: int):
        """Device/sharded bag mask, uploaded only when bagging changed it."""
        if self._bag_dev[cls] is None:
            mask = self.bag_masks[cls]
            if self.grower is not None:
                self._bag_dev[cls] = self.grower.shard_rows(mask, self.n_pad)
            else:
                self._bag_dev[cls] = jnp.asarray(mask)
        return self._bag_dev[cls]

    def _bag_mask_dev_packed(self, cls: int):
        """Bit-packed bag mask for the fused step (8x less transfer per
        re-bagging; the step unpacks on device).  The ordered-partition
        re-sort replaces this cache with an already-permuted bool mask —
        _unpack_bag passes bool through."""
        if self._bag_dev_packed[cls] is None:
            self._bag_dev_packed[cls] = jnp.asarray(
                np.packbits(self.bag_masks[cls]))
        return self._bag_dev_packed[cls]

    @contract.rank_uniform
    def _can_fuse(self) -> bool:
        """The fused single-dispatch iteration covers the single-class
        path with a jax-traceable objective (regression/binary) on the
        serial learner OR single-host tree_learner=data (shard_map
        variant, _make_fused_step_sharded); DART (per-iteration score
        surgery + varying shrinkage), custom gradients, multiclass,
        multi-host and voting/feature growers take the general path.
        The sharded variant additionally needs a row_shardable objective
        — elementwise grad_state shards along the data axis, and
        lambdarank's query-block state shards query-granularly through
        its own RowShardLayout (shard_layout/build_sharded_state), so
        rank runs the fused sharded step too; rank_impl=native keeps
        the general path (host gradients)."""
        return (type(self) is GBDT and self.num_class == 1
                and (self.grower is None
                     or (self._fused_sharded
                         and getattr(self.objective, "row_shardable",
                                     False)))
                and getattr(self.objective, "jax_traceable", False)
                and self.objective.fused_key() is not None)

    @contract.rank_uniform
    def _can_fuse_multi(self) -> bool:
        """The multiclass fused iteration (_make_fused_step_multi):
        serial learner OR tree_learner=data (the shard_map variant,
        _make_fused_step_multi_sharded — VERDICT r4 #3, single- AND
        multi-host since round 5), K > 1, traceable row-shardable
        objective.  DART overrides via type check (its per-iteration
        drop surgery needs host trees)."""
        return (type(self) is GBDT and self.num_class > 1
                and (self.grower is None
                     or (self._fused_sharded
                         and getattr(self.objective, "row_shardable",
                                     False)))
                and getattr(self.objective, "jax_traceable", False)
                and self.objective.fused_key() is not None)

    def _bag_masks_stacked_dev(self):
        """[K, n_pad] bool device stack of the per-class bag masks for
        the multiclass fused step; rebuilt only when re-bagging
        invalidated it (_bagging clears the cache).  Host masks stay in
        FILE order (mt19937 parity); under an active shared row order
        the rebuilt stack permutes once on device — the reorder step
        keeps the cached stack permuted thereafter."""
        if self._bag_stacked is None:
            stack = np.stack(self.bag_masks)
            # multi-host: local file-order draws assemble into the
            # global [K, N] row-sharded mask
            m = (self.grower.shard_rows(stack, self.n_pad)
                 if self._mh_fused else jnp.asarray(stack))
            if self._row_order is not None:
                if self.grower is not None:
                    # sharded fused multiclass: shard-local permute, not
                    # a cross-shard global gather
                    m = self.grower.permute_rows(m, self._row_order)
                else:
                    m = jnp.take(m, self._row_order, axis=1)
            self._bag_stacked = m
        return self._bag_stacked

    def _run_fused_multi(self, k_iters: int = 1):
        cfg = self.config
        lr = self.shrinkage_rate
        # per-iteration host draws in the exact sequential order: class-
        # wise bagging (a no-op past the segment's first iteration — the
        # scheduler ends segments at re-bag boundaries), then the K
        # per-class feature masks
        fmasks_list = []
        for j in range(k_iters):
            for cls in range(self.num_class):
                self._bagging(self.iter + j, cls)
            if j == 0:
                self._ensure_bag_arranged()
            fmasks_list.append(np.stack([self._feature_mask(c)
                                         for c in range(self.num_class)]))
        fmasks = (fmasks_list[0] if k_iters == 1
                  else np.stack(fmasks_list))
        # shared-joint-order ordered-partition growth (round 4): same
        # gate and cadence as the single-class reorder — re-sort after
        # the first iteration, then every reorder_every (hist_ranged
        # already requires serial or the fused sharded learner)
        reorder = self._reorder_now_multi()
        compact = self._bag_compact_rows() if self._bag_arranged else 0
        gstate = self._gstate_for_fused()
        key = ("multi", self.objective.fused_key(), lr, self.dtype,
               self.hist_impl, self.max_bin, max(cfg.num_leaves, 2),
               cfg.max_depth, self.params, len(self.valid_bins_dev),
               self.hist_slots, self.hist_compact, self.hist_ranged,
               self.hist_fused, self.hist_acc,
               reorder, compact, k_iters,
               (cfg.hist_agg, self.grower.num_shards,
                id(self.grower.mesh)) if self.grower is not None else None)

        def make():
            grow_kw = self._grow_kw()
            if self.grower is not None:
                # single-host tree_learner=data (VERDICT r4 #3): the
                # class-wise scan under shard_map, same protocol wiring
                # as the single-class sharded step
                from ..parallel.mesh import DATA_AXIS
                grow_kw.update(psum_axis=DATA_AXIS,
                               hist_agg=cfg.hist_agg,
                               num_shards=self.grower.num_shards,
                               voting_top_k=0)
                return _make_fused_step_multi_sharded(
                    self.objective.make_grad_fn(), grow_kw, lr,
                    self.dtype, self.grower.mesh,
                    len(self.valid_bins_dev),
                    self._fused_gspecs(gstate), reorder,
                    self.objective.make_permute_fn(), compact, k_iters)
            return _make_fused_step_multi(self.objective.make_grad_fn(),
                                          grow_kw, lr, self.dtype,
                                          reorder,
                                          self.objective.make_permute_fn(),
                                          compact, k_iters)

        fn = _get_fused_step(key, make)
        _note_dispatch()
        fmasks_dev = (self.grower.replicate(fmasks) if self._mh_fused
                      else jnp.asarray(fmasks))
        common = (self.scores, list(self.valid_scores),
                  self._bag_masks_stacked_dev(), fmasks_dev,
                  self.bins_dev, tuple(self.valid_bins_dev), gstate,
                  self._dev_stopped)
        if reorder:
            order = (self._row_order if self._row_order is not None
                     else self._identity_order_dev())
            (scores, valid, ints_k, floats_k, self._dev_stopped,
             self.bins_dev, self._bag_stacked, self._gstate_override,
             self._row_order) = fn(*common, order)
            self._inv_order = None
            self._trees_since_reorder = 0
        else:
            (scores, valid, ints_k, floats_k,
             self._dev_stopped) = fn(*common)
            self._trees_since_reorder += k_iters
        self.scores = scores
        self.valid_scores = list(valid)
        # device row slices stay unmaterialized: _flush_pending stacks
        # and pulls every pending tree in ONE transfer
        if k_iters == 1:
            return [_PendingTree(ints_k[c], floats_k[c], lr, gated=True)
                    for c in range(self.num_class)]
        return [_PendingTree(ints_k[j, c], floats_k[j, c], lr, gated=True)
                for j in range(k_iters) for c in range(self.num_class)]

    def _gstate_for_fused(self):
        """Gradient state for the fused dispatch: the cached permuted/
        global override when present, else the objective's own arrays —
        assembled ONCE into global row-sharded arrays under multi-host
        (the reorder steps keep the cached state permuted).  Under the
        query-granular layout the objective builds its shard-major state
        instead (lambdarank: per-shard query blocks with shard-local doc
        indices), placed once via put_spec."""
        gstate = self._gstate_override
        if gstate is None:
            if self._layout_active:
                host, specs = self._build_sharded_gstate_host()
                self._gstate_specs = specs
                gstate = tuple(self.grower.put_spec(a, sp)
                               for a, sp in zip(host, specs))
                self._gstate_override = gstate
                return gstate
            gstate = self.objective.grad_state()
            if self._mh_fused:
                gstate = jax.tree_util.tree_map(
                    lambda a: self.grower.shard_rows(np.asarray(a),
                                                     self.n_pad), gstate)
                self._gstate_override = gstate
        return gstate

    def _build_sharded_gstate_host(self):
        """(host_leaves, specs) of the objective's query-sharded state
        (multi-host syncs the block shapes so every process's put
        agrees)."""
        sync = None
        if self._mh_fused:
            from ..parallel.dist import sync_max_ints
            sync = sync_max_ints
        return self.objective.build_sharded_state(self._shard_layout,
                                                  sync=sync)

    def _identity_order_dev(self):
        """Initial ordered-partition row order: global POSITIONS
        (process p's file rows start at p * n_pad under the equal-block
        multi-host assembly)."""
        if self._mh_fused:
            base = jax.process_index() * self.n_pad
            return self.grower.shard_rows(
                np.arange(base, base + self.n_pad, dtype=np.int32),
                self.n_pad)
        return jnp.arange(self.n_pad, dtype=jnp.int32)

    def _reorder_enabled(self) -> bool:
        # bagging composes with the ordered partition since round 3:
        # masks draw on the host in FILE order (mt19937 parity) and are
        # permuted once per re-bagging on device (_bag_mask_dev_fused)
        return (self.hist_ranged
                and getattr(self.objective, "row_permutable", False)
                and self._can_fuse())

    def _reorder_due(self) -> bool:
        """Does the NEXT iteration hit the re-sort cadence?  (First tree
        re-sorts — clustering pays from tree 2 on — then every
        reorder_every trees.)"""
        return (self._trees_since_reorder
                >= (0 if self._row_order is None
                    else self.reorder_every - 1))

    def _reorder_now(self) -> bool:
        return self._reorder_enabled() and self._reorder_due()

    def _ordered_on_multi(self) -> bool:
        """The multiclass ordered-partition gate (shared by the segment
        scheduler and the dispatch so they can never disagree on which
        body variant a segment runs)."""
        return (self.hist_ranged
                and getattr(self.objective, "row_permutable", False))

    def _reorder_now_multi(self) -> bool:
        return self._ordered_on_multi() and self._reorder_due()

    # -- iteration batching (config.iter_batch): segment scheduling ----
    def _iter_batch_k(self) -> int:
        """The configured dispatch batch K (1 = per-iteration oracle)."""
        v = self.config.iter_batch
        if v == "auto":
            return self._auto_iter_batch()
        return max(int(v), 1)

    _ITER_BATCH_AUTO = 8

    def _auto_iter_batch(self) -> int:
        """auto K: the default batch on ACCELERATORS, shrunk to the
        largest divisor of metric_freq when metric output is live so
        segments tile the metric grid with ONE executable instead of an
        alternating pair.  On the CPU backend auto resolves to 1: local
        dispatch costs microseconds — batching only removes the
        host<->device round-trips of remote-attached accelerators — and
        the K-scan's extra XLA CPU compile time buys nothing (explicit
        iter_batch=N still forces batching anywhere)."""
        if jax.devices()[0].platform == "cpu":
            return 1
        return self._auto_iter_batch_accel()

    def _auto_iter_batch_accel(self) -> int:
        k = self._ITER_BATCH_AUTO
        if self._metrics_active():
            mf = max(int(self.config.metric_freq), 1)
            k = min(k, mf)
            while mf % k:
                k -= 1
        return k

    def _metrics_active(self) -> bool:
        return (bool(self.training_metrics)
                or any(len(ms) > 0 for ms in self.valid_metrics))

    def _segment_fusible(self) -> bool:
        """Paths the batched dispatch covers (the general per-tree path
        keeps K=1: its per-iteration grad round-trip is the thing the
        fused steps already removed)."""
        return self._can_fuse() or self._can_fuse_multi()

    @contract.rank_uniform
    def _plan_segment(self, max_iters: int, is_eval: bool) -> int:
        """K for the next dispatch: min(iter_batch, metric boundary,
        early-stop check, re-bagging epoch boundary, re-sort cadence,
        remaining iterations) — every host-observable boundary ends a
        segment, so batched training is bit-parity with the K=1 oracle
        including the exact metric lines, early-stop iteration, bagging
        epochs and checkpoints."""
        k = min(self._iter_batch_k(), max_iters)
        if k <= 1 or self._stopped or not self._segment_fusible():
            return 1
        if is_eval:
            if self.early_stopping_round > 0:
                # the reference checks early stopping every iteration;
                # batching would skip checks, so the oracle cadence wins
                return 1
            if self._metrics_active():
                mf = max(int(self.config.metric_freq), 1)
                k = min(k, mf - self.iter % mf)
        if self.bagging_enabled:
            freq = max(int(self.config.bagging_freq), 1)
            # iteration `it` re-bags when it % freq == 0; the segment
            # may start ON a boundary but not cross the next one
            k = min(k, freq - self.iter % freq)
        ordered_on = (self._reorder_enabled() if self.num_class == 1
                      else self._ordered_on_multi())
        if ordered_on:
            if self.reorder_every > 1:
                if self._reorder_due():
                    return 1     # the re-sort dispatch runs alone
                k = min(k, self.reorder_every - 1
                        - self._trees_since_reorder)
            # reorder_every == 1: every iteration re-sorts — the segment
            # scans the reorder body uniformly, no cap needed
        # (DART needs no extra cap: _ensure_bank_capacity grows the
        # bank to fit any k before the dispatch)
        return max(k, 1)

    @contract.rank_uniform
    def train_segment(self, max_iters: int,
                      is_eval: bool = True) -> "Tuple[bool, int]":
        """Train up to max_iters boosting iterations, batching
        K = _plan_segment of them into ONE device dispatch; host work
        (metric lines, early stopping, flushes, re-bagging draws) runs
        only at segment boundaries, exactly where the K=1 loop would
        have run it.  Returns (stop, iterations_done)."""
        k = self._plan_segment(max_iters, is_eval)
        if k <= 1:
            return self.train_one_iter(None, None, is_eval), 1
        it0 = self.iter
        self._train_segment_fused(k)
        self.iter += k
        self.num_used_model = len(self._models) // self.num_class
        if is_eval:
            return self.eval_and_check_early_stopping(), k
        if it0 // self._flush_every != self.iter // self._flush_every:
            # the segment crossed a deferred-flush boundary: same
            # amortized device->host pull cadence as the K=1 loop
            if self._sync_stop(self._flush_pending()):
                log.info("Stopped training because there are no more "
                         "leafs that meet the split requirements.")
                return True, k
        return False, k

    def _train_segment_fused(self, k: int) -> None:
        """Dispatch one K-iteration segment and append the pending trees
        (DART overrides with its banked variant)."""
        if self._can_fuse():
            self._ensure_layout()
            self._bagging(self.iter, 0)
            self._ensure_bag_arranged()
            fmasks = np.stack([self._feature_mask(0) for _ in range(k)])
            fmasks_dev = (self.grower.replicate(fmasks) if self._mh_fused
                          else jnp.asarray(fmasks))
            self._models.extend(self._run_fused(
                self._bag_mask_dev_fused(0), fmasks_dev, k))
        else:
            self._models.extend(self._run_fused_multi(k))

    def _bag_mask_dev_fused(self, cls: int):
        """Fused-path bag mask: bit-packed file-order upload normally;
        under an active row order, the cached ORDERED bool mask —
        rebuilt (unpack + one device take) only when re-bagging
        invalidated it.  The reorder step keeps this cache permuted.
        The SHARDED fused step always takes the bool mask: a packed byte
        row only splits on shard boundaries when N_local % 8 == 0, which
        the xla hist impl does not guarantee."""
        if self._fused_sharded:
            if self._bag_dev_packed[cls] is None:
                # multi-host: the local file-order draw (mt19937 parity
                # with the reference's per-machine bagging) assembles
                # into the global row-sharded mask; the order permute is
                # shard-local by construction (ShardedGrower.permute_rows)
                m_host = self.bag_masks[cls]
                if self._layout_active:
                    # query-granular layout: file-order draw scatters
                    # into the per-shard blocks; gap rows stay False
                    m_host = self._shard_layout.place(
                        m_host[:self.num_data], fill=False)
                m = (self.grower.shard_rows(m_host, self.n_pad)
                     if self._mh_fused else jnp.asarray(m_host))
                if self._row_order is not None:
                    m = self.grower.permute_rows(m, self._row_order)
                self._bag_dev_packed[cls] = m
            return self._bag_dev_packed[cls]
        if self._row_order is None:
            return self._bag_mask_dev_packed(cls)
        if self._bag_dev_packed[cls] is None:
            self._bag_dev_packed[cls] = _permute_packed_bag(
                self._bag_mask_dev_packed(cls), self._row_order)
        return self._bag_dev_packed[cls]

    # -- bag compaction (config.bag_compact) ---------------------------
    def _compact_fusible(self) -> bool:
        """Does this booster run a fused path compaction can attach to?
        (DART overrides with its banked-path check.)"""
        return self._can_fuse() or self._can_fuse_multi()

    def _compute_bag_window(self) -> int:
        """Static compacted sweep window in rows (0 = compaction off):
        ceil_pad of a deterministic upper bound on any draw's in-bag
        count, so shapes are stable and one executable serves every
        re-bagging epoch.  Serial bounds are exact (row bagging draws
        exactly int(fraction*n) rows; query bagging is bounded by the
        largest that-many queries).  Sharded learners get a per-shard
        window: expected count plus a generous margin, with a host-side
        overflow check per re-bagging (_ensure_bag_arranged) that falls
        back to the masked path if a freak draw exceeds it."""
        cfg = self.config
        if (not self.bagging_enabled or cfg.bag_compact == "off"
                or not self._compact_fusible()
                or not getattr(self.objective, "row_permutable", False)):
            return 0
        if self.hist_compact:
            if cfg.bag_compact == "on":
                log.warning("hist_compact=on disables bag_compact "
                            "(mutually exclusive row strategies)")
            return 0
        if cfg.bag_compact == "auto":
            # auto keeps the f64 parity configuration on the masked
            # full-sweep oracle and skips fractions too close to 1
            if (cfg.bagging_fraction > 0.8
                    or self.dtype != jnp.float32):
                return 0
        unit = self._row_unit_base
        bound = self.objective.bag_rows_bound(cfg.bagging_fraction)
        if self.num_class > 1:
            # per-class draws differ: the window must hold their UNION
            bound = min(self.num_data, self.num_class * bound)
        if self._fused_sharded:
            import math
            cap = self.n_pad // self.grower.local_shard_count()
            frac = min(bound / max(self.num_data, 1), 1.0)
            # margin: 4 sigma of the per-shard hypergeometric count (the
            # binomial sigma bounds it), floored at cap/8 so query-
            # granular draws' row clumping is covered too
            sigma = math.sqrt(cap * frac * (1.0 - frac))
            w = int(cap * frac) + max(unit, cap // 8, int(4 * sigma) + 1)
            w = min(-(-w // unit) * unit, cap)
            return w if w < cap else 0
        if self.grower is not None:
            return 0   # feature/voting growers keep the masked path
        w = -(-max(bound, 1) // unit) * unit
        return w if w < self.n_pad else 0

    @contract.rank_uniform
    def _bag_compact_rows(self) -> int:
        """The active compacted window (rows per device shard under the
        sharded fused step; all rows otherwise).  0 = masked path."""
        if self._bag_window is None:
            self._bag_window = self._compute_bag_window()
        return 0 if self._bag_overflowed else self._bag_window

    @contract.rank_uniform
    def _bag_window_overflow(self) -> bool:
        """Host-side guard for the sharded per-shard window: True when
        the current draw's per-shard in-bag union count exceeds it
        (multi-host ORs the decision so every rank falls back
        together)."""
        union = self.bag_masks[0]
        for m in self.bag_masks[1:]:
            union = union | m
        if self._shard_layout is not None:
            union = self._shard_layout.place(union[:self.num_data],
                                             fill=False)
        counts = self.grower.shard_row_counts(union, self.n_pad)
        over = int(counts.max()) > self._bag_window
        if self._mh_fused:
            from ..parallel.dist import sync_max_ints
            over = bool(int(sync_max_ints([int(over)])[0]))
        return over

    def _ensure_bag_arranged(self) -> None:
        """Arrange device state in-bag-first when compaction is active
        and a re-bagging (or a general-path excursion) left it
        unarranged; no-op otherwise."""
        w = self._bag_compact_rows()
        if w <= 0 or self._bag_arranged:
            return
        if self._fused_sharded and self._bag_window_overflow():
            self._bag_overflowed = True
            log.warning(
                "bag_compact: a re-bagging draw overflowed the static "
                "per-shard window (%d rows); falling back to the masked "
                "full-sweep path for the rest of this run"
                % self._bag_window)
            return
        self._arrange_for_bag()
        self._bag_arranged = True

    def _dart_bank_rows(self):
        """Per-row DART bank buffers the arrangement must carry (base
        GBDT has none; DART returns its leaf bank)."""
        return None

    def _set_dart_bank_rows(self, arr) -> None:
        raise NotImplementedError   # only reachable from DART

    def _fused_gspecs(self, gstate):
        """PartitionSpecs of the fused gradient state: the objective's
        own query-sharded specs under the rank layout, else every leaf
        sharded on its last (row) axis."""
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import DATA_AXIS
        if self._layout_active:
            return self._gstate_specs
        return jax.tree_util.tree_map(
            lambda a: P(*([None] * (np.ndim(a) - 1) + [DATA_AXIS])),
            gstate)

    def _arrange_for_bag(self) -> None:
        """One device dispatch per re-bagging: stable-sort every per-row
        buffer in-bag-first so the fused step's static window holds every
        in-bag row.  The result is 'just another row order', so metrics,
        checkpoints and the general-path restore reuse the existing
        ordered-partition machinery unchanged."""
        multi = self.num_class > 1
        if multi:
            mask = self._bag_masks_stacked_dev()
        else:
            mask = self._bag_mask_dev_fused(0)
            if mask.dtype == jnp.uint8:
                mask = _unpack_bag_jit(mask, self.n_pad)
        gstate = self._gstate_for_fused()
        order = (self._row_order if self._row_order is not None
                 else self._identity_order_dev())
        bank = self._dart_bank_rows()
        key = ("bag_arrange", multi, bank is not None,
               self.objective.fused_key(), self.dtype,
               id(self.grower.mesh) if self._fused_sharded else None)

        def make():
            permute_state = self.objective.make_permute_fn()
            if self._fused_sharded:
                return _make_bag_arrange_sharded(
                    permute_state, multi, self.grower.mesh,
                    self._fused_gspecs(gstate))
            return _make_bag_arrange(permute_state, multi,
                                     bank is not None)

        fn = _get_fused_step(key, make)
        _note_dispatch()
        args = (self.bins_dev, self.scores, mask, gstate, order)
        if bank is not None:
            args += (bank,)
        out = fn(*args)
        self.bins_dev, self.scores, mask_new, gstate_new, order_new = \
            out[:5]
        if bank is not None:
            self._set_dart_bank_rows(out[5])
        if multi:
            self._bag_stacked = mask_new
        else:
            self._bag_dev_packed[0] = mask_new
        self._gstate_override = gstate_new
        self._row_order = order_new
        self._inv_order = None

    def _run_fused(self, bag_mask_dev, fmask_dev,
                   k_iters: int = 1) -> "List[_PendingTree]":
        """One fused dispatch covering k_iters boosting iterations
        (config.iter_batch; k_iters=1 is the per-iteration oracle).
        fmask_dev is [F] for k_iters=1 and [K, F] stacked otherwise;
        packed trees come back stacked and stay device-resident until
        the next flush."""
        cfg = self.config
        lr = self.shrinkage_rate
        # re-sort after the FIRST tree (clustering pays from tree 2 on),
        # then every reorder_every trees.  Segments with k_iters > 1 are
        # scheduled body-uniform (_plan_segment): either every iteration
        # re-sorts (reorder_every == 1) or none does.
        reorder = self._reorder_now()
        # bag compaction: the static window is live only while the
        # device state is actually arranged in-bag-first (the masked
        # full-sweep executable serves every other dispatch)
        compact = self._bag_compact_rows() if self._bag_arranged else 0
        gstate = self._gstate_for_fused()
        key = (self.objective.fused_key(), lr, self.dtype,
               self.hist_impl, self.max_bin, max(cfg.num_leaves, 2),
               cfg.max_depth, self.params, len(self.valid_bins_dev),
               self.hist_slots, self.hist_compact, self.hist_ranged,
               self.hist_fused, self.hist_acc,
               reorder, compact, k_iters,
               # sharded steps close over the mesh and the aggregation
               # protocol — two data-parallel configs that differ only
               # here MUST NOT share an executable
               (cfg.hist_agg, self.grower.num_shards,
                id(self.grower.mesh)) if self._fused_sharded else None)

        def make():
            grow_kw = self._grow_kw()
            if self._fused_sharded:
                from ..parallel.mesh import DATA_AXIS
                grow_kw.update(psum_axis=DATA_AXIS,
                               hist_agg=cfg.hist_agg,
                               num_shards=self.grower.num_shards,
                               voting_top_k=0)
                # query-sharded objectives carry their own specs (the
                # query-block leaves shard on their LEADING axis);
                # elementwise state shards on its last (row) axis
                return _make_fused_step_sharded(
                    self.objective.make_grad_fn(), grow_kw, lr,
                    self.dtype, self.grower.mesh,
                    len(self.valid_bins_dev),
                    self._fused_gspecs(gstate), reorder,
                    self.objective.make_permute_fn(), compact, k_iters)
            if reorder:
                return _make_fused_step_reorder(
                    self.objective.make_grad_fn(), grow_kw, lr,
                    self.dtype, self.objective.make_permute_fn(),
                    compact, k_iters)
            return _make_fused_step(self.objective.make_grad_fn(),
                                    grow_kw, lr, self.dtype, compact,
                                    k_iters)

        fn = _get_fused_step(key, make)
        _note_dispatch()
        if reorder:
            # the reorder executable must see ONE bag-mask signature:
            # dispatches under an active row order pass the cached
            # ordered bool mask, so the first (identity-order) dispatch
            # unpacks its packed upload here — otherwise the second
            # re-sort retraces and recompiles the whole ~20s step with
            # bool[n] in place of u8[n/8] (observed as a mid-training
            # stall exactly at iteration hist_reorder_every+1)
            if bag_mask_dev.dtype == jnp.uint8:
                bag_mask_dev = _unpack_bag_jit(bag_mask_dev, self.n_pad)
            order = (self._row_order if self._row_order is not None
                     else self._identity_order_dev())
            (scores, valid, ints, floats, bins_new, bag_new, gstate_new,
             order_new, self._dev_stopped) = fn(
                self.scores, list(self.valid_scores), bag_mask_dev,
                fmask_dev, self.bins_dev, tuple(self.valid_bins_dev),
                gstate, order, self._dev_stopped)
            self.bins_dev = bins_new
            self._bag_dev_packed[0] = bag_new
            self._gstate_override = gstate_new
            self._row_order = order_new
            self._inv_order = None
            self._trees_since_reorder = 0
        else:
            scores, valid, ints, floats, self._dev_stopped = fn(
                self.scores, list(self.valid_scores), bag_mask_dev,
                fmask_dev, self.bins_dev, tuple(self.valid_bins_dev),
                gstate, self._dev_stopped)
            self._trees_since_reorder += k_iters
        self.scores = scores
        self.valid_scores = list(valid)
        if k_iters == 1:
            return [_PendingTree(ints, floats, lr, gated=True)]
        # stacked [K, ...] rows stay unmaterialized device slices; the
        # deferred flush stacks every pending tree and pulls them in one
        # device_get
        return [_PendingTree(ints[j], floats[j], lr, gated=True)
                for j in range(k_iters)]

    @contract.parity_oracle("the general per-tree path: one grow "
                            "dispatch per tree — the oracle every fused "
                            "path is parity-tested against (PARITY.md)")
    def _train_tree(self, grad, hess, bag_mask_dev, fmask, cls):
        cfg = self.config
        _note_dispatch()   # the general path: one grow dispatch per tree
        if self.grower is not None and self._mh:
            # assemble process-local grad/hess into global sharded arrays,
            # grow SPMD across hosts, then pull the tree (replicated) and
            # this process's leaf_id block back to local
            g = self.grower.shard_rows(
                np.asarray(grad, dtype=self.dtype), self.n_pad)
            h = self.grower.shard_rows(
                np.asarray(hess, dtype=self.dtype), self.n_pad)
            dev_tree, leaf_id = self.grower.grow(
                self.bins_dev, g, h, bag_mask_dev,
                self.grower.replicate(fmask))
            dev_tree = self.grower.replicated_to_local(dev_tree)
            leaf_id = self.grower.local_rows(leaf_id)
        elif self.grower is not None and self._feat_mh:
            # feature-parallel across hosts: rows replicated (every
            # process computes identical grad/hess on its full local
            # copy), features split; pull the replicated outputs local
            g = self.grower.shard_rows(
                np.asarray(grad, dtype=self.dtype), self.n_pad)
            h = self.grower.shard_rows(
                np.asarray(hess, dtype=self.dtype), self.n_pad)
            dev_tree, leaf_id = self.grower.grow(
                self.bins_dev, g, h, bag_mask_dev, fmask)
            dev_tree = self.grower.replicated_to_local(dev_tree)
            leaf_id = self.grower.local_replicated(leaf_id)
        elif self.grower is not None:
            dev_tree, leaf_id = self.grower.grow(
                self.bins_dev, grad.astype(self.dtype),
                hess.astype(self.dtype), bag_mask_dev, jnp.asarray(fmask))
        else:
            dev_tree, leaf_id = grow_tree(
                self.bins_dev,
                grad.astype(self.dtype), hess.astype(self.dtype),
                bag_mask_dev, jnp.asarray(fmask), **self._grow_kw())

        lr = self.shrinkage_rate
        # train-score update: leaf_value[leaf_id] gather for ALL rows —
        # covers both the reference's partition fast path and the
        # out-of-bag traversal (gbdt.cpp:162-167, score_updater.hpp:44-68).
        # Shrinkage multiplies in the hist dtype BEFORE the f32 cast, like
        # the reference's double leaf_value * rate then score_t cast.
        # (A 1-leaf stump has leaf_value[0] == 0, so this add is a no-op
        # for stopped trees — see _flush_pending.)
        leaf_vals = (dev_tree.leaf_value * lr).astype(jnp.float32)
        self.scores = self.scores.at[cls].add(leaf_vals[leaf_id])

        # validation scores via vectorized binned traversal, kept on device
        for i, vbins in enumerate(self.valid_bins_dev):
            vleaf = predict_leaf_binned(dev_tree.split_feature,
                                        dev_tree.threshold_bin,
                                        dev_tree.left_child,
                                        dev_tree.right_child, vbins)
            self.valid_scores[i] = (
                self.valid_scores[i].at[cls].add(leaf_vals[vleaf]))

        # Pack the tree into two flat device buffers; the next flush
        # stacks every pending tree and pulls them in one transfer, so
        # training never blocks on a per-iteration roundtrip.
        ints, floats = _pack_tree(dev_tree)
        return _PendingTree(ints, floats, lr)

    # -- lazy host materialization ------------------------------------
    @property
    def models(self) -> List[Tree]:
        """Host trees; materializes any pending device trees first."""
        self._flush_pending()
        return self._models

    @models.setter
    def models(self, value) -> None:
        self._models = list(value)

    @contract.counted_flush
    def _flush_pending(self) -> bool:
        """Unpack pending device trees; truncate at the first 1-leaf stump
        (the reference stops training there, gbdt.cpp:186).  Deleted trees
        that were NOT stumps (possible under changing bag/feature masks)
        have their score contributions subtracted so scores match the kept
        trees.  A multiclass stop mid-iteration keeps that iteration's
        earlier-class trees in the model AND in the scores even though
        prediction floors them away — exactly the reference's behavior
        (models_ keeps partials, gbdt.cpp:186-197; prediction floors
        num_used_model_ = size/num_class, gbdt.cpp:455,489).  Returns True
        when training must stop."""
        # ONE device->host pull for every pending tree: on the remote-
        # attached TPU each small-array transfer is a ~tens-of-ms tunnel
        # round-trip (measured: a 5-class iteration spent ~380 of its
        # 414 ms pulling ten per-class tree buffers), so the flush
        # stacks all pending ints/floats on device (this also fuses
        # multiclass batch-row slices) and materializes them in two
        # transfers, amortized over _flush_every iterations
        pend = [m for m in self._models
                if isinstance(m, _PendingTree)
                and not isinstance(m.ints, np.ndarray)]
        if pend:
            # _pack_tree pads every tree to the config-fixed leaf count
            # (see _PendingTree); a future variable-size packing change
            # must group by shape before stacking
            assert len({m.ints.shape for m in pend}) == 1 \
                and len({m.floats.shape for m in pend}) == 1, \
                "pending tree buffers must share one packed shape"
            # explicit device_get: ONE counted transfer for the whole
            # batch (analysis/guards.py device_get accounting — bench
            # reports it as the per-tree sync metric)
            faultpoint("flush.device_get")
            ints_all, floats_all = jax.device_get(
                (jnp.stack([m.ints for m in pend]),
                 jnp.stack([m.floats for m in pend])))
            for m, ih, fh in zip(pend, ints_all, floats_all):
                m.ints, m.floats = ih, fh
        stop_at = None
        gated_flags = {}
        for idx, m in enumerate(self._models):
            if not isinstance(m, _PendingTree):
                continue
            gated_flags[idx] = m.gated
            tree = self._unpack_tree(m)
            self._models[idx] = tree
            if tree.num_leaves <= 1 and stop_at is None:
                stop_at = idx
        if stop_at is not None:
            for idx in range(stop_at, len(self._models)):
                t = self._models[idx]
                # fused-step trees past the stump were grown with the
                # device stopped flag set: their score updates were
                # already suppressed on device, nothing to subtract
                if t.num_leaves > 1 and not gated_flags.get(idx, False):
                    self._subtract_tree_scores(t, idx % self.num_class)
            del self._models[stop_at:]
            self._stopped = True
            self.num_used_model = len(self._models) // self.num_class
            self.iter = self.num_used_model
        return self._stopped

    def _subtract_tree_scores(self, tree: Tree, cls: int) -> None:
        """Remove a discarded tree's leaf values from train/valid scores
        (leaf assignment by binned traversal == the growth-time leaf_id;
        reverses _train_tree's adds to within one f32 ulp)."""
        self._add_tree_to_scores(tree, cls, -1.0, train=True, valid=True)

    def _add_tree_to_scores(self, tree: Tree, cls: int, scale: float,
                            train: bool, valid: bool) -> None:
        """Add scale * tree's (already-shrunk) leaf values to the train
        and/or valid score vectors via binned traversal on device.  Used
        by the stump-stop rollback and DART's drop/normalize cycle
        (dart.hpp:86-129)."""
        sf = jnp.asarray(tree.split_feature)
        tb = jnp.asarray(tree.threshold_bin)
        lc = jnp.asarray(tree.left_child)
        rc = jnp.asarray(tree.right_child)
        lv = jnp.asarray((tree.leaf_value * scale).astype(np.float32))
        if train:
            leaf = predict_leaf_binned(sf, tb, lc, rc, self.bins_dev)
            self.scores = self.scores.at[cls].add(lv[leaf])
        if valid:
            for i, vbins in enumerate(self.valid_bins_dev):
                vleaf = predict_leaf_binned(sf, tb, lc, rc, vbins)
                self.valid_scores[i] = (
                    self.valid_scores[i].at[cls].add(lv[vleaf]))

    def _unpack_tree(self, p: "_PendingTree") -> Tree:
        L = max(self.config.num_leaves, 2)
        ints = np.asarray(p.ints)
        floats = np.asarray(p.floats, dtype=np.float64)
        nl = int(ints[0])
        o = 1
        sf, tb, lc, rc, lp, ld, lcnt = (
            ints[o:o + L - 1], ints[o + L - 1:o + 2 * (L - 1)],
            ints[o + 2 * (L - 1):o + 3 * (L - 1)],
            ints[o + 3 * (L - 1):o + 4 * (L - 1)],
            ints[o + 4 * (L - 1):o + 4 * (L - 1) + L],
            ints[o + 4 * (L - 1) + L:o + 4 * (L - 1) + 2 * L],
            ints[o + 4 * (L - 1) + 2 * L:o + 4 * (L - 1) + 3 * L])
        sg = floats[:L - 1]
        lv = floats[L - 1:2 * L - 1]
        iv = floats[2 * L - 1:3 * L - 2]
        ds = self.train_data
        sf = sf[:nl - 1]
        tb = tb[:nl - 1]
        bounds = [ds.bin_mappers[f].bin_upper_bound for f in sf]
        threshold = np.array([bounds[i][tb[i]] for i in range(nl - 1)],
                             dtype=np.float64)
        tree = Tree(
            num_leaves=nl,
            split_feature=sf.copy(),
            split_feature_real=ds.real_feature_index[sf].astype(np.int32),
            threshold_bin=tb.copy(),
            threshold=threshold,
            split_gain=sg[:nl - 1],
            left_child=lc[:nl - 1],
            right_child=rc[:nl - 1],
            internal_value=iv[:nl - 1],
            leaf_parent=lp[:nl],
            leaf_value=lv[:nl],
            leaf_depth=ld[:nl],
            leaf_count=lcnt[:nl],
        )
        tree.shrinkage(p.lr)
        return tree

    def _inverse_row_order(self):
        """Device [n_pad] inverse permutation of the ordered-partition
        row order (cached between re-sorts), or None for identity."""
        if self._row_order is None:
            return None
        if self._inv_order is None:
            self._inv_order = jnp.argsort(self._row_order)
        return self._inv_order

    def _ensure_layout(self) -> None:
        """(Re-)place per-row state into the query-granular layout when
        the fused path resumes after a general-path excursion (custom
        gradients restore file order via _restore_row_order).  The
        initial placement happens in __init__; multi-host never comes
        back (the fused->general fallback is one-way there)."""
        if self._shard_layout is None or self._layout_active:
            return
        lay = self._shard_layout
        host = np.asarray(self.scores)[:, :self.num_data]
        self.scores = jnp.asarray(lay.place(host))
        if self.rows_sharded and not self._mh:
            self.scores = jax.device_put(self.scores,
                                         self.grower.row_sharding_2d())
        self.bins_dev = self.grower.shard_bins(
            lay.place(self.train_data.bins))
        self._bag_dev = [None] * self.num_class
        self._bag_dev_packed = [None] * self.num_class
        self._bag_stacked = None
        self._gstate_override = None
        self._layout_active = True

    def _layout_pos_dev(self):
        """Cached device copy of the layout's file-row -> padded-
        position map (reads scores back to file order without a host
        round trip)."""
        if getattr(self, "_layout_pos", None) is None:
            self._layout_pos = jnp.asarray(self._shard_layout.pos)
        return self._layout_pos

    def _unplace_host(self, arr: np.ndarray) -> np.ndarray:
        """Layout space -> file order + trailing pad (host, [.., n_pad])."""
        out = np.zeros_like(arr)
        filed = self._shard_layout.unplace(arr)
        out[..., :filed.shape[-1]] = filed
        return out

    def _restore_row_order(self) -> None:
        """Return all per-row state to FILE order (leaving the fused
        ordered-partition path and/or the query-granular layout: custom
        gradients, objective swaps)."""
        if self._mh_fused:
            # leaving the multi-host fused path (custom gradients): pull
            # this process's file-order block local and fall back to the
            # general per-tree path for the REST of training — one-way,
            # because the general path keeps scores process-local and
            # cannot hand them back to the global fused dispatch.
            # Materialize pending fused trees FIRST: their packed buffers
            # are REPLICATED global arrays, and a later flush would stack
            # them with the general path's process-local buffers
            # (incompatible devices); _stopped propagates via the next
            # flush either way.
            self._flush_pending()
            self.scores = jnp.asarray(self._mh_local_file_scores())
            self.valid_scores = [
                jnp.asarray(np.asarray(v.addressable_data(0)))
                for v in self.valid_scores]
            self.valid_bins_dev = [
                jnp.asarray(np.asarray(v.addressable_data(0)))
                for v in self.valid_bins_dev]
            self._dev_stopped = jnp.asarray(
                bool(np.asarray(self._dev_stopped.addressable_data(0))))
            if self._row_order is not None or self._layout_active:
                # rebuild the global sharded bins from FILE order: the
                # general mh path keeps using self.bins_dev, which the
                # ordered-partition re-sorts (and the query layout)
                # left permuted — training later trees on permuted bins
                # against file-order gradients would silently corrupt
                # every subsequent tree
                bins = self.train_data.bins
                if self.n_pad != self.num_data:
                    bins = np.pad(bins, ((0, 0),
                                         (0, self.n_pad - self.num_data)))
                self.bins_dev = self.grower.shard_bins(bins)
            self._layout_active = False
            self._shard_layout = None
            self._mh_fused = False
            self._fused_sharded = False
            # the general path has no device stopped flag: deferred
            # flushing is only sound without bagging/feature_fraction
            # (same recompute DART's _exit_bank_mode does)
            self._flush_every = (
                16 if (self.num_class == 1 and not self.bagging_enabled
                       and self.config.feature_fraction >= 1.0) else 1)
            self._bag_dev = [None] * self.num_class
            self._bag_dev_packed = [None] * self.num_class
            self._bag_stacked = None
            self._row_order = None
            self._inv_order = None
            self._gstate_override = None
            self._trees_since_reorder = 0
            self._bag_arranged = False
            return
        if self._row_order is None and not self._layout_active:
            return
        inv = self._inverse_row_order()
        if inv is not None:
            self.scores = jnp.take(self.scores, inv, axis=1)
        if self._layout_active:
            # query-granular layout -> file order + trailing pad (the
            # general path's convention); _ensure_layout re-places when
            # the fused path resumes
            s = jnp.take(self.scores, self._layout_pos_dev(), axis=1)
            self.scores = jnp.pad(
                s, ((0, 0), (0, self.n_pad - self.num_data)))
            self._layout_active = False
        bins = self.train_data.bins
        if self.n_pad != self.num_data:
            bins = np.pad(bins, ((0, 0), (0, self.n_pad - self.num_data)))
        self.bins_dev = jnp.asarray(bins)
        self._bag_dev = [None] * self.num_class
        self._bag_dev_packed = [None] * self.num_class
        self._bag_stacked = None
        self._row_order = None
        self._inv_order = None
        self._gstate_override = None
        self._trees_since_reorder = 0
        self._bag_arranged = False

    def _mh_local_base_scores(self) -> np.ndarray:
        """Multi-host fused: this process's [K, n_pad] block of the
        global row-sharded scores with any shard-local ordered-partition
        permutation undone (base layout space — file order + trailing
        pad for the default layout, query-granular blocks under the
        rank shard layout)."""
        s = np.asarray(self.grower.local_rows(self.scores))
        if self._row_order is not None:
            base = jax.process_index() * self.n_pad
            ordl = np.asarray(self.grower.local_rows(self._row_order)) \
                - base
            out = np.empty_like(s)
            out[:, ordl] = s
            s = out
        return s

    def _mh_local_file_scores(self) -> np.ndarray:
        """Multi-host fused: this process's [K, n_pad] block restored to
        FILE order (+ trailing pad)."""
        s = self._mh_local_base_scores()
        if self._layout_active:
            s = self._unplace_host(s)
        return s

    def _training_score(self):
        if self._mh_fused:
            s = self._mh_local_file_scores()[:, :self.num_data]
            return s[0] if self.num_class == 1 else s
        s = self.scores
        inv = self._inverse_row_order()
        if inv is not None:
            # ordered-partition mode keeps per-row state sorted by tree
            # leaves; metrics (and any external reader) see file order
            s = jnp.take(s, inv, axis=1)
        if self._layout_active:
            s = jnp.take(s, self._layout_pos_dev(), axis=1)
        s = s[:, :self.num_data]
        return s[0] if self.num_class == 1 else s

    def _score_for_gradients(self):
        """Padded scores handed to the objective (which is itself padded via
        pad_to, so no per-iteration slice/pad resharding round-trips); DART
        drops trees here first (GetTrainingScore override, dart.hpp:60-65)."""
        s = self.scores
        return s[0] if self.num_class == 1 else s

    # ------------------------------------------------------------------
    # multi-host: cli.init_train installs an OR-allreduce here so every
    # rank takes the same stop decision — a rank stopping alone would
    # deadlock the others' next SPMD collective (metrics are already
    # globally reduced, so decisions agree; this is the hard guarantee)
    stop_sync = None

    @contract.rank_uniform
    def _sync_stop(self, stop: bool) -> bool:
        if self.stop_sync is not None:
            return bool(self.stop_sync(bool(stop)))
        return stop

    def eval_and_check_early_stopping(self) -> bool:
        # Flush BEFORE evaluating: if a pending 1-leaf stump stopped
        # training, that stop wins — evaluating or popping trees off the
        # truncated model would corrupt it (the reference never reaches
        # its early-stopping path after the stump stop, gbdt.cpp:186).
        if self._sync_stop(self._flush_pending()):
            log.info("Stopped training because there are no more leafs "
                     "that meet the split requirements.")
            return True
        stop = self._sync_stop(self.output_metric(self.iter))
        if stop:
            log.info("Early stopping at iteration %d, the best iteration "
                     "round is %d" % (self.iter,
                                      self.iter - self.early_stopping_round))
            for _ in range(self.early_stopping_round * self.num_class):
                self.models.pop()
            self.num_used_model = len(self.models) // self.num_class
        return stop

    def output_metric(self, it: int) -> bool:
        """GBDT::OutputMetric (gbdt.cpp:231-267)."""
        cfg = self.config
        ret = False
        if it % cfg.metric_freq == 0:
            train_score = np.asarray(self._training_score())
            for metric in self.training_metrics:
                for name, val in zip(metric.names, metric.eval(train_score)):
                    log.info("Iteration: %d, %s : %f" % (it, name, val))
        if it % cfg.metric_freq == 0 or self.early_stopping_round > 0:
            for i in range(len(self.valid_metrics)):
                vs = np.asarray(self.valid_scores[i])
                score = vs[0] if self.num_class == 1 else vs
                for j, metric in enumerate(self.valid_metrics[i]):
                    vals = metric.eval(score)
                    if it % cfg.metric_freq == 0:
                        for name, val in zip(metric.names, vals):
                            log.info("Iteration: %d, %s : %f" % (it, name, val))
                    if not ret and self.early_stopping_round > 0:
                        cur = metric.factor_to_bigger_better * vals[-1]
                        if cur > self.best_score[i][j]:
                            self.best_score[i][j] = cur
                            self.best_iter[i][j] = it
                        elif it - self.best_iter[i][j] >= self.early_stopping_round:
                            ret = True
        return ret

    def get_eval_at(self, data_idx: int) -> List[float]:
        if data_idx == 0:
            score = np.asarray(self._training_score())
            return [v for m in self.training_metrics for v in m.eval(score)]
        i = data_idx - 1
        vs = np.asarray(self.valid_scores[i])
        score = vs[0] if self.num_class == 1 else vs
        return [v for m in self.valid_metrics[i] for v in m.eval(score)]

    # ------------------------------------------------------------------
    # prediction over raw feature values.  Default path: stacked-tree
    # device traversal (ops/predict.predict_leaf_stacked) in bounded row
    # chunks — the reference's whole-file host loop
    # (predictor.hpp:35-70) redesigned as data-parallel descents.  The
    # device routes with (hi, lo) f32 pair compares (f64-faithful, no
    # x64 needed); leaf-value accumulation happens on the host in f64,
    # so output formatting stays byte-identical to the reference under
    # any backend configuration.
    PREDICT_CHUNK = 1 << 17
    # matmul predictor: trees per scan block and rows per chunk (the
    # [C, tb*M, 4] selection temporary bounds memory)
    PREDICT_TREE_BLOCK = 8
    PREDICT_MM_CHUNK = 1 << 16
    PREDICT_INFLIGHT = 8

    def _stacked_trees(self, nmodels: int):
        """Padded [T, M]/[T, L] arrays for the first nmodels trees,
        cached until the model list grows."""
        from ..ops.predict import split_hi_lo
        # keyed on iter too: DART renormalizes EXISTING trees' leaf values
        # in place between iterations (dart.hpp Normalize), so a pack from
        # an earlier iteration would be stale
        key = (nmodels, self.iter)
        cached = getattr(self, "_stack_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        trees = self.models[:nmodels]
        max_l = max(t.num_leaves for t in trees)
        m = max(1, max_l - 1)
        sf = np.zeros((nmodels, m), dtype=np.int32)
        thr = np.zeros((nmodels, m), dtype=np.float64)
        lc = np.full((nmodels, m), -1, dtype=np.int32)
        rc = np.full((nmodels, m), -1, dtype=np.int32)
        lv = np.zeros((nmodels, max_l), dtype=np.float64)
        for i, t in enumerate(trees):
            ni = t.num_leaves - 1
            if ni > 0:
                sf[i, :ni] = t.split_feature_real[:ni]
                thr[i, :ni] = t.threshold[:ni]
                lc[i, :ni] = t.left_child[:ni]
                rc[i, :ni] = t.right_child[:ni]
            # ni == 0 keeps lc[0] == -1 == ~0: every row lands in leaf 0
            lv[i, :t.num_leaves] = t.leaf_value[:t.num_leaves]
        th, tl = split_hi_lo(thr)
        dev = tuple(jnp.asarray(a) for a in (sf, th, tl, lc, rc))
        # the matmul-predictor pack builds LAZILY (first accelerator
        # predict): CPU-only runs never pay its DFS/uploads
        pack = {"dev": dev, "lv": lv, "mm": None, "mm_built": False,
                "np": (trees, sf, th, tl, lc, rc, max_l, m)}
        self._stack_cache = (key, pack)
        return pack

    def _matmul_cached(self, pack):
        if not pack["mm_built"]:
            pack["mm"] = self._matmul_pack(*pack["np"])
            pack["mm_built"] = True
        return pack["mm"]

    def _matmul_pack(self, trees, sf, th, tl, lc, rc, max_l, m):
        """Device pack for the gather-free matmul predictor
        (ops/predict.predict_leaf_matmul).  Host-side array construction
        is SHARED with the serving forest (ops/predict.
        matmul_host_arrays) so the two packs cannot drift."""
        from ..ops.predict import matmul_host_arrays
        host = matmul_host_arrays(trees, sf, th, tl, lc, rc, max_l, m,
                                  self.max_feature_idx + 1,
                                  self.PREDICT_TREE_BLOCK)
        if host is None:
            return None
        tables, sel, thr_code, pos, neg, depth = host
        return (tables, (jnp.asarray(sel), jnp.asarray(thr_code),
                         jnp.asarray(pos), jnp.asarray(neg),
                         jnp.asarray(depth)))

    def _predict_leaves(self, x: np.ndarray, nmodels: int) -> np.ndarray:
        """[N, F] raw values -> [N, T] i32 leaf indices on device,
        chunked so memory stays bounded.

        Two kernels, same exact f64 routing semantics: accelerators take
        the gather-free matmul predictor (pointer-chasing descents cost
        one serialized gather per level per tree on TPU — measured 9x
        SLOWER than host numpy at 1Mx20; the matmul form runs on the
        MXU); CPU keeps the while-loop descent (XLA CPU handles the
        gathers fine and skips the O(C*M) compare work)."""
        from ..ops.predict import (predict_leaf_matmul,
                                   predict_leaf_stacked, rank_encode,
                                   split_hi_lo)
        x = np.asarray(x, dtype=np.float64)
        want = self.max_feature_idx + 1
        if x.shape[1] < want:
            # absent trailing features read as 0.0, the reference's
            # missing-value convention (predictor.hpp feature buffer) —
            # a narrow matrix must not silently gather-clamp on device
            x = np.pad(x, ((0, 0), (0, want - x.shape[1])))
        elif x.shape[1] > want:
            x = x[:, :want]
        pack = self._stacked_trees(nmodels)
        dev = pack["dev"]
        mm = (self._matmul_cached(pack)
              if jax.default_backend() != "cpu" else None)
        use_mm = mm is not None
        step = self.PREDICT_MM_CHUNK if use_mm else self.PREDICT_CHUNK
        n = x.shape[0]
        out = np.empty((n, nmodels), dtype=np.int64)

        def per_chunk(chunk):
            xh, xl = split_hi_lo(chunk)
            if use_mm:
                tables, mm_dev = mm
                code = rank_encode(xh, xl, tables)
                return predict_leaf_matmul(
                    *mm_dev, jnp.asarray(code),
                    tree_block=self.PREDICT_TREE_BLOCK)
            return predict_leaf_stacked(*dev, jnp.asarray(xh),
                                        jnp.asarray(xl))

        def write(a, rows, got):
            got = got[:rows]
            out[a:a + rows] = got[:, :nmodels] if use_mm else got

        self._predict_pipeline(x, step, per_chunk, write)
        return out

    def _predict_pipeline(self, x, step, per_chunk, write) -> None:
        """Bounded-in-flight chunk dispatch shared by the predict paths:
        the device pipelines chunk k+1 while chunk k's result reads back
        (the remote-tunnel round trip amortizes), but device buffers stay
        O(window), not O(N).  Rows pad up to a power-of-two bucket: one
        compiled executable per bucket instead of per distinct batch
        size.  per_chunk(padded_chunk) -> device array; write(a, rows,
        host_array) consumes results in order."""
        pending = []

        def drain(limit):
            while len(pending) > limit:
                a, rows, dev_res = pending.pop(0)
                write(a, rows, np.asarray(dev_res))

        n = x.shape[0]
        for a in range(0, n, step):
            chunk = np.ascontiguousarray(x[a:a + step])
            rows = chunk.shape[0]
            bucket = 256
            while bucket < rows:
                bucket <<= 1
            if bucket > rows:
                chunk = np.pad(chunk, ((0, bucket - rows), (0, 0)))
            pending.append((a, rows, per_chunk(chunk)))
            drain(self.PREDICT_INFLIGHT)
        drain(0)

    def predict_raw(self, x: np.ndarray) -> np.ndarray:
        """x [N, num_total_features] -> [K, N] raw scores."""
        k = self.num_class
        n = x.shape[0]
        nmodels = self.num_used_model * k
        if nmodels == 0 or n == 0:
            return np.zeros((k, n), dtype=np.float64)
        if jax.default_backend() != "cpu" and jax.config.jax_enable_x64:
            # fuse the f64 accumulation into the device dispatch: the
            # [C, T] leaf-index readback (the remote-tunnel predict
            # bottleneck) collapses to [K, C] doubles, bit-identically
            # (ops/predict.accumulate_scores replays the host loop)
            out = self._predict_raw_device(x, nmodels)
            if out is not None:
                return out
        leaves = self._predict_leaves(x, nmodels)
        lv = self._stacked_trees(nmodels)["lv"]
        out = np.zeros((k, n), dtype=np.float64)
        # per-tree f64 accumulation in boosting order, exactly the
        # reference predictor's += tree->Predict (predictor.hpp:35-70)
        for i in range(nmodels):
            out[i % k] += lv[i, leaves[:, i]]
        return out

    def _predict_raw_device(self, x: np.ndarray,
                            nmodels: int) -> "Optional[np.ndarray]":
        """Chunked matmul-predictor leaves + on-device f64 accumulation;
        None when the matmul pack declines (wide features / code
        overflow), falling back to the leaf-readback path."""
        from ..ops.predict import (accumulate_scores, predict_leaf_matmul,
                                   rank_encode, split_hi_lo)
        x = np.asarray(x, dtype=np.float64)
        want = self.max_feature_idx + 1
        if x.shape[1] < want:
            x = np.pad(x, ((0, 0), (0, want - x.shape[1])))
        elif x.shape[1] > want:
            x = x[:, :want]
        pack = self._stacked_trees(nmodels)
        mm = self._matmul_cached(pack)
        if mm is None:
            return None
        if "lv_dev" not in pack or pack["lv_dev"] is None:
            pack["lv_dev"] = jnp.asarray(pack["lv"], dtype=jnp.float64)
        lv_dev = pack["lv_dev"]
        if lv_dev.dtype != jnp.float64:   # x64 actually off: not exact
            pack["lv_dev"] = None
            return None
        k = self.num_class
        n = x.shape[0]
        out = np.zeros((k, n), dtype=np.float64)
        tables, mm_dev = mm

        def per_chunk(chunk):
            xh, xl = split_hi_lo(chunk)
            code = rank_encode(xh, xl, tables)
            leaves = predict_leaf_matmul(
                *mm_dev, jnp.asarray(code),
                tree_block=self.PREDICT_TREE_BLOCK)
            return accumulate_scores(leaves[:, :nmodels], lv_dev,
                                     num_class=k)

        def write(a, rows, scores):
            out[:, a:a + rows] = scores[:, :rows]

        self._predict_pipeline(x, self.PREDICT_MM_CHUNK, per_chunk, write)
        return out

    def predict(self, x: np.ndarray) -> np.ndarray:
        raw = self.predict_raw(x)
        if self.sigmoid > 0:
            return 1.0 / (1.0 + np.exp(-2.0 * self.sigmoid * raw))
        if self.num_class > 1:
            e = np.exp(raw - raw.max(axis=0, keepdims=True))
            return e / e.sum(axis=0, keepdims=True)
        return raw

    def predict_leaf_index(self, x: np.ndarray) -> np.ndarray:
        k = self.num_class
        nmodels = self.num_used_model * k
        n = x.shape[0]
        if nmodels == 0 or n == 0:
            return np.zeros((n, nmodels), dtype=np.int64)
        return self._predict_leaves(x, nmodels)

    def set_num_used_model(self, num: int) -> None:
        if num >= 0:
            self.num_used_model = min(num // self.num_class,
                                      len(self.models) // self.num_class)

    # ------------------------------------------------------------------
    def save_model_to_file(self, num_used_model: int, is_finish: bool,
                           filename: str) -> None:
        """Incremental-append save (gbdt.cpp:351-400): holds back the last
        early_stopping_round trees until finish."""
        if self.saved_upto < 0:
            # atomic incremental save (resilience/atomic): trees stream
            # to a sibling tmp across segments; the finish commit
            # fsync+renames it into place, so a crash at ANY iteration
            # leaves the previous complete model file, never a
            # truncated one
            self._model_file = text_writer(filename)
            f = self._model_file
            f.write(self.name + "\n")
            f.write("num_class=%d\n" % self.num_class)
            f.write("label_index=%d\n" % self.label_idx)
            f.write("max_feature_idx=%d\n" % self.max_feature_idx)
            if self.objective is not None:
                f.write("objective=%s\n" % self.objective.name)
            f.write("sigmoid=%g\n" % self.sigmoid)
            f.write("\n")
            self.saved_upto = 0
        if self._model_file is None:
            return
        f = self._model_file
        if num_used_model == NO_LIMIT:
            num_used_model = len(self.models)
        else:
            num_used_model = num_used_model * self.num_class
        rest = num_used_model - self.early_stopping_round * self.num_class
        for i in range(self.saved_upto, rest):
            f.write("Tree=%d\n" % i)
            f.write(self.models[i].to_string() + "\n")
        self.saved_upto = max(self.saved_upto, rest)
        f.flush()
        if is_finish:
            for i in range(self.saved_upto, num_used_model):
                f.write("Tree=%d\n" % i)
                f.write(self.models[i].to_string() + "\n")
            f.write("\n" + self.feature_importance() + "\n")
            f.close()
            self._model_file = None

    def abort_model_save(self) -> None:
        """Discard an in-progress incremental save (graceful
        preemption): the sibling tmp is removed instead of orphaned,
        and the previously committed model file stays untouched."""
        if self._model_file is not None:
            self._model_file.abort()
            self._model_file = None
        self.saved_upto = -1

    def feature_importance(self) -> str:
        """Split-count importances (gbdt.cpp:458-485).  The reference
        orders ties among equal counts by non-stable std::sort; the native
        helper reruns that exact sort so the footer is byte-identical
        (falls back to a stable sort without the toolchain)."""
        imp = np.zeros(self.max_feature_idx + 1, dtype=np.int64)
        for tree in self.models:
            for s in tree.split_feature_real[:tree.num_leaves - 1]:
                imp[s] += 1
        names = (self.train_data.feature_names if self.train_data is not None
                 else ["Column_%d" % i for i in range(len(imp))])
        pairs = [(imp[i], names[i]) for i in range(len(imp)) if imp[i] > 0]
        from .. import native
        perm = native.sort_importance(np.asarray([p[0] for p in pairs]))
        if perm is not None:
            pairs = [pairs[i] for i in perm]
        else:
            pairs.sort(key=lambda p: -p[0])
        out = ["", "feature importances:"]
        out += ["%s=%d" % (name, cnt) for cnt, name in pairs]
        return "\n".join(out) + "\n"

    # -- exact-state checkpointing (superset of the reference, whose only
    # resume path re-boosts from predicted init scores and restarts the
    # bagging/feature RNG streams — SURVEY.md §5 checkpoint/resume) -----
    _TREE_FIELDS = ("split_feature", "split_feature_real", "threshold_bin",
                    "threshold", "split_gain", "left_child", "right_child",
                    "internal_value", "leaf_parent", "leaf_value",
                    "leaf_depth", "leaf_count")

    def save_checkpoint(self, path: str) -> None:
        """Snapshot the FULL trainer state: exact tree arrays (NOT the
        lossy 6-digit text format), score vectors, bagging masks,
        early-stopping bookkeeping and mt19937 stream positions.
        Resuming from it continues training bit-for-bit."""
        self._flush_pending()
        # ordered-partition mode keeps scores leaf-sorted; checkpoints
        # store FILE order plus the row order itself, so a restored
        # booster reconstructs the exact permuted state and resumes
        # bit-for-bit
        if self._mh_fused:
            # multi-host fused: each process snapshots ITS file-order
            # block (plus its local slice of the global row order below)
            scores = self._mh_local_file_scores()
        else:
            scores = np.asarray(self.scores)
            inv = self._inverse_row_order()
            if inv is not None:
                scores = scores[:, np.asarray(inv)]
            if self._layout_active:
                # checkpoints always store FILE order (+ trailing pad);
                # load_checkpoint re-places into the layout
                scores = self._unplace_host(scores)
        arrays = {
            "iter": np.int64(self.iter),
            "num_used_model": np.int64(self.num_used_model),
            "stopped": np.int64(self._stopped),
            "scores": scores,
            "bag_masks": np.stack(self.bag_masks),
            "num_valid_sets": np.int64(len(self.best_iter)),
            "num_trees": np.int64(len(self._models)),
            # bag compaction: whether the stored row order is the
            # in-bag-first arrangement of the stored masks (resume must
            # not re-arrange an already-arranged epoch), and whether a
            # sharded window overflow pinned this run to the masked path
            "bag_arranged": np.int64(self._bag_arranged),
            "bag_overflowed": np.int64(self._bag_overflowed),
        }
        if self._row_order is not None:
            arrays["row_order"] = (
                np.asarray(self.grower.local_rows(self._row_order))
                if self._mh_fused else np.asarray(self._row_order))
            arrays["trees_since_reorder"] = np.int64(
                self._trees_since_reorder)
        # per-valid-set keys: metric counts can differ between valid sets,
        # so one rectangular [sets, metrics] array would be ragged
        for i in range(len(self.best_iter)):
            arrays["best_iter_%d" % i] = np.asarray(self.best_iter[i],
                                                    dtype=np.int64)
            arrays["best_score_%d" % i] = np.asarray(self.best_score[i],
                                                     dtype=np.float64)
        for t, tree in enumerate(self._models):
            arrays["tree%d_num_leaves" % t] = np.int64(tree.num_leaves)
            for f in self._TREE_FIELDS:
                arrays["tree%d_%s" % (t, f)] = np.asarray(getattr(tree, f))
        for i, vs in enumerate(self.valid_scores):
            arrays["valid_scores_%d" % i] = np.asarray(vs)
        for name, rng in self._rng_streams():
            arrays[name] = rng.get_state()
        # config/dataset binding: load_checkpoint (and resume=auto's
        # snapshot validation) reject a snapshot whose run this booster
        # does not continue — shape-coincident state under changed
        # hyper-parameters would otherwise resume silently wrong
        arrays["resume_fp"] = np.array(resume_fingerprint(self))
        arrays.update(self._extra_checkpoint_arrays())
        # atomic + sha256-footered write (resilience/atomic.write_npz
        # keeps the exact path — a direct savez would append .npz to a
        # bare name, and a crash mid-write would leave a truncated
        # archive that poisons the next resume)
        write_npz(path, arrays)

    def _extra_checkpoint_arrays(self) -> dict:
        """Subclass hook: extra state for save_checkpoint (DART's device
        tree bank)."""
        return {}

    def _restore_extra_checkpoint(self, z) -> None:
        """Subclass hook: restore _extra_checkpoint_arrays state."""

    def load_checkpoint(self, path: str) -> None:
        """Restore a save_checkpoint snapshot into a booster built with
        the same config and datasets.  Raises
        resilience.atomic.IntegrityError on a corrupt/truncated
        snapshot (footer-less archives from older versions load
        unverified)."""
        z = read_npz(path)
        if "resume_fp" in z.files:
            want, have = str(z["resume_fp"]), resume_fingerprint(self)
            if want != have:
                z.close()
                log.fatal("checkpoint %s was written under a different "
                          "config/dataset (%s) — loading it would "
                          "silently continue the OLD run; delete the "
                          "snapshot or restore the original config"
                          % (path, fingerprint_diff(want, have)))
        self.iter = int(z["iter"])
        self._stopped = bool(z["stopped"])
        self._dev_stopped = (
            self.grower.replicate(np.asarray(self._stopped))
            if self._mh_fused else jnp.asarray(self._stopped))
        # checkpointed per-row state is in FILE order; when the snapshot
        # carries an ordered-partition row order, rebuild the exact
        # permuted state (bins/scores/objective state) so training
        # resumes bit-for-bit on the same accumulation order.  "Base"
        # space below = file order + trailing pad, or the query-granular
        # layout blocks when the rank shard layout is configured (the
        # row order permutes base positions in both cases).
        lay = self._shard_layout
        bins = self.train_data.bins if self.train_data is not None else None
        if bins is not None:
            if lay is not None:
                bins = lay.place(bins)
            elif self.n_pad != self.num_data:
                bins = np.pad(bins,
                              ((0, 0), (0, self.n_pad - self.num_data)))
        z_file = np.asarray(z["scores"])
        if lay is not None:
            self._layout_active = True
            z_base = lay.place(z_file[:, :self.num_data])
        else:
            z_base = z_file
        ordl = None     # this process's local base-space permutation
        if "row_order" in z:
            order = np.asarray(z["row_order"])
            self._trees_since_reorder = int(z["trees_since_reorder"])
            if self._mh_fused:
                # the snapshot holds THIS process's slice of the global
                # order (global positions); rebuild host-side in local
                # coordinates, then assemble the global arrays
                ordl = order - jax.process_index() * self.n_pad
                self._row_order = self.grower.shard_rows(
                    order.astype(np.int32), self.n_pad)
                self.bins_dev = self.grower.shard_bins(bins[:, ordl])
                self._gstate_override = self._restored_gstate(ordl)
                z_scores = z_base[:, ordl]
            else:
                ordl = order
                self._row_order = jnp.asarray(order, dtype=jnp.int32)
                self.bins_dev = jnp.asarray(bins[:, order])
                self._gstate_override = self._restored_gstate(ordl)
                z_scores = z_base[:, order]
            bag_restored = True
        else:
            if bins is not None and (self._row_order is not None
                                     or lay is not None):
                self.bins_dev = (self.grower.shard_bins(bins)
                                 if self._mh_fused or lay is not None
                                 else jnp.asarray(bins))
            self._row_order = None
            self._trees_since_reorder = 0
            self._gstate_override = None
            z_scores = z_base
            bag_restored = False
        self._inv_order = None
        if self._mh_fused:
            self.scores = self.grower.shard_rows(z_scores, self.n_pad)
        else:
            self.scores = jnp.asarray(z_scores)
            if self.grower is not None and self.rows_sharded \
                    and not self._mh:
                self.scores = jax.device_put(
                    self.scores, self.grower.row_sharding_2d())
        self.bag_masks = [m.copy() for m in z["bag_masks"]]
        self._bag_dev = [None] * self.num_class
        self._bag_dev_packed = [None] * self.num_class
        self._bag_stacked = None
        self._bag_arranged = bool(z["bag_arranged"]) \
            if "bag_arranged" in z else False
        self._bag_overflowed = bool(z["bag_overflowed"]) \
            if "bag_overflowed" in z else False
        if bag_restored:
            # the fused-path device bag mask must follow the restored row
            # order (host bag_masks stay in file order like everything host)
            bag_base = self.bag_masks[0]
            if lay is not None:
                bag_base = lay.place(bag_base[:self.num_data], fill=False)
            bag_ordered = bag_base[ordl]
            self._bag_dev_packed[0] = (
                self.grower.shard_rows(bag_ordered, self.n_pad)
                if self._mh_fused else jnp.asarray(bag_ordered))
        if "num_valid_sets" in z:
            nv = int(z["num_valid_sets"])
            self.best_iter = [[int(v) for v in z["best_iter_%d" % i]]
                              for i in range(nv)]
            self.best_score = [[float(v) for v in z["best_score_%d" % i]]
                               for i in range(nv)]
        else:   # 0.1.0 checkpoints: one rectangular [sets, metrics] array
            self.best_iter = [list(map(int, r)) for r in z["best_iter"]]
            self.best_score = [list(map(float, r)) for r in z["best_score"]]
        vput = (self.grower.replicate if self._mh_fused else jnp.asarray)
        for i in range(len(self.valid_scores)):
            self.valid_scores[i] = vput(z["valid_scores_%d" % i])
        for name, rng in self._rng_streams():
            rng.set_state(z[name])
        self._models = []
        for t in range(int(z["num_trees"])):
            fields = {f: z["tree%d_%s" % (t, f)].copy()
                      for f in self._TREE_FIELDS}
            self._models.append(Tree(
                num_leaves=int(z["tree%d_num_leaves" % t]), **fields))
        # honor a SetNumUsedModel cap active at checkpoint time
        self.num_used_model = min(int(z["num_used_model"]),
                                  len(self._models) // self.num_class)
        self._restore_extra_checkpoint(z)
        z.close()       # read_npz is lazy now: drop the archive's fd

    def _restored_gstate(self, ordl):
        """Gradient-state override matching a restored row order: the
        objective's permute fn over base state (elementwise), or the
        host-side per-shard permute of the query-sharded state (the
        re-sorts were shard-local, so the permutation applies block by
        block before the device put)."""
        if self._layout_active:
            host, specs = self._build_sharded_gstate_host()
            host = self.objective.permute_sharded_state_host(
                host, self._shard_layout, ordl)
            self._gstate_specs = specs
            return tuple(self.grower.put_spec(a, sp)
                         for a, sp in zip(host, specs))
        if not getattr(self.objective, "row_permutable", False):
            return None
        gs = self.objective.make_permute_fn()(
            self.objective.grad_state(),
            jnp.asarray(np.asarray(ordl), dtype=jnp.int32))
        if self._mh_fused:
            gs = jax.tree_util.tree_map(
                lambda a: self.grower.shard_rows(np.asarray(a),
                                                 self.n_pad), gs)
        return gs

    def _rng_streams(self):
        out = [("bag_rng", self.bag_rng)]
        out += [("feat_rng_%d" % i, r) for i, r in enumerate(self.feat_rngs)]
        if hasattr(self, "drop_rng"):
            out.append(("drop_rng", self.drop_rng))
        return out

    def load_model_from_string(self, model_str: str) -> None:
        """GBDT::LoadModelFromString (gbdt.cpp:402-456).  Header + tree
        parsing is shared with the native predict fast path via
        models.tree.parse_model_text."""
        from .tree import parse_model_text

        header, trees = parse_model_text(model_str)
        self.num_class = header["num_class"]
        self.label_idx = header["label_index"]
        self.max_feature_idx = header["max_feature_idx"]
        if header["sigmoid"] is not None:
            self.sigmoid = header["sigmoid"]
        self.models = trees
        self.num_used_model = len(self.models) // self.num_class


class DART(GBDT):
    """Dropout boosting (reference src/boosting/dart.hpp).

    The serial single-class path with a traceable objective runs the
    BANKED fused iteration (_make_fused_step_dart): trees stay packed on
    device, the per-iteration drop/normalize score surgery happens
    in-dispatch, and host trees materialize from the async-copied
    as-trained rows plus an exact f64 replay of each tree's drop-factor
    history — no per-iteration host round trips and no drift from
    device-dtype compounding.  Multiclass, custom gradients and
    continued training keep the host-tree path."""
    name = "dart"

    def __init__(self, config: Config, train_data, objective,
                 training_metrics=()):
        super().__init__(config, train_data, objective, training_metrics)
        self.drop_rate = config.drop_rate
        self.drop_rng = Mt19937Random(config.drop_seed)
        self.drop_index: List[int] = []
        self._bank = None           # [bank_ints [T+1, Li], bank_floats]
        self._bank_count = 0
        self._bank_disabled = False
        self._bank_dirty = False    # drop factors newer than host trees
        # per-row drop-factor history [(iteration, rate, k), ...]: the
        # host-side f64 record of every tree->Shrinkage chain the device
        # applied (in its own dtype) to the bank row
        self._bank_hist = {}
        self._bank_lv0 = {}         # row -> as-trained f64 leaf values
        # the banked path defers flushes like the fused GBDT paths; the
        # host-tree fallback needs trees (and the drop surgery) per
        # iteration
        self._flush_every = 16 if self._can_fuse_dart() else 1

    @contract.rank_uniform
    def _can_fuse_dart(self) -> bool:
        # objective check first: prediction-only instances return before
        # GBDT.__init__ sets grower/hist attributes
        return (getattr(self.objective, "jax_traceable", False)
                and self.num_class == 1
                and getattr(self, "grower", None) is None
                and not self._bank_disabled
                and self.objective.fused_key() is not None)

    def _compact_fusible(self) -> bool:
        # bag compaction attaches to the banked fused path; the
        # host-tree fallback keeps the masked oracle
        return self._can_fuse_dart()

    def _segment_fusible(self) -> bool:
        # iteration batching rides the banked path only (host-tree DART
        # needs per-iteration score surgery on host trees)
        return (self._can_fuse_dart()
                and (self._bank is not None or not self._models))

    def _train_segment_fused(self, k: int) -> None:
        self._run_fused_dart(k)

    def _dart_bank_rows(self):
        """The leaf bank [T, n_pad] is per-row state: the in-bag-first
        arrangement must carry it (drop/normalize gathers read it by
        row position)."""
        return self._bank[2] if self._bank is not None else None

    def _set_dart_bank_rows(self, arr) -> None:
        self._bank[2] = arr

    def _score_for_gradients(self):
        self._dropping_trees()
        return super()._score_for_gradients()

    def train_one_iter(self, gradients=None, hessians=None,
                       is_eval: bool = True) -> bool:
        if (gradients is None and self._can_fuse_dart()
                and (self._bank is not None or not self._models)):
            return self._train_one_iter_banked(is_eval)
        self._exit_bank_mode()
        stopped = super().train_one_iter(gradients, hessians, False)
        self._normalize()
        if stopped:
            return True
        if is_eval:
            return self.eval_and_check_early_stopping()
        return False

    # -- banked fused path ---------------------------------------------
    def _train_one_iter_banked(self, is_eval: bool) -> bool:
        self._run_fused_dart()
        self.iter += 1
        self.num_used_model = len(self._models) // self.num_class
        if self.iter % self._flush_every == 0 and not is_eval:
            if self._sync_stop(self._flush_pending()):
                log.info("Stopped training because there are no more "
                         "leafs that meet the split requirements.")
                return True
        if is_eval:
            return self.eval_and_check_early_stopping()
        return False

    def _draw_drops(self, it: int) -> None:
        """The drop lottery (dart.hpp:86-99) for iteration `it`, shared
        verbatim by both paths so the mt19937 stream stays golden-pinned.
        Pure host state (drop_rng position + `it`), so a K-iteration
        segment precomputes all K lotteries before the dispatch."""
        self.drop_index = []
        if self.drop_rate > 1e-15:
            if it > 0:
                draws = self.drop_rng.next_doubles(it)
                self.drop_index = [i for i in range(it)
                                   if draws[i] < self.drop_rate]
        if not self.drop_index and it > 0:
            self.drop_index = list(self.drop_rng.sample(it, 1))
        self.shrinkage_rate = 1.0 / (1.0 + len(self.drop_index))

    def _ensure_bank_capacity(self, k_iters: int) -> None:
        """Bank rows for the next k_iters trees (+ the dummy row dead
        steps write to); initializes on first use, doubles past
        config.num_iterations (api num_boost_round, bench loops)."""
        cfg = self.config
        L = max(cfg.num_leaves, 2)
        SF0, TB0, LC0, RC0, RC1, LV0, LV1 = _dart_layout(L)
        leaf_dt = np.uint8 if L <= 256 else np.int32
        if self._bank is None:
            T = max(cfg.num_iterations, k_iters) + 1  # + dummy row
            li = 1 + 4 * (L - 1) + 3 * L
            lf = 3 * L - 2
            bi = np.zeros((T, li), np.int32)
            # untouched rows must TERMINATE traversal: child slots -1
            # (~0 = leaf 0, whose value is 0.0) instead of a node-0
            # self-loop
            bi[:, LC0:RC1] = -1
            self._bank = [jnp.asarray(bi),
                          jnp.zeros((T, lf), dtype=self.dtype),
                          jnp.zeros((T, self.n_pad), dtype=leaf_dt),
                          [jnp.zeros((T, int(vb.shape[1])), dtype=leaf_dt)
                           for vb in self.valid_bins_dev]]
            self._bank_count = 0
        while self._bank_count + k_iters > self._bank[0].shape[0] - 1:
            # double the bank, keeping new rows traversal-safe.  The OLD
            # dummy row becomes a real row — reset it too: dead
            # (post-stop) steps may have written a garbage tree there,
            # which would otherwise materialize as a phantom model entry
            T = self._bank[0].shape[0]
            safe = np.zeros((1, self._bank[0].shape[1]), np.int32)
            safe[:, LC0:RC1] = -1
            pad_i = np.repeat(safe, T, axis=0)

            def dbl(a):
                return jnp.concatenate(
                    [a, jnp.zeros((T,) + a.shape[1:], dtype=a.dtype)])

            self._bank = [
                jnp.concatenate([self._bank[0][:-1],
                                 jnp.asarray(safe), jnp.asarray(pad_i)]),
                dbl(self._bank[1].at[T - 1].set(0.0)),
                dbl(self._bank[2]),
                [dbl(vb) for vb in self._bank[3]]]

    def _run_fused_dart(self, k_iters: int = 1) -> None:
        cfg = self.config
        L = max(cfg.num_leaves, 2)
        self._ensure_bank_capacity(k_iters)
        # per-iteration host inputs, drawn in the exact sequential order
        # (drop lottery -> bagging -> feature mask per iteration): drop
        # lists, 1/(1+k) shrinkages and normalization factors are pure
        # host/mt19937 state, so a K-segment precomputes them all and
        # feeds them as stacked [K, ...] scan inputs
        drops, rates, kfs, fmasks = [], [], [], []
        for j in range(k_iters):
            it = self.iter + j
            self._draw_drops(it)
            kd = len(self.drop_index)
            # record this cycle's f64 factor pair against every dropped
            # row (replayed at materialization; entries from iterations
            # past a stump stop are filtered out there, matching the
            # device gating)
            for i in self.drop_index:
                self._bank_hist.setdefault(i, []).append(
                    (it, self.shrinkage_rate, float(kd)))
            drops.append(list(self.drop_index))
            rates.append(self.shrinkage_rate)
            kfs.append(float(kd))
            self._bagging(it, 0)
            if j == 0:
                self._ensure_bag_arranged()
            fmasks.append(self._feature_mask(0))
        compact = self._bag_compact_rows() if self._bag_arranged else 0
        # fixed cap -> ONE executable for every drop count <= 8 (padded
        # slots are lax.cond-skipped); pow2 buckets beyond are the rare
        # escape for high drop rates.  A segment pads every iteration to
        # its max bucket so the whole segment shares one executable.
        dp = 8
        while dp < max(len(d) for d in drops):
            dp *= 2
        drop_idx = np.zeros((k_iters, dp), np.int32)
        drop_mask = np.zeros((k_iters, dp), bool)
        for j, d in enumerate(drops):
            drop_idx[j, :len(d)] = d
            drop_mask[j, :len(d)] = True
        key = ("dart", self.objective.fused_key(), self.dtype,
               self.hist_impl, self.max_bin, L, cfg.max_depth,
               self.params, len(self.valid_bins_dev), self.hist_slots,
               self.hist_compact, self.hist_ranged, self.hist_fused,
               self.hist_acc, dp, compact, k_iters)

        def make():
            grow_kw = self._grow_kw()
            return _make_fused_step_dart(self.objective.make_grad_fn(),
                                         grow_kw, self.dtype, L, compact,
                                         k_iters)

        fn = _get_fused_step(key, make)
        _note_dispatch()
        if k_iters == 1:
            dev_in = (jnp.asarray(drop_idx[0]), jnp.asarray(drop_mask[0]),
                      jnp.asarray(rates[0], dtype=self.dtype),
                      jnp.asarray(kfs[0], dtype=self.dtype))
            t_row = jnp.int32(self._bank_count)
        else:
            dev_in = (jnp.asarray(drop_idx), jnp.asarray(drop_mask),
                      jnp.asarray(np.asarray(rates, dtype=np.float64)
                                  .astype(self.dtype)),
                      jnp.asarray(np.asarray(kfs, dtype=np.float64)
                                  .astype(self.dtype)))
            t_row = jnp.arange(self._bank_count,
                               self._bank_count + k_iters,
                               dtype=jnp.int32)
        (self.scores, valid, bi, bf, lb, vbs, ints, floats,
         self._dev_stopped) = fn(
            self.scores, list(self.valid_scores), self._bank[0],
            self._bank[1], self._bank[2], list(self._bank[3]),
            dev_in[0], dev_in[1], dev_in[2], dev_in[3],
            self._bag_mask_dev_fused(0),
            jnp.asarray(fmasks[0] if k_iters == 1 else np.stack(fmasks)),
            self.bins_dev, tuple(self.valid_bins_dev),
            self._gstate_for_fused(), self._dev_stopped, t_row)
        self._bank = [bi, bf, lb, list(vbs)]
        self.valid_scores = list(valid)
        # raw floats + each iteration's 1/(1+k) shrinkage applied on the
        # host in f64, like every other fused path
        if k_iters == 1:
            self._models.append(_PendingTree(ints, floats, rates[0],
                                             gated=True))
        else:
            self._models.extend(
                _PendingTree(ints[j], floats[j], rates[j], gated=True)
                for j in range(k_iters))
        self._bank_count += k_iters
        self._bank_dirty = True

    def _materialize_bank(self) -> None:
        """Refresh every materialized tree's leaf values by replaying
        its recorded drop-factor chain in FLOAT64 from the as-trained
        values — exactly the host/reference tree->Shrinkage sequence
        (the device bank compounds the same chain in the histogram dtype
        for score updates only).  Runs after the base flush so new
        pending trees exist as host Trees; entries from iterations past
        a stump stop are excluded, matching the device's live gating."""
        if self._bank is None or not self._bank_dirty:
            return
        stop_iter = self.iter if self._stopped else float("inf")
        for idx, tree in enumerate(self._models):
            lv0 = self._bank_lv0.get(idx)
            if lv0 is None:
                lv0 = np.asarray(tree.leaf_value, dtype=np.float64).copy()
                self._bank_lv0[idx] = lv0
            v = lv0.copy()
            for it, rate, k in self._bank_hist.get(idx, ()):
                if it > stop_iter:
                    break
                v *= -1.0
                v *= rate
                v *= -k
            tree.leaf_value = v
        self._bank_dirty = False

    def _flush_pending(self) -> bool:
        stopped = super()._flush_pending()
        self._materialize_bank()
        return stopped

    def _exit_bank_mode(self) -> None:
        """Leave the banked path permanently (custom gradients, objective
        swap, continued training): host trees become authoritative."""
        if self._bank_disabled:
            return
        if self._bank is not None:
            self._flush_pending()   # base flush + f64 replay
        self._bank = None
        self._bank_disabled = True
        self._flush_every = 1

    def _dropping_trees(self) -> None:
        """dart.hpp:86-110 on HOST trees (non-banked path): drop trees
        from the train score, set shrinkage."""
        self._draw_drops(self.iter)
        for i in self.drop_index:
            for cls in range(self.num_class):
                t = self.models[i * self.num_class + cls]
                t.shrinkage(-1.0)
                self._add_tree_to_scores(t, cls, 1.0, train=True, valid=False)

    def _normalize(self) -> None:
        """dart.hpp:114-129."""
        k = float(len(self.drop_index))
        for i in self.drop_index:
            for cls in range(self.num_class):
                t = self.models[i * self.num_class + cls]
                t.shrinkage(self.shrinkage_rate)
                self._add_tree_to_scores(t, cls, 1.0, train=False, valid=True)
                t.shrinkage(-k)
                self._add_tree_to_scores(t, cls, 1.0, train=True, valid=False)

    def save_model_to_file(self, num_used_model, is_finish, filename):
        # DART only saves once training finished (dart.hpp:71-76)
        if is_finish and self.saved_upto < 0:
            super().save_model_to_file(num_used_model, is_finish, filename)

    # -- checkpointing of the device bank ------------------------------
    def _extra_checkpoint_arrays(self) -> dict:
        """Bank state for exact banked resume: the (mutated) device rows,
        the drop-factor history and the as-trained leaf values the f64
        replay starts from.  Host-tree-path snapshots mark bank=0 and
        restore into the host path."""
        if self._bank is None:
            return {"dart_bank": np.int64(0)}
        out = {
            "dart_bank": np.int64(1),
            "dart_bank_count": np.int64(self._bank_count),
            "dart_bank_i": np.asarray(self._bank[0]),
            "dart_bank_f": np.asarray(self._bank[1]),
            "dart_bank_hist": np.asarray(
                [(r, it, rate, k)
                 for r, entries in sorted(self._bank_hist.items())
                 for (it, rate, k) in entries],
                dtype=np.float64).reshape(-1, 4),
            "dart_bank_lv0_rows": np.asarray(
                sorted(self._bank_lv0), dtype=np.int64),
        }
        if self._bank_lv0:
            out["dart_bank_lv0"] = np.stack(
                [self._bank_lv0[r] for r in sorted(self._bank_lv0)])
        return out

    def _restore_extra_checkpoint(self, z) -> None:
        if ("dart_bank" not in z or int(z["dart_bank"]) == 0
                or self.train_data is None):
            # host-tree-path snapshot (or a pre-bank version): resume
            # through the host path, whose trees the base restore rebuilt
            self._bank = None
            self._bank_disabled = True
            self._bank_hist = {}
            self._bank_lv0 = {}
            self._bank_dirty = False
            self._flush_every = 1
            return
        bank_i = jnp.asarray(np.asarray(z["dart_bank_i"]))
        bank_f = jnp.asarray(np.asarray(z["dart_bank_f"]),
                             dtype=self.dtype)
        self._bank_count = int(z["dart_bank_count"])
        # leaf-assignment banks are NOT checkpointed ([T, N] would dwarf
        # the snapshot); rebuild them with one traversal per restored
        # tree — structure is immutable, so this reproduces the training-
        # time leaf ids exactly.  Rows collect in HOST buffers and upload
        # once (per-tree .at[t].set on the device bank would copy the
        # whole [T, N] array per tree: O(T^2 N) traffic).
        T = int(bank_i.shape[0])
        L = max(self.config.num_leaves, 2)
        leaf_dt = np.uint8 if L <= 256 else np.int32
        lb = np.zeros((T, self.n_pad), dtype=leaf_dt)
        vbs = [np.zeros((T, int(vb.shape[1])), dtype=leaf_dt)
               for vb in self.valid_bins_dev]
        for t, tree in enumerate(self._models[:self._bank_count]):
            sf = jnp.asarray(tree.split_feature)
            tb = jnp.asarray(tree.threshold_bin)
            lc = jnp.asarray(tree.left_child)
            rc = jnp.asarray(tree.right_child)
            lb[t] = np.asarray(predict_leaf_binned(
                sf, tb, lc, rc, self.bins_dev)).astype(leaf_dt)
            for i, vbins in enumerate(self.valid_bins_dev):
                vbs[i][t] = np.asarray(predict_leaf_binned(
                    sf, tb, lc, rc, vbins)).astype(leaf_dt)
        self._bank = [bank_i, bank_f, jnp.asarray(lb),
                      [jnp.asarray(vb) for vb in vbs]]
        self._bank_disabled = False
        self._bank_dirty = False      # restored trees hold final values
        hist = {}
        for r, it, rate, k in np.asarray(z["dart_bank_hist"]).reshape(-1, 4):
            hist.setdefault(int(r), []).append((int(it), float(rate),
                                                float(k)))
        self._bank_hist = hist
        rows = [int(r) for r in z["dart_bank_lv0_rows"]]
        self._bank_lv0 = (
            {r: np.asarray(z["dart_bank_lv0"])[i].copy()
             for i, r in enumerate(rows)} if rows else {})


def create_boosting(config: Config, train_data, objective,
                    training_metrics=()) -> GBDT:
    if config.boosting_type == "dart":
        return DART(config, train_data, objective, training_metrics)
    return GBDT(config, train_data, objective, training_metrics)


def boosting_type_from_model_file(path: str) -> str:
    """Sniff first line (reference src/boosting/boosting.cpp:7-16)."""
    with open(path) as f:
        first = f.readline().strip()
    return first if first in ("gbdt", "dart") else "gbdt"
