"""Host-side tree model with reference-compatible text serialization.

The on-device representation during growth is ops.grow.TreeArrays; this
class is its host twin used for model IO and prediction bookkeeping.
Text format is byte-compatible with Tree::ToString / Tree::Tree(str)
(reference src/io/tree.cpp:105-176): same keys, same ordering, same
6-significant-digit default ostream formatting.
"""

from __future__ import annotations

__jax_free__ = True

import dataclasses
from typing import List

import numpy as np

from ..io.parser import _clean_token


def _fmt(x: float) -> str:
    """C++ `ostream << double` default formatting (6 significant digits)."""
    return "%g" % x


def _fmt_arr(a) -> str:
    return " ".join(_fmt(x) for x in a)


def _fmt_int_arr(a) -> str:
    return " ".join(str(int(x)) for x in a)


@dataclasses.dataclass
class Tree:
    num_leaves: int
    # node arrays [num_leaves - 1]
    split_feature: np.ndarray        # inner (used-feature) index
    split_feature_real: np.ndarray   # original column index
    threshold_bin: np.ndarray
    threshold: np.ndarray            # real-valued (bin upper bound)
    split_gain: np.ndarray
    left_child: np.ndarray
    right_child: np.ndarray
    internal_value: np.ndarray
    # leaf arrays [num_leaves]
    leaf_parent: np.ndarray
    leaf_value: np.ndarray
    leaf_depth: np.ndarray
    leaf_count: np.ndarray

    def shrinkage(self, rate: float) -> None:
        """Tree::Shrinkage (reference include/LightGBM/tree.h:95-99)."""
        self.leaf_value = self.leaf_value * rate

    # ------------------------------------------------------------------
    def to_string(self) -> str:
        nl = self.num_leaves
        lines = [
            "num_leaves=%d" % nl,
            "split_feature=" + _fmt_int_arr(self.split_feature_real[:nl - 1]),
            "split_gain=" + _fmt_arr(self.split_gain[:nl - 1]),
            "threshold=" + _fmt_arr(self.threshold[:nl - 1]),
            "left_child=" + _fmt_int_arr(self.left_child[:nl - 1]),
            "right_child=" + _fmt_int_arr(self.right_child[:nl - 1]),
            "leaf_parent=" + _fmt_int_arr(self.leaf_parent[:nl]),
            "leaf_value=" + _fmt_arr(self.leaf_value[:nl]),
            "internal_value=" + _fmt_arr(self.internal_value[:nl - 1]),
            "",
        ]
        return "\n".join(lines) + "\n"

    @staticmethod
    def from_string(s: str) -> "Tree":
        kv = {}
        for line in s.splitlines():
            parts = line.split("=", 1)
            if len(parts) == 2 and parts[0].strip() and parts[1].strip():
                kv[parts[0].strip()] = parts[1].strip()
        required = ("num_leaves", "split_feature", "split_gain", "threshold",
                    "left_child", "right_child", "leaf_parent", "leaf_value",
                    "internal_value")
        for k in required:
            if k not in kv:
                raise ValueError("Tree model string format error: missing %s" % k)
        nl = int(kv["num_leaves"])

        def ints(key, cnt):
            if cnt <= 0:
                return np.zeros(0, np.int32)
            return np.array(kv[key].split()[:cnt], dtype=np.int32)

        def floats(key, cnt):
            # the reference reads model doubles back through its Atof
            # (StringToArray<double>, common.h:229-247 -> Atof), whose
            # digit arithmetic is NOT correctly-rounded — parse the same
            # way so loaded thresholds compare against Atof-parsed data
            # values exactly as the reference binary would.  Native batch
            # path keeps big-model loads fast; token loop is the fallback.
            if cnt <= 0:
                return np.zeros(0, np.float64)
            toks = kv[key].split()[:cnt]
            from .. import native
            nat = native.parse_doubles(" ".join(toks).encode(), len(toks))
            if nat is not None:
                return nat
            return np.array([_clean_token(t) for t in toks],
                            dtype=np.float64)

        sf = ints("split_feature", nl - 1)
        return Tree(
            num_leaves=nl,
            split_feature=sf.copy(),       # inner==real when loaded from text
            split_feature_real=sf,
            threshold_bin=np.zeros(max(nl - 1, 0), dtype=np.int32),
            threshold=floats("threshold", nl - 1),
            split_gain=floats("split_gain", nl - 1),
            left_child=ints("left_child", nl - 1),
            right_child=ints("right_child", nl - 1),
            internal_value=floats("internal_value", nl - 1),
            leaf_parent=ints("leaf_parent", nl),
            leaf_value=floats("leaf_value", nl),
            leaf_depth=np.zeros(nl, dtype=np.int32),
            leaf_count=np.zeros(nl, dtype=np.int32),
        )

    # ------------------------------------------------------------------
    def predict(self, x: np.ndarray) -> np.ndarray:
        """Batch raw-feature prediction, [N, num_total_features] -> [N] f64.
        Vectorized equivalent of Tree::GetLeaf (tree.h:179-189)."""
        return self.leaf_value[self.predict_leaf_index(x)]

    def predict_leaf_index(self, x: np.ndarray) -> np.ndarray:
        n = x.shape[0]
        node = np.zeros(n, dtype=np.int64)
        if self.num_leaves == 1:
            return node
        active = node >= 0
        while active.any():
            idx = node[active]
            feat = self.split_feature_real[idx]
            thr = self.threshold[idx]
            val = x[active, feat]
            nxt = np.where(val <= thr, self.left_child[idx],
                           self.right_child[idx])
            node[active] = nxt
            active = node >= 0
        return ~node


def select_used_trees(trees: List["Tree"], num_class: int,
                      num_model_predict: int) -> List["Tree"]:
    """set_num_used_model resolution, shared by the native predict fast
    path and serving: num_model_predict counts ITERATIONS, each holding
    num_class trees (gbdt.cpp:455-456); < 0 keeps everything."""
    num_used = len(trees) // num_class
    if num_model_predict >= 0:
        num_used = min(num_model_predict, num_used)
    return trees[:num_used * num_class]


def parse_model_text(model_str: str):
    """Model text -> (header dict, [Tree]) — the jax-free core of
    GBDT::LoadModelFromString (reference gbdt.cpp:402-456), shared by
    GBDT.load_model_from_string and the native predict fast path
    (predict_fast._LightModel) so the two readers cannot drift.

    Header keys: num_class, label_index, max_feature_idx (ints, fatal if
    absent like the reference) and sigmoid (Atof-parsed; None when the
    line is absent, so callers can keep their configured value exactly
    like the original in-place parse did)."""
    from ..utils import log

    lines = model_str.splitlines()

    def find_line(prefix: str) -> str:
        for ln in lines:
            if prefix in ln:
                return ln
        return ""

    header = {}
    ln = find_line("num_class=")
    if not ln:
        log.fatal("Model file doesn't specify the number of classes")
    header["num_class"] = int(ln.split("=")[1])
    ln = find_line("label_index=")
    if not ln:
        log.fatal("Model file doesn't specify the label index")
    header["label_index"] = int(ln.split("=")[1])
    ln = find_line("max_feature_idx=")
    if not ln:
        log.fatal("Model file doesn't specify max_feature_idx")
    header["max_feature_idx"] = int(ln.split("=")[1])
    header["sigmoid"] = None
    ln = find_line("sigmoid=")
    if ln:
        # Atof semantics, like every double the reference reads back
        header["sigmoid"] = _clean_token(ln.split("=")[1])

    trees: List[Tree] = []
    i = 0
    while i < len(lines):
        if lines[i].startswith("Tree="):
            j = i + 1
            while j < len(lines) and not lines[j].startswith("Tree="):
                j += 1
            block = "\n".join(lines[i + 1:j])
            if "num_leaves=" in block:
                trees.append(Tree.from_string(block))
            i = j
        else:
            i += 1
    log.info("Finished loading %d models" % len(trees))
    return header, trees
