"""Continuous train->deploy: online model refresh with shadow-eval
gating (refresh/agent.py).

The package closes the loop the other subsystems left open: ingest
streams data in (PR 9), training warm-starts from the champion
(init_model, api/cli), the serving fleet hot-swaps models behind one
port (PR 8) — the refresh agent wires them into the production story
where data arrives, the model retrains, the fleet updates, and users
never notice.
"""

from __future__ import annotations

__jax_free__ = True

from .agent import RefreshAgent, run_refresh_cli

__all__ = ["RefreshAgent", "run_refresh_cli"]
