"""The jax-free deploy agent: watch -> retrain -> shadow-eval -> promote.

`task=refresh` runs this agent next to the PR 8 serving fleet (same
jax-free supervisor profile as serving/frontend.py: it only watches a
directory, spawns subprocesses and talks HTTP — the heavy lifting
happens in a fresh `task=train` interpreter per cycle and inside the
serving workers).  One refresh cycle:

  1. WATCH    new data files land in `refresh_drop_dir`; a file is
              picked up only once its (size, mtime) held still across
              two polls (half-written drops never train).
  2. RETRAIN  a `task=train` subprocess warm-starts from the current
              champion (`input_model=` continued training, optionally
              through a `task=ingest` shard pass first) and writes the
              challenger model atomically.
  3. PUSH     the challenger enters the serving fleet NON-default
              (POST /reload {"model":.., "default": false}) — on every
              SO_REUSEPORT worker, confirmed by sha via /healthz.
  4. SHADOW   the held-out eval rows are mirrored through the batcher
              to champion (default route) AND challenger
              (/predict?model=) concurrently; both answer the SAME
              bytes-in, and the agent scores both answer sets against
              the labels.
  5. PROMOTE  only on a metric win (lower loss by > refresh_min_gain):
              POST /reload {"model": challenger} repoints the default
              on every worker.  A losing or erroring challenger is
              demoted (never made default) and counted.

Hardening: every network/subprocess step runs under a deadline with
the shared resilience/backoff retry curve; the named faultpoints
`refresh.train_spawn`, `refresh.eval`, `deploy.push` and
`deploy.promote` make each seam chaos-testable (an injected `raise` is
a cycle FAILURE, never retried away — kill schedules prove a dead
agent leaves the fleet serving the champion byte-identically, and the
rerun converges).  Consecutive cycle failures past
`refresh_breaker_threshold` open a circuit breaker: the agent stops
retraining for `refresh_cooldown_s` and the champion keeps serving.
Durable agent state (consumed drops, champion lineage, outcome
counters) lives in one atomically-written JSON file, so a SIGKILL at
any instant reruns the interrupted cycle deterministically.
"""

from __future__ import annotations

__jax_free__ = True

import hashlib
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from ..config import Config
from ..ingest.manifest import snapshot_sources
from ..models.tree import parse_model_text
from ..resilience.atomic import atomic_write_bytes, atomic_writer
from ..resilience.backoff import Backoff, retry_with_backoff
from ..resilience.faults import FaultInjected, faultpoint
from ..utils import log

STATE_NAME = "refresh_state.json"

#: training keys the agent forwards verbatim to the retrain (and
#: ingest) subprocess — the operator writes ONE conf holding both the
#: refresh_* keys and the training hyper-parameters, exactly like
#: task=train would read it.  `refresh_train_args` appends after these,
#: so explicit extras win (CLI precedence).
FORWARD_KEYS: Tuple[str, ...] = (
    "objective", "boosting_type", "num_class", "num_leaves",
    "max_depth", "max_bin", "min_data_in_leaf",
    "min_sum_hessian_in_leaf", "learning_rate", "lambda_l1",
    "lambda_l2", "min_gain_to_split", "feature_fraction",
    "feature_fraction_seed", "bagging_fraction", "bagging_freq",
    "bagging_seed", "data_random_seed", "drop_rate", "drop_seed",
    "sigmoid", "label_column", "weight_column", "group_column",
    "ignore_column", "bin_construct_sample_cnt", "has_header",
    "device_type", "hist_impl", "hist_dtype",
)

#: objectives the shadow eval scores with a proper loss; anything else
#: falls back to L2 on the raw scores with a warning (once)
EVAL_LOSSES = ("binary", "multiclass", "regression")


class CycleError(RuntimeError):
    """One refresh cycle failed (retrain, push, eval or promote); the
    champion keeps serving and the drop files stay unconsumed."""


def _fmt_param(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


_SHA_CACHE: Dict[str, Tuple[Tuple[int, int], str]] = {}


def _sha256_file_cached(path: str) -> str:
    """_sha256_file memoized by (size, mtime_ns): a Prometheus scrape
    loop must not stream + hash a hundreds-of-MB model file every 10s
    for a value that only changes at promotion."""
    try:
        st = os.stat(path)
    except OSError:
        return "missing"
    key = (st.st_size, st.st_mtime_ns)
    hit = _SHA_CACHE.get(path)
    if hit is not None and hit[0] == key:
        return hit[1]
    sha = _sha256_file(path)
    _SHA_CACHE[path] = (key, sha)
    return sha


def _tail(text: str, lines: int = 15) -> str:
    return "\n".join(text.splitlines()[-lines:])


# ---------------------------------------------------------------------------
# shadow-eval scoring (host-side, numpy only)
# ---------------------------------------------------------------------------

def parse_label_column(body: bytes, label_idx: int) -> np.ndarray:
    """Labels from held-out rows in the task=predict data-file format
    (CSV/TSV/LibSVM, sniffed with the shared io/parser rule)."""
    from ..io.parser import sniff_format
    chunks = iter((body,))
    fmt, sep = sniff_format(lambda: next(chunks, b""))
    labels: List[float] = []
    for ln in body.decode("utf-8", "replace").splitlines():
        if not ln.strip("\r"):
            continue
        if fmt == "libsvm":
            labels.append(float(ln.split(None, 1)[0]))
        else:
            labels.append(float(ln.split(sep)[label_idx]))
    return np.asarray(labels, dtype=np.float64)


def parse_score_rows(body: bytes) -> np.ndarray:
    """A /predict?mode=raw response -> [N, K] scores (one line per
    row, K whitespace-separated values — the task=predict format)."""
    rows = [[float(t) for t in ln.split()]
            for ln in body.decode("utf-8", "replace").splitlines()
            if ln.strip()]
    if not rows:
        return np.zeros((0, 1), dtype=np.float64)
    return np.asarray(rows, dtype=np.float64)


def shadow_loss(scores: np.ndarray, labels: np.ndarray,
                objective: str, sigmoid: float = 1.0) -> float:
    """Lower-is-better loss of raw scores against labels: binary
    logloss, multiclass softmax logloss, or L2 (regression and the
    fallback for objectives without a per-row loss here)."""
    if scores.shape[0] != labels.shape[0]:
        raise CycleError("shadow eval: %d score rows for %d labels"
                         % (scores.shape[0], labels.shape[0]))
    if scores.shape[0] == 0:
        raise CycleError("shadow eval: empty eval set")
    eps = 1e-15
    if objective == "binary":
        p = 1.0 / (1.0 + np.exp(-sigmoid * scores[:, 0]))
        p = np.clip(p, eps, 1.0 - eps)
        y = (labels > 0).astype(np.float64)
        return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))
    if objective == "multiclass":
        z = scores - scores.max(axis=1, keepdims=True)
        ez = np.exp(z)
        p = ez / ez.sum(axis=1, keepdims=True)
        idx = labels.astype(np.int64)
        if (idx < 0).any() or (idx >= scores.shape[1]).any():
            raise CycleError("shadow eval: label outside [0, %d)"
                             % scores.shape[1])
        pt = np.clip(p[np.arange(len(idx)), idx], eps, 1.0)
        return float(-np.mean(np.log(pt)))
    return float(np.mean((scores[:, 0] - labels) ** 2))


# ---------------------------------------------------------------------------
# the agent
# ---------------------------------------------------------------------------

class RefreshAgent:
    """One continuous train->deploy loop against one serving fleet."""

    def __init__(self, cfg: Config):
        if not cfg.refresh_drop_dir:
            log.fatal("RefreshAgent needs refresh_drop_dir")
        if not cfg.refresh_serve_url:
            log.fatal("RefreshAgent needs refresh_serve_url")
        if not cfg.refresh_eval_data:
            log.fatal("RefreshAgent needs refresh_eval_data")
        if not cfg.input_model:
            log.fatal("RefreshAgent needs input_model (the starting "
                      "champion)")
        if cfg.faults:
            # deterministic fault injection: same arming rule as
            # cli.Application / api.Booster (config wins over env)
            from ..resilience.faults import configure
            configure(cfg.faults)
        self.cfg = cfg
        self.drop_dir = cfg.refresh_drop_dir
        self.work_dir = (cfg.refresh_work_dir
                         or os.path.join(cfg.refresh_drop_dir,
                                         ".refresh"))
        self.serve_url = cfg.refresh_serve_url.rstrip("/")
        self.deadline_s = float(cfg.refresh_deadline_s)
        self.min_gain = float(cfg.refresh_min_gain)
        self.rounds = int(cfg.refresh_rounds or cfg.num_iterations)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._data_event = threading.Event()
        self._pending: Dict[str, Tuple[int, int]] = {}
        self._watcher: Optional[threading.Thread] = None
        self._status_httpd: Optional[ThreadingHTTPServer] = None
        self._status_thread: Optional[threading.Thread] = None
        self._warned_loss_fallback = False
        # observable state (all mutated under _lock; the status server
        # thread renders it)
        self.outcomes: Dict[str, int] = {"promoted": 0, "rejected": 0,
                                         "failed": 0}
        self.consecutive_failures = 0
        self.breaker_open_until = 0.0
        self.last_losses: Optional[Tuple[float, float]] = None
        self.last_cycle_at = 0.0
        self.cycle = 0
        self.champion = cfg.input_model
        self.consumed: Dict[str, List[int]] = {}
        os.makedirs(self.work_dir, exist_ok=True)
        self._load_state()
        if not os.path.isfile(self.champion):
            log.fatal("champion model %s does not exist" % self.champion)

    # -- durable state --------------------------------------------------
    @property
    def _state_path(self) -> str:
        return os.path.join(self.work_dir, STATE_NAME)

    def _load_state(self) -> None:
        try:
            with open(self._state_path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return
        champ = doc.get("champion")
        if champ and os.path.isfile(champ):
            self.champion = str(champ)
        self.cycle = int(doc.get("cycle", 0))
        self.consumed = {str(k): [int(v[0]), int(v[1])]
                         for k, v in dict(doc.get("consumed",
                                                  {})).items()}
        for k in self.outcomes:
            self.outcomes[k] = int(doc.get("outcomes", {}).get(k, 0))

    def _save_state(self) -> None:
        with self._lock:
            doc = {"champion": self.champion, "cycle": self.cycle,
                   "consumed": self.consumed,
                   "outcomes": dict(self.outcomes)}
        atomic_write_bytes(self._state_path,
                           (json.dumps(doc, indent=1, sort_keys=True)
                            + "\n").encode("utf-8"), checksum=False)

    # -- HTTP plumbing --------------------------------------------------
    def _http(self, path: str, data: Optional[bytes] = None,
              ctype: str = "application/json",
              timeout: Optional[float] = None) -> Tuple[int, bytes]:
        req = urllib.request.Request(
            self.serve_url + path, data=data,
            headers={} if data is None else {"Content-Type": ctype})
        try:
            with urllib.request.urlopen(
                    req, timeout=timeout or self.deadline_s) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as ex:
            body = ex.read()
            if ex.code < 500:
                # a client fault (bad challenger, unknown model) will
                # not heal by retrying: fail the step immediately
                raise CycleError("%s -> HTTP %d: %s"
                                 % (path, ex.code,
                                    body.decode("utf-8", "replace")
                                    [:300])) from ex
            raise

    def _healthz(self) -> Dict[str, Any]:
        _, body = self._http("/healthz")
        doc = json.loads(body.decode("utf-8"))
        if not isinstance(doc, dict):
            raise CycleError("/healthz returned a non-object")
        return doc

    def wait_serving(self) -> None:
        """Block until the serving fleet answers /healthz (startup
        race: the agent and the fleet come up together)."""
        retry_with_backoff(self._healthz, "serving fleet /healthz",
                           deadline_s=self.deadline_s,
                           base_s=0.2, cap_s=2.0)

    # -- retrain --------------------------------------------------------
    def _forward_params(self) -> List[str]:
        cfg = self.cfg
        out = ["%s=%s" % (k, _fmt_param(getattr(cfg, k)))
               for k in FORWARD_KEYS]
        out.append("metric=%s" % ",".join(cfg.metric))
        if cfg.refresh_train_args:
            out.extend(cfg.refresh_train_args.split())
        return out

    def _train_argv(self, data_path: str, out_model: str) -> List[str]:
        return ([sys.executable, "-m", "lightgbm_tpu", "task=train",
                 "data=" + data_path, "input_model=" + self.champion,
                 "output_model=" + out_model,
                 "num_iterations=%d" % self.rounds,
                 "verbose=%d" % self.cfg.verbose]
                + self._forward_params())

    def _ingest_argv(self, data_path: str, shards_dir: str) -> List[str]:
        cfg = self.cfg
        return ([sys.executable, "-m", "lightgbm_tpu", "task=ingest",
                 "data=" + data_path, "ingest_dir=" + shards_dir,
                 "ingest_memory_budget_mb=%d"
                 % cfg.ingest_memory_budget_mb,
                 "ingest_shard_rows=%d" % cfg.ingest_shard_rows,
                 "ingest_workers=%d" % cfg.ingest_workers,
                 "verbose=%d" % cfg.verbose]
                + self._forward_params())

    def _run_subprocess(self, argv: List[str], what: str) -> None:
        proc = subprocess.run(argv, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT,
                              timeout=self.deadline_s)
        out = proc.stdout.decode("utf-8", "replace")
        if proc.returncode != 0:
            raise CycleError("%s exited %d:\n%s"
                             % (what, proc.returncode, _tail(out)))

    def _retrain(self, data_path: str, out_model: str) -> None:
        """Warm-start retrain subprocess (champion -> challenger),
        retried with backoff under the step deadline.  The spawn seam
        is `refresh.train_spawn`; an injected raise is a cycle failure
        (give_up_on), never absorbed by a retry."""
        if self.cfg.refresh_ingest:
            shards = os.path.join(self.work_dir,
                                  "cycle_%04d.shards" % self.cycle)
            retry_with_backoff(
                lambda: self._run_subprocess(
                    self._ingest_argv(data_path, shards),
                    "ingest subprocess"),
                "cycle %d ingest" % self.cycle,
                deadline_s=self.deadline_s, base_s=0.5, cap_s=4.0,
                give_up_on=(FaultInjected, CycleError))
            data_path = shards

        def attempt() -> None:
            faultpoint("refresh.train_spawn")
            self._run_subprocess(self._train_argv(data_path, out_model),
                                 "retrain subprocess")
            if not os.path.isfile(out_model):
                raise CycleError("retrain subprocess wrote no model "
                                 "at %s" % out_model)

        retry_with_backoff(attempt, "cycle %d retrain" % self.cycle,
                           deadline_s=self.deadline_s,
                           base_s=0.5, cap_s=4.0,
                           give_up_on=(FaultInjected, CycleError))

    # -- deploy (push / promote) ----------------------------------------
    def _model_live(self, doc: Dict[str, Any], sha: str,
                    as_default: bool) -> bool:
        if as_default:
            return bool(doc.get("model", {}).get("sha") == sha)
        return any(m.get("warm") and m.get("sha") == sha
                   for m in doc.get("models", ()))

    def _deploy(self, path: str, make_default: bool) -> None:
        """POST the model into the fleet and CONFIRM it landed on every
        worker.  SO_REUSEPORT routes each connection to one worker, so
        the push repeats (idempotent re-warm) until /healthz scrapes
        have confirmed the sha on all `worker.count` indexes — a
        single-process server confirms on the first scrape."""
        sha = _sha256_file(path)
        fp = "deploy.promote" if make_default else "deploy.push"
        body = json.dumps({"model": path,
                           "default": make_default}).encode("utf-8")
        curve = Backoff(base_s=0.2, cap_s=2.0)
        t0 = time.monotonic()
        confirmed: Set[int] = set()
        attempt = 0
        last: Optional[BaseException] = None
        while True:
            attempt += 1
            try:
                faultpoint(fp)
                self._http("/reload", data=body)
                doc = self._healthz()
                worker = doc.get("worker")
                live = self._model_live(doc, sha, make_default)
                if worker is None:
                    if live:
                        return
                else:
                    if live:
                        confirmed.add(int(worker["index"]))
                    if len(confirmed) >= int(worker.get("count", 1)):
                        return
                raise RuntimeError(
                    "confirmed on %s so far" % (sorted(confirmed),))
            except (FaultInjected, CycleError):
                raise
            except Exception as ex:
                last = ex
            delay = curve.delay(attempt)
            if time.monotonic() - t0 + delay > self.deadline_s:
                raise CycleError(
                    "%s of %s did not confirm on every worker within "
                    "%.1fs: %s" % (fp, path, self.deadline_s,
                                   last)) from last
            time.sleep(delay)

    # -- shadow eval -----------------------------------------------------
    def _mirror_predict(self, body: bytes,
                        model: Optional[str]) -> bytes:
        qs = "/predict?mode=raw"
        if model is not None:
            qs += "&model=" + urllib.parse.quote(model, safe="")
        status, out = retry_with_backoff(
            lambda: self._http(qs, data=body, ctype="text/plain"),
            "shadow predict (%s)" % (model or "champion"),
            deadline_s=self.deadline_s, base_s=0.2, cap_s=2.0,
            give_up_on=(FaultInjected, CycleError))
        return out

    def _shadow_eval(self, challenger: str) -> Tuple[float, float]:
        """Mirror the held-out rows to champion (default route) and
        challenger concurrently; return (champion_loss,
        challenger_loss).  The two requests ride the SAME bytes through
        the production /predict path (batcher included), on named
        daemon eval threads joined under the step deadline."""
        faultpoint("refresh.eval")
        with open(self.cfg.refresh_eval_data, "rb") as f:
            body = f.read()
        if self.cfg.has_header:
            from ..serving.server import _strip_first_line
            body = _strip_first_line(body)
        if not body.strip():
            raise CycleError("refresh_eval_data %s is empty"
                             % self.cfg.refresh_eval_data)
        with open(self.champion) as f:
            header, _ = parse_model_text(f.read())
        labels = parse_label_column(body, int(header["label_index"]))
        results: Dict[str, Tuple[str, Any]] = {}

        def fetch(tag: str, model: Optional[str]) -> None:
            try:
                results[tag] = ("ok", self._mirror_predict(body, model))
            except BaseException as ex:   # re-raised on the main thread
                results[tag] = ("err", ex)

        threads = [
            threading.Thread(target=fetch, args=("champion", None),
                             name="lgbm-refresh-eval-champion",
                             daemon=True),
            threading.Thread(target=fetch, args=("challenger",
                                                 challenger),
                             name="lgbm-refresh-eval-challenger",
                             daemon=True)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + self.deadline_s + 5.0
        for t in threads:
            t.join(max(0.1, deadline - time.monotonic()))
            if t.is_alive():
                raise CycleError("shadow eval thread %s missed the "
                                 "deadline" % t.name)
        losses = {}
        objective = self.cfg.objective
        if objective not in EVAL_LOSSES \
                and not self._warned_loss_fallback:
            self._warned_loss_fallback = True
            log.warning("shadow eval: objective %s has no per-row "
                        "loss here; scoring raw scores with L2"
                        % objective)
        for tag in ("champion", "challenger"):
            kind, val = results[tag]
            if kind == "err":
                raise CycleError("shadow eval (%s) failed: %s"
                                 % (tag, val)) from val
            losses[tag] = shadow_loss(parse_score_rows(val), labels,
                                      objective,
                                      sigmoid=self.cfg.sigmoid)
        return losses["champion"], losses["challenger"]

    # -- one cycle -------------------------------------------------------
    def _stage_cycle_data(self, sources: Dict[str, Tuple[int, int]]
                          ) -> str:
        """Concatenate this cycle's stable drop files (sorted for
        determinism) into one atomically-written training file."""
        out = os.path.join(self.work_dir,
                           "cycle_%04d.data" % self.cycle)
        with atomic_writer(out) as f:
            for path in sorted(sources):
                with open(path, "rb") as src:
                    payload = src.read()
                f.write(payload)
                if payload and not payload.endswith(b"\n"):
                    f.write(b"\n")
        return out

    def run_cycle(self, sources: Dict[str, Tuple[int, int]]) -> str:
        """One refresh cycle over `sources` (a stable snapshot_sources
        slice).  Returns the outcome: promoted | rejected | failed.
        Failure leaves the fleet untouched (champion serving) and the
        sources unconsumed; the next cycle retries them."""
        t0 = time.monotonic()
        challenger = os.path.join(self.work_dir,
                                  "challenger_%04d.txt" % self.cycle)
        try:
            data_path = self._stage_cycle_data(sources)
            self._retrain(data_path, challenger)
            self._deploy(challenger, make_default=False)
            champ_loss, chall_loss = self._shadow_eval(challenger)
            win = chall_loss < champ_loss - self.min_gain
            with self._lock:
                self.last_losses = (champ_loss, chall_loss)
            if win:
                self._deploy(challenger, make_default=True)
                outcome = "promoted"
            else:
                # demotion: the challenger stays registered non-default
                # (shadow-only); it was NEVER the default
                outcome = "rejected"
            log.info("refresh cycle %d: %s (champion %.6g vs "
                     "challenger %.6g, min_gain %g) in %.1fs"
                     % (self.cycle, outcome, champ_loss, chall_loss,
                        self.min_gain, time.monotonic() - t0))
        except Exception as ex:
            with self._lock:
                self.outcomes["failed"] += 1
                self.consecutive_failures += 1
                failures = self.consecutive_failures
                if failures >= self.cfg.refresh_breaker_threshold:
                    self.breaker_open_until = (
                        time.monotonic() + self.cfg.refresh_cooldown_s)
            log.warning("refresh cycle %d FAILED (%s: %s) — champion "
                        "keeps serving%s"
                        % (self.cycle, type(ex).__name__, ex,
                           "; breaker OPEN for %gs"
                           % self.cfg.refresh_cooldown_s
                           if failures
                           >= self.cfg.refresh_breaker_threshold
                           else ""))
            self._save_state()
            return "failed"
        with self._lock:
            self.outcomes[outcome] += 1
            self.consecutive_failures = 0
            self.breaker_open_until = 0.0
            if outcome == "promoted":
                self.champion = challenger
            self.consumed.update(
                {p: [st[0], st[1]] for p, st in sources.items()})
            self.cycle += 1
        self._save_state()
        return outcome

    # -- breaker / scheduling -------------------------------------------
    def breaker_open(self) -> bool:
        with self._lock:
            return time.monotonic() < self.breaker_open_until

    def _take_pending(self) -> Dict[str, Tuple[int, int]]:
        with self._lock:
            pend = dict(self._pending)
            self._pending.clear()
            self._data_event.clear()
        return pend

    # -- watcher thread --------------------------------------------------
    def _watch_loop(self) -> None:
        prev: Dict[str, Tuple[int, int]] = {}
        while not self._stop.wait(self.cfg.refresh_poll_s):
            cur = snapshot_sources(self.drop_dir)
            with self._lock:
                consumed = dict(self.consumed)
                fresh = {
                    p: st for p, st in cur.items()
                    if prev.get(p) == st
                    and consumed.get(p) != [st[0], st[1]]}
                if fresh:
                    self._pending.update(fresh)
                    self._data_event.set()
            prev = cur

    # -- status endpoint -------------------------------------------------
    def _status_doc(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "status": ("cooling" if time.monotonic()
                           < self.breaker_open_until else "ok"),
                "champion": self.champion,
                "cycle": self.cycle,
                "outcomes": dict(self.outcomes),
                "consecutive_failures": self.consecutive_failures,
                "pending": len(self._pending),
                "last_losses": (list(self.last_losses)
                                if self.last_losses else None),
            }

    def render_metrics(self) -> bytes:
        """Prometheus text: the refresh observability the /metrics
        satellite asks for (cycle outcomes, breaker, shadow deltas)."""
        out: List[str] = []
        with self._lock:
            outcomes = dict(self.outcomes)
            failures = self.consecutive_failures
            open_ = time.monotonic() < self.breaker_open_until
            losses = self.last_losses
            champion = self.champion
        out.append("# HELP lgbm_refresh_cycles_total refresh cycles "
                   "by outcome")
        out.append("# TYPE lgbm_refresh_cycles_total counter")
        for k in ("promoted", "rejected", "failed"):
            out.append('lgbm_refresh_cycles_total{outcome="%s"} %d'
                       % (k, outcomes[k]))
        out.append("# HELP lgbm_refresh_breaker_open 1 while the "
                   "agent's circuit breaker is cooling down")
        out.append("# TYPE lgbm_refresh_breaker_open gauge")
        out.append("lgbm_refresh_breaker_open %d" % int(open_))
        out.append("# HELP lgbm_refresh_consecutive_failures failed "
                   "cycles since the last success")
        out.append("# TYPE lgbm_refresh_consecutive_failures gauge")
        out.append("lgbm_refresh_consecutive_failures %d" % failures)
        if losses is not None:
            out.append("# HELP lgbm_refresh_shadow_loss last "
                       "shadow-eval loss per contender")
            out.append("# TYPE lgbm_refresh_shadow_loss gauge")
            out.append('lgbm_refresh_shadow_loss{model="champion"} '
                       "%.17g" % losses[0])
            out.append('lgbm_refresh_shadow_loss{model="challenger"} '
                       "%.17g" % losses[1])
            out.append("# HELP lgbm_refresh_shadow_delta champion "
                       "minus challenger shadow-eval loss (positive = "
                       "challenger better)")
            out.append("# TYPE lgbm_refresh_shadow_delta gauge")
            out.append("lgbm_refresh_shadow_delta %.17g"
                       % (losses[0] - losses[1]))
        out.append("# HELP lgbm_refresh_champion the currently "
                   "promoted model")
        out.append("# TYPE lgbm_refresh_champion gauge")
        out.append('lgbm_refresh_champion{path="%s",sha="%s"} 1'
                   % (champion, _sha256_file_cached(champion)[:12]))
        return ("\n".join(out) + "\n").encode("utf-8")

    def _start_status_server(self) -> None:
        if self.cfg.refresh_status_port < 0:
            return
        agent = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt: str, *args: Any) -> None:
                log.debug("refresh status: " + fmt % args)

            def do_GET(self) -> None:
                path = urllib.parse.urlparse(self.path).path
                if path == "/metrics":
                    body = agent.render_metrics()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/healthz":
                    body = (json.dumps(agent._status_doc())
                            + "\n").encode("utf-8")
                    ctype = "application/json"
                else:
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._status_httpd = ThreadingHTTPServer(
            ("127.0.0.1", max(0, self.cfg.refresh_status_port)),
            Handler)
        self._status_httpd.daemon_threads = True
        self._status_thread = threading.Thread(
            target=self._status_httpd.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="lgbm-refresh-status", daemon=True)
        self._status_thread.start()
        log.info("refresh agent status on http://127.0.0.1:%d"
                 % self._status_httpd.server_address[1])

    @property
    def status_url(self) -> Optional[str]:
        if self._status_httpd is None:
            return None
        return ("http://127.0.0.1:%d"
                % self._status_httpd.server_address[1])

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        """Start the watcher + status threads (named daemons; joined
        on the SIGTERM drain)."""
        self.wait_serving()
        self._start_status_server()
        self._watcher = threading.Thread(target=self._watch_loop,
                                         name="lgbm-refresh-watch",
                                         daemon=True)
        self._watcher.start()
        log.info("refresh agent watching %s -> fleet %s (champion %s)"
                 % (self.drop_dir, self.serve_url, self.champion))

    def shutdown(self) -> None:
        """Drain: stop the loop, join the watcher, stop the status
        server — every named agent thread exits."""
        self._stop.set()
        if self._watcher is not None:
            self._watcher.join(10.0)
            self._watcher = None
        if self._status_httpd is not None:
            self._status_httpd.shutdown()
            self._status_httpd.server_close()
            self._status_httpd = None
        if self._status_thread is not None:
            self._status_thread.join(10.0)
            self._status_thread = None

    def run_forever(self) -> None:
        """Supervise cycles until SIGTERM/SIGINT (or
        refresh_max_cycles attempts — smokes/tests)."""
        stop_sig = threading.Event()

        def _on_signal(signum: int, frame: Any) -> None:
            log.info("Signal %d: draining refresh agent..." % signum)
            stop_sig.set()
            self._stop.set()

        prev: Dict[int, Any] = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            try:
                prev[sig] = signal.signal(sig, _on_signal)
            except ValueError:       # not on the main thread
                pass
        attempts = 0
        try:
            while not stop_sig.is_set():
                self._data_event.wait(timeout=0.2)
                if stop_sig.is_set():
                    break
                if not self._pending:
                    continue
                if self.breaker_open():
                    time.sleep(min(0.5, self.cfg.refresh_cooldown_s))
                    continue
                since = time.monotonic() - self.last_cycle_at
                if self.last_cycle_at \
                        and since < self.cfg.refresh_period_s:
                    time.sleep(min(0.5,
                                   self.cfg.refresh_period_s - since))
                    continue
                pending = self._take_pending()
                if not pending:
                    continue
                self.last_cycle_at = time.monotonic()
                self.run_cycle(pending)
                attempts += 1
                if self.cfg.refresh_max_cycles \
                        and attempts >= self.cfg.refresh_max_cycles:
                    log.info("refresh_max_cycles=%d reached, exiting"
                             % self.cfg.refresh_max_cycles)
                    break
        finally:
            for sig, h in prev.items():
                signal.signal(sig, h)
            self.shutdown()
            log.info("Refresh agent drained, exiting")


def run_refresh_cli(cfg: Config) -> None:
    """CLI entry (task=refresh)."""
    agent = RefreshAgent(cfg)
    agent.start()
    agent.run_forever()
