"""Evaluation metrics.

Formula-parity ports of src/metric/* (reference), vectorized numpy:
  - l1/l2 (l2 reports RMSE via the AverageLoss sqrt): regression_metric.hpp
  - binary_logloss / binary_error (sigmoid inside Eval): binary_metric.hpp:18-143
  - auc (weighted trapezoid with tie groups): binary_metric.hpp:148-256
  - ndcg@k (all-negative queries count as 1): rank_metric.hpp + dcg_calculator.cpp
  - multi_logloss / multi_error: multiclass_metric.hpp

Metric display names (including the reference's quirky "name's : metric"
prefix and NDCG trailing space) are reproduced so training logs diff
cleanly against the reference CLI.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from . import native
from .config import Config
from .io.dataset import Metadata
from .objectives import check_rank_label, default_label_gain, max_dcg_at_k
from .utils import log

K_EPSILON = 1e-15


class Metric:
    factor_to_bigger_better = -1.0

    # multi-host reduction hooks (parallel/dist.make_metric_reducer):
    # _reduce_sum allreduces partial-sum vectors across ranks so metrics
    # over rank-sharded data report GLOBAL values on every rank (the
    # reference evaluates machine-locally; a gap VERDICT r1 flagged);
    # _concat gathers raw per-rank columns for order-sensitive metrics
    reduce_sum = None
    concat = None

    def set_reducer(self, reduce_sum, concat) -> None:
        self.reduce_sum = reduce_sum
        self.concat = concat

    def _reduce(self, *parts: float) -> List[float]:
        v = np.asarray(parts, dtype=np.float64)
        if self.reduce_sum is not None:
            v = self.reduce_sum(v)
        return [float(x) for x in v]

    def init(self, test_name: str, metadata: Metadata, num_data: int) -> None:
        self.metadata = metadata
        self.num_data = num_data
        self.weights = metadata.weights
        self.sum_weights = (float(num_data) if self.weights is None
                            else float(self.weights.sum()))
        self.names: List[str] = []

    def eval(self, score: np.ndarray) -> List[float]:
        raise NotImplementedError


class _RegressionMetric(Metric):
    display = ""

    def init(self, test_name, metadata, num_data):
        super().init(test_name, metadata, num_data)
        # regression names have no "'s" (reference regression_metric.hpp:28)
        self.names = ["%s : %s" % (test_name, self.display)]

    def loss_on_point(self, label, score):
        raise NotImplementedError

    def average_loss(self, sum_loss, sum_weights):
        return sum_loss / sum_weights

    def eval(self, score):
        label = self.metadata.label.astype(np.float64)
        loss = self.loss_on_point(label, score.astype(np.float64))
        if self.weights is not None:
            loss = loss * self.weights
        s, w = self._reduce(float(loss.sum()), self.sum_weights)
        return [self.average_loss(s, w)]


class L2Metric(_RegressionMetric):
    display = "l2 loss"

    def loss_on_point(self, label, score):
        return (score - label) ** 2

    def average_loss(self, sum_loss, sum_weights):
        return float(np.sqrt(sum_loss / sum_weights))


class L1Metric(_RegressionMetric):
    display = "l1 loss"

    def loss_on_point(self, label, score):
        return np.abs(score - label)


class _BinaryMetric(Metric):
    display = ""

    def __init__(self, config: Config):
        self.sigmoid = float(config.sigmoid)

    def init(self, test_name, metadata, num_data):
        super().init(test_name, metadata, num_data)
        self.names = ["%s's : %s" % (test_name, self.display)]

    def loss_on_point(self, label, prob):
        raise NotImplementedError

    def eval(self, score):
        prob = 1.0 / (1.0 + np.exp(-2.0 * self.sigmoid
                                   * score.astype(np.float64)))
        loss = self.loss_on_point(self.metadata.label.astype(np.float64), prob)
        if self.weights is not None:
            loss = loss * self.weights
        s, w = self._reduce(float(loss.sum()), self.sum_weights)
        return [s / w]


class BinaryLoglossMetric(_BinaryMetric):
    display = "log loss"

    def loss_on_point(self, label, prob):
        p = np.where(label == 0, 1.0 - prob, prob)
        return -np.log(np.maximum(p, K_EPSILON))


class BinaryErrorMetric(_BinaryMetric):
    display = "error rate"

    def loss_on_point(self, label, prob):
        return np.where(prob < 0.5, label, 1.0 - label)


class AUCMetric(Metric):
    factor_to_bigger_better = 1.0

    def __init__(self, config: Config):
        pass

    def init(self, test_name, metadata, num_data):
        super().init(test_name, metadata, num_data)
        self.names = ["%s's : AUC" % test_name]

    def eval(self, score):
        """Weighted trapezoid with score-tie groups
        (reference binary_metric.hpp:185-248)."""
        s = score.astype(np.float64)
        label = self.metadata.label.astype(np.float64)
        w = (np.ones_like(label) if self.weights is None
             else self.weights.astype(np.float64))
        sum_w = self.sum_weights
        if self.concat is not None:
            # AUC needs the global score ordering: gather the per-rank
            # (score, label, weight) columns and rank a global AUC —
            # unlike the sum-decomposable losses, partial AUCs don't add
            cols = self.concat(np.stack([s, label, w], axis=1))
            s, label, w = cols[:, 0], cols[:, 1], cols[:, 2]
            # the gathered weight column already carries the global sum
            # (and sums in the same order sum_pos accumulates, so the
            # all-positive == test below stays exact)
            sum_w = float(w.sum())
        order = np.argsort(-s, kind="stable")
        s, label, w = s[order], label[order], w[order]
        pos = label * w
        neg = (1.0 - label) * w
        # group by equal scores
        boundary = np.concatenate([[True], s[1:] != s[:-1]])
        group = np.cumsum(boundary) - 1
        ngroups = group[-1] + 1
        gpos = np.bincount(group, weights=pos, minlength=ngroups)
        gneg = np.bincount(group, weights=neg, minlength=ngroups)
        cum_pos_before = np.concatenate([[0.0], np.cumsum(gpos)[:-1]])
        accum = float((gneg * (gpos * 0.5 + cum_pos_before)).sum())
        sum_pos = float(gpos.sum())
        if sum_pos > 0 and sum_pos != sum_w:
            return [accum / (sum_pos * (sum_w - sum_pos))]
        return [1.0]


class _MulticlassMetric(Metric):
    display = ""

    def __init__(self, config: Config):
        self.num_class = config.num_class

    def init(self, test_name, metadata, num_data):
        super().init(test_name, metadata, num_data)
        # multiclass names have no "'s" (reference multiclass_metric.hpp:28)
        self.names = ["%s : %s" % (test_name, self.display)]

    def loss_on_point(self, label_int, prob):
        raise NotImplementedError

    def eval(self, score):
        """score [K, N]."""
        sc = score.astype(np.float64)
        e = np.exp(sc - sc.max(axis=0, keepdims=True))
        prob = e / e.sum(axis=0, keepdims=True)              # [K, N]
        li = self.metadata.label.astype(np.int64)
        loss = self.loss_on_point(li, prob)
        if self.weights is not None:
            loss = loss * self.weights
        s, w = self._reduce(float(loss.sum()), self.sum_weights)
        return [s / w]


class MultiLoglossMetric(_MulticlassMetric):
    display = "multi logloss"

    def loss_on_point(self, label_int, prob):
        p = prob[label_int, np.arange(prob.shape[1])]
        return -np.log(np.maximum(p, K_EPSILON))


class MultiErrorMetric(_MulticlassMetric):
    display = "multi error"

    def loss_on_point(self, label_int, prob):
        pred = prob.argmax(axis=0)
        return (pred != label_int).astype(np.float64)


class NDCGMetric(Metric):
    factor_to_bigger_better = 1.0

    def __init__(self, config: Config):
        self.eval_at = sorted(config.ndcg_eval_at or [1, 2, 3, 4, 5])
        self.label_gain = np.asarray(config.label_gain or default_label_gain(),
                                     dtype=np.float64)
        self.discount = 1.0 / np.log2(2.0 + np.arange(10000))

    def init(self, test_name, metadata, num_data):
        super().init(test_name, metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("The NDCG metric requires query information")
        self.qb = metadata.query_boundaries
        check_rank_label(metadata.label, len(self.label_gain))
        self.names = ["%s's : NDCG@%d " % (test_name, k) for k in self.eval_at]
        nq = len(self.qb) - 1
        self._inv_max = None   # per-(query, k) cache, fallback path only
        qw = metadata.query_weights
        self.query_weights = qw
        self.sum_query_weights = (float(nq) if qw is None else float(qw.sum()))

    def eval(self, score):
        # Native path: per-query top-k membership under tied scores follows
        # std::sort's permutation and fp32 accumulation (rank_metric.hpp:89-
        # 145) — required for golden-log digit parity; see native/.
        res = native.ndcg_eval(np.asarray(score, dtype=np.float32),
                               self.metadata.label, self.qb, self.eval_at,
                               self.label_gain, self.query_weights)
        if res is not None:
            # NDCG sums decompose per query, so rank-sharded (query-
            # granular) valid data reduces exactly
            parts = self._reduce(*list(res), self.sum_query_weights)
            return [v / parts[-1] for v in parts[:-1]]
        s = np.asarray(score).astype(np.float64)
        nq = len(self.qb) - 1
        if self._inv_max is None:
            # built only here: the native path recomputes it in C++ and
            # this python double loop is expensive at many-query scale
            self._inv_max = np.zeros((nq, len(self.eval_at)))
            for q in range(nq):
                lab = self.metadata.label[self.qb[q]:self.qb[q + 1]]
                for j, k in enumerate(self.eval_at):
                    m = max_dcg_at_k(k, lab, self.label_gain, self.discount)
                    self._inv_max[q, j] = 1.0 / m if m > 0 else -1.0
        inv_max = self._inv_max
        result = np.zeros(len(self.eval_at))
        for q in range(nq):
            a, b = int(self.qb[q]), int(self.qb[q + 1])
            w = 1.0 if self.query_weights is None else float(self.query_weights[q])
            lab = self.metadata.label[a:b].astype(np.int64)
            order = np.argsort(-s[a:b], kind="stable")
            gains = self.label_gain[lab[order]]
            for j, k in enumerate(self.eval_at):
                if inv_max[q, 0] <= 0:
                    # all-negative query counts as perfect, UNWEIGHTED even
                    # under query weights — reference quirk reproduced by
                    # the native path too (rank_metric.hpp:99,120-123)
                    result[j] += 1.0
                else:
                    kk = min(k, b - a)
                    dcg = float((gains[:kk] * self.discount[:kk]).sum())
                    result[j] += dcg * inv_max[q, j] * w
        parts = self._reduce(*list(result), self.sum_query_weights)
        return [v / parts[-1] for v in parts[:-1]]


def create_metric(name: str, config: Config) -> Optional[Metric]:
    if name in ("l2", "mse", "regression"):
        return L2Metric()
    if name in ("l1", "mae"):
        return L1Metric()
    if name == "binary_logloss":
        return BinaryLoglossMetric(config)
    if name == "binary_error":
        return BinaryErrorMetric(config)
    if name == "auc":
        return AUCMetric(config)
    if name == "ndcg":
        return NDCGMetric(config)
    if name == "multi_logloss":
        return MultiLoglossMetric(config)
    if name == "multi_error":
        return MultiErrorMetric(config)
    if name in ("", "none", "null"):
        return None
    log.fatal("Unknown metric type %s" % name)


def create_metrics(config: Config) -> List[Metric]:
    out = []
    for name in config.metric:
        m = create_metric(name, config)
        if m is not None:
            out.append(m)
    return out
