"""Pallas TPU histogram kernel — the fast path for the #1 hot loop.

The XLA formulation (ops/histogram.py) materializes per-feature one-hot
matrices in HBM (~N*B bytes per feature per split), which dominates at
scale.  This kernel uses a radix decomposition bin = hi*32 + lo and packs
MM_FEATS=4 features into ONE block-diagonal MXU matmul (a grid step
covers _feat_block(F) <= MAX_FEAT_BLOCK features, several matmuls):

    lhs[(f, c, hi), r] = gh3[c, r] * (bins_hi[f, r] == hi)   [96, blk]
    rhs[r, (f, lo)]    = (bins_lo[f, r] == lo)               [blk, 128]
    part = lhs @ rhs                                         [96, 128]

so hist[f, hi*32+lo, c] is the f-diagonal of the [4x4 blocks] product.
The off-diagonal (f != f') blocks are wasted FLOPs, but the [96,128]x[blk]
shape keeps the MXU at near-full tile utilization — ~5x faster end-to-end
than one [32, blk] x [blk, 32] matmul per feature, whose 32-wide tiles run
the MXU at 1/16 of peak.

Inputs are kept slim because HBM streaming dominates: bins [F, N] uint8,
gh2 [2, N] (grad, hess; built once per tree), and ONE leaf_eff [N]
int32 with the bagging mask pre-folded (out-of-bag rows get -1, which can
never equal a target leaf).  The (leaf_eff == target) mask is computed
in-kernel, so per-split traffic is bins + gh2 + leaf_eff only — no [N]
per-split gvals materialization.

Accumulator modes (`hist_acc`, round 16): "f32" is the default and the
parity configuration; "bf16" streams gh2 and builds the one-hot operands
in bfloat16 (halving their VMEM footprint and the gh2 HBM stream) with
f32 MXU accumulation; "i32" quantizes gh2 to int32 fixed point with a
per-tree scale bounded so no sum of N terms can overflow (make_gh2_acc),
accumulates EXACTLY in integers (order-independent), and dequantizes on
output — counts come out exact.  bf16/i32 round the inputs, so they are
opt-in behind the f32 parity gate (config.hist_acc; tests pin their
divergence envelopes).

Fused histogram+gain kernels (round 16): the *_fused variants extend the
masked / ranged / blocklist sweeps so the LAST grid step, with the
feature block's accumulators still resident in VMEM, also runs the
best-split threshold scan in-register — the exact jnp ops of
`ops/split.per_feature_split_rows`, on the exact accumulator values the
two-op path would extract — for the swept (small) child AND its sibling
(parent - small, the subtraction trick: the parent streams in once) and
emits one [F, 8] best row per child.  A tiny XLA argmax
(`ops/split.find_best_split_fused`) finishes the reduction, so the
[F, B, 3] tensor is written once for the histogram-pool state and never
read back for scanning: the ~2 full-tensor scan passes per split that
dominated the two-op path's non-sweep time disappear.  Interpret-mode
results are bit-identical to the two-op oracle by construction (same
ops, same values, same order).

Equivalent to DenseBin::ConstructHistogram (reference
src/io/dense_bin.hpp:39-104) with the leaf/bag mask folded into the
accumulated values, and — fused — to the reference's
ConstructHistograms -> FindBestThreshold pass that never leaves the
feature-histogram buffer (SURVEY §7.3).  Supports max_bin <= 256.
"""

from __future__ import annotations

import functools

from ..utils.compile_cache import enable_compilation_cache

enable_compilation_cache()   # before any jit traces (was a package-import side effect)

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .split import PF_COLS, SplitParams, per_feature_split_rows

MAX_FEAT_BLOCK = 16   # features per grid step (gh2/leaf_eff stream from
                      # HBM once per row block per GRID STEP, so wide
                      # feature blocks amortize that traffic; sublane
                      # tiling wants a multiple of 8)
MM_FEATS = 4      # features per block-diagonal matmul
N_HI = 8
N_LO = 32
N_COMP = 3    # grad, hess, count
M_ROWS = MM_FEATS * N_COMP * N_HI   # 96
N_COLS = MM_FEATS * N_LO            # 128
PALLAS_ROW_BLOCK = 8192   # rows per grid step; N must be a multiple —
#                           this is also the alignment of the
#                           bag-compacted sweep window (models/gbdt.py
#                           pads the static in-bag window to it), so the
#                           kernels never see a partial block

HIST_ACC_MODES = ("f32", "bf16", "i32")

# SMEM scalar layouts of the fused kernels: info (int32[8]) and
# stats (float32[8])
IF_TARGET, IF_START, IF_ACTIVE, IF_CNT_S, IF_CNT_L = range(5)
SF_SG_S, SF_SH_S, SF_SG_L, SF_SH_L, SF_INV = range(5)


def _feat_block(f: int) -> int:
    return min(MAX_FEAT_BLOCK, ((f + 7) // 8) * 8)


def _operand_dtype(hist_acc: str):
    """dtype of the in-kernel one-hot/gh operands per accumulator mode."""
    if hist_acc == "bf16":
        return jnp.bfloat16
    if hist_acc == "i32":
        return jnp.int32
    return jnp.float32


def _acc_dtype(hist_acc: str):
    """dtype the MXU partials accumulate in (the out buffer)."""
    return jnp.int32 if hist_acc == "i32" else jnp.float32


def make_gh2(grad: jax.Array, hess: jax.Array) -> jax.Array:
    """[2, N] f32 (grad, hess) — per-tree constant rows."""
    return jnp.stack([grad.astype(jnp.float32), hess.astype(jnp.float32)])


def make_gh2_acc(grad: jax.Array, hess: jax.Array, hist_acc: str = "f32"):
    """(gh2 [2, N], inv_scale) in the accumulator mode's streaming dtype.

    f32: the parity default (inv_scale None).  bf16: rounded to
    bfloat16 — half the gh2 stream and operand VMEM.  i32: fixed-point
    quantization with a per-tree scale chosen so |q| <= 2**30 / N —
    ANY sum of N quantized terms stays inside int32, so integer
    accumulation can never overflow regardless of block/grid
    association; inv_scale (traced f32) dequantizes the grad/hess
    components on output (counts are exact integers already).
    """
    if hist_acc == "bf16":
        return make_gh2(grad, hess).astype(jnp.bfloat16), None
    if hist_acc == "i32":
        gh2 = make_gh2(grad, hess)
        n = max(int(grad.shape[0]), 1)
        cap = jnp.float32((2.0 ** 30) / n)
        m = jnp.maximum(jnp.max(jnp.abs(gh2)), jnp.float32(1e-30))
        scale = cap / m
        q = jnp.round(gh2 * scale).astype(jnp.int32)
        return q, (jnp.float32(1.0) / scale).astype(jnp.float32)
    return make_gh2(grad, hess), None


def dequant_hist(hist: jax.Array, hist_acc: str, inv_scale) -> jax.Array:
    """[..., 3]-component histogram -> f32, dequantizing the grad/hess
    components in i32 mode (counts carry scale 1 and come out exact)."""
    if hist_acc != "i32":
        return hist
    vec = jnp.stack([inv_scale, inv_scale, jnp.float32(1.0)])
    return hist.astype(jnp.float32) * vec


def fold_leaf_mask(leaf_id: jax.Array, mask: jax.Array) -> jax.Array:
    """leaf_eff [N] i32: leaf_id where mask, else -1 (never a target)."""
    return jnp.where(mask, leaf_id.astype(jnp.int32), jnp.int32(-1))


def _accumulate(target, bins_ref, gh_ref, leaf_ref, out_ref, r, active,
                hist_acc):
    """The shared radix matmul accumulation of every kernel variant:
    r == 0 initializes the block accumulators, later ACTIVE steps add.
    Inactive steps (ranged/blocklist grids past n_active) skip their
    matmuls — their cost is grid bookkeeping only."""
    feat_block, blk = bins_ref.shape
    odt = _operand_dtype(hist_acc)
    adt = _acc_dtype(hist_acc)

    def emit(init):
        mask = (leaf_ref[:] == target).astype(odt)
        gh3 = jnp.stack([gh_ref[0, :] * mask, gh_ref[1, :] * mask, mask])
        bins = bins_ref[...].astype(jnp.int32)                 # [fb, blk]
        hi = bins >> 5
        lo = bins & 31
        iota_hi = jax.lax.broadcasted_iota(jnp.int32, (N_HI, blk), 0)
        iota_lo = jax.lax.broadcasted_iota(jnp.int32, (N_LO, blk), 0)
        for m in range(feat_block // MM_FEATS):
            lhs_parts = []
            rhs_parts = []
            for f in range(m * MM_FEATS, (m + 1) * MM_FEATS):
                ohi = (hi[f][None, :] == iota_hi).astype(odt)  # [8, blk]
                lhs_parts.append((gh3[:, None, :] * ohi[None, :, :])
                                 .reshape(N_COMP * N_HI, blk))
                rhs_parts.append((lo[f][None, :] == iota_lo)
                                 .astype(odt))                 # [32, blk]
            lhs = jnp.concatenate(lhs_parts, axis=0)           # [96, blk]
            # rhs stays lane-major [128, blk]: contracting BOTH operands
            # on the row (lane) dim avoids the [blk, 32] one-hot
            # transpose relayout
            rhs = jnp.concatenate(rhs_parts, axis=0)           # [128, blk]
            part = jax.lax.dot_general(
                lhs, rhs, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=adt)                    # [96, 128]
            if init:
                out_ref[0, m, :, :] = part
            else:
                out_ref[0, m, :, :] += part

    @pl.when(r == 0)
    def _init():
        emit(True)

    @pl.when((r != 0) & active)
    def _acc():
        emit(False)


def _diag_hist_xla(out: jax.Array, fpad: int, hist_acc: str, inv_scale):
    """[groups, fb//4, 96, 128] accumulators -> [fpad, 256, 3] f32: the
    feature f == f' diagonal of the 4x4 block structure, dequantized."""
    part = out.reshape(-1, MM_FEATS, N_COMP, N_HI, MM_FEATS, N_LO)
    diag = jnp.einsum("gfchfl->gfchl", part)
    hist = diag.transpose(0, 1, 3, 4, 2).reshape(fpad, N_HI * N_LO,
                                                 N_COMP)
    return dequant_hist(hist, hist_acc, inv_scale)


def _hist_kernel(hist_acc, target_ref, bins_ref, gh_ref, leaf_ref,
                 out_ref):
    r = pl.program_id(1)
    _accumulate(target_ref[0], bins_ref, gh_ref, leaf_ref, out_ref, r,
                True, hist_acc)


@functools.partial(jax.jit,
                   static_argnames=("max_bin", "hist_acc", "row_block",
                                    "interpret"))
def leaf_histogram_masked(bins_t: jax.Array, gh2: jax.Array,
                          leaf_eff: jax.Array, target_leaf,
                          inv_scale=None, *, max_bin: int,
                          hist_acc: str = "f32",
                          row_block: int = PALLAS_ROW_BLOCK,
                          interpret: bool = False) -> jax.Array:
    """Histogram over rows with leaf_eff == target_leaf.

    bins_t [F, N] uint8; gh2 [2, N] in the hist_acc streaming dtype
    (see make_gh2_acc) — built ONCE per tree; leaf_eff [N] i32 with
    bagging folded in (see fold_leaf_mask).
    Returns hist [F, max_bin, 3] f32 with components (grad, hess, count).
    """
    f, n = bins_t.shape
    assert n % row_block == 0, (n, row_block)
    assert max_bin <= N_HI * N_LO, max_bin
    fb = _feat_block(f)
    fpad = ((f + fb - 1) // fb) * fb
    if fpad != f:
        bins_t = jnp.pad(bins_t, ((0, fpad - f), (0, 0)))
    groups = fpad // fb
    nblocks = n // row_block
    target = jnp.asarray(target_leaf, dtype=jnp.int32).reshape(1)

    out = pl.pallas_call(
        functools.partial(_hist_kernel, hist_acc),
        grid=(groups, nblocks),   # row dim minor: out block stays in VMEM
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((fb, row_block), lambda i, r: (i, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, row_block), lambda i, r: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_block,), lambda i, r: (r,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, fb // MM_FEATS, M_ROWS, N_COLS),
                               lambda i, r: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (groups, fb // MM_FEATS, M_ROWS, N_COLS),
            _acc_dtype(hist_acc)),
        interpret=interpret,
    )(target, bins_t, gh2, leaf_eff)
    # rows are (f, c, hi), cols are (f', lo); feature f's histogram is the
    # f == f' diagonal of the 4x4 block structure
    hist = _diag_hist_xla(out, fpad, hist_acc, inv_scale)
    return hist[:f, :max_bin, :]


def _hist_body(hist_acc, info_ref, bins_ref, gh_ref, leaf_ref, out_ref):
    """Shared body of the ranged/blocklist kernels: info = [target, _,
    n_active] (SMEM).

    The grid's row dimension is the static worst case; steps past
    n_active revisit the last active block (index maps clamp), so the
    pipeline skips their DMA, and pl.when skips their matmuls — the cost
    of an inactive step is grid bookkeeping only.  This is what makes
    sweep time proportional to the leaf's block count instead of N.
    """
    r = pl.program_id(1)
    _accumulate(info_ref[0], bins_ref, gh_ref, leaf_ref, out_ref, r,
                r < info_ref[2], hist_acc)


def _hist_kernel_ranged(hist_acc, info_ref, bins_ref, gh_ref, leaf_ref,
                        out_ref):
    _hist_body(hist_acc, info_ref, bins_ref, gh_ref, leaf_ref, out_ref)


def _hist_kernel_blocklist(hist_acc, info_ref, blist_ref, bins_ref,
                           gh_ref, leaf_ref, out_ref):
    # blist_ref is consumed by the index maps; the body only needs info
    _hist_body(hist_acc, info_ref, bins_ref, gh_ref, leaf_ref, out_ref)


@functools.partial(jax.jit,
                   static_argnames=("max_bin", "hist_acc", "row_block",
                                    "interpret"))
def leaf_histogram_ranged(bins_t: jax.Array, gh2: jax.Array,
                          leaf_eff: jax.Array, target_leaf, start_block,
                          n_active, inv_scale=None, *, max_bin: int,
                          hist_acc: str = "f32",
                          row_block: int = PALLAS_ROW_BLOCK,
                          interpret: bool = False) -> jax.Array:
    """leaf_histogram_masked restricted to row blocks
    [start_block, start_block + n_active) — correct whenever every row
    with leaf_eff == target_leaf lies inside that block range (the
    ordered-partition invariant; rows of OTHER leaves inside the range
    are masked out as usual).  start_block/n_active are traced scalars:
    one compiled kernel serves every leaf range."""
    f, n = bins_t.shape
    assert n % row_block == 0, (n, row_block)
    assert max_bin <= N_HI * N_LO, max_bin
    fb = _feat_block(f)
    fpad = ((f + fb - 1) // fb) * fb
    if fpad != f:
        bins_t = jnp.pad(bins_t, ((0, fpad - f), (0, 0)))
    groups = fpad // fb
    nblocks = n // row_block
    # n_active >= 1 keeps the clamp and the r==0 init well-defined; an
    # EMPTY target leaf stays correct because the in-kernel mask
    # (leaf_eff == target) selects nothing in whatever block is swept
    info = jnp.stack([jnp.asarray(target_leaf, jnp.int32),
                      jnp.clip(jnp.asarray(start_block, jnp.int32), 0,
                               nblocks - 1),
                      jnp.maximum(jnp.asarray(n_active, jnp.int32), 1)])

    def _rb(r, info_ref):
        # clamp to the last active block: inactive steps re-request it,
        # which the pipeline recognizes as "same block, no copy"
        return info_ref[1] + jnp.minimum(r, info_ref[2] - 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(groups, nblocks),
        in_specs=[
            pl.BlockSpec((fb, row_block), lambda i, r, s: (i, _rb(r, s))),
            pl.BlockSpec((2, row_block), lambda i, r, s: (0, _rb(r, s))),
            pl.BlockSpec((row_block,), lambda i, r, s: (_rb(r, s),)),
        ],
        out_specs=pl.BlockSpec((1, fb // MM_FEATS, M_ROWS, N_COLS),
                               lambda i, r, s: (i, 0, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_hist_kernel_ranged, hist_acc),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (groups, fb // MM_FEATS, M_ROWS, N_COLS),
            _acc_dtype(hist_acc)),
        interpret=interpret,
    )(info, bins_t, gh2, leaf_eff)
    hist = _diag_hist_xla(out, fpad, hist_acc, inv_scale)
    return hist[:f, :max_bin, :]


@functools.partial(jax.jit,
                   static_argnames=("max_bin", "hist_acc", "grid_blocks",
                                    "row_block", "interpret"))
def leaf_histogram_blocklist(bins_t: jax.Array, gh2: jax.Array,
                             leaf_eff: jax.Array, target_leaf,
                             block_list: jax.Array, n_active,
                             inv_scale=None, *,
                             max_bin: int, hist_acc: str = "f32",
                             grid_blocks: int = 0,
                             row_block: int = PALLAS_ROW_BLOCK,
                             interpret: bool = False) -> jax.Array:
    """leaf_histogram_masked restricted to the row blocks named by
    block_list[:n_active] (any order; ascending preserves the full
    sweep's accumulation association, making the result BIT-identical to
    it — skipped blocks contribute exact +0.0f).  Correct whenever every
    row with leaf_eff == target_leaf lies in a listed block; rows of
    other leaves in listed blocks are masked as usual.

    grid_blocks statically bounds the grid (and therefore the per-call
    floor cost); callers dispatch over a ladder of compiled variants and
    pick the smallest with grid_blocks >= n_active.  Steps past n_active
    revisit the last listed block (no DMA) and skip their matmuls.
    """
    f, n = bins_t.shape
    assert n % row_block == 0, (n, row_block)
    assert max_bin <= N_HI * N_LO, max_bin
    fb = _feat_block(f)
    fpad = ((f + fb - 1) // fb) * fb
    if fpad != f:
        bins_t = jnp.pad(bins_t, ((0, fpad - f), (0, 0)))
    groups = fpad // fb
    nblocks = n // row_block
    if grid_blocks <= 0 or grid_blocks > nblocks:
        grid_blocks = nblocks
    info = jnp.stack([jnp.asarray(target_leaf, jnp.int32),
                      jnp.int32(0),
                      jnp.clip(jnp.asarray(n_active, jnp.int32), 1,
                               grid_blocks)])
    blist = jnp.clip(block_list.astype(jnp.int32), 0, nblocks - 1)

    def _rb(r, info_ref, blist_ref):
        return blist_ref[jnp.minimum(r, info_ref[2] - 1)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(groups, grid_blocks),
        in_specs=[
            pl.BlockSpec((fb, row_block),
                         lambda i, r, s, bl: (i, _rb(r, s, bl))),
            pl.BlockSpec((2, row_block),
                         lambda i, r, s, bl: (0, _rb(r, s, bl))),
            pl.BlockSpec((row_block,),
                         lambda i, r, s, bl: (_rb(r, s, bl),)),
        ],
        out_specs=pl.BlockSpec((1, fb // MM_FEATS, M_ROWS, N_COLS),
                               lambda i, r, s, bl: (i, 0, 0, 0)),
    )
    out = pl.pallas_call(
        functools.partial(_hist_kernel_blocklist, hist_acc),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (groups, fb // MM_FEATS, M_ROWS, N_COLS),
            _acc_dtype(hist_acc)),
        interpret=interpret,
    )(info, blist, bins_t, gh2, leaf_eff)
    hist = _diag_hist_xla(out, fpad, hist_acc, inv_scale)
    return hist[:f, :max_bin, :]


def leaf_histogram_pallas(bins_t: jax.Array, gh2: jax.Array,
                          mask: jax.Array, *, max_bin: int,
                          row_block: int = PALLAS_ROW_BLOCK,
                          interpret: bool = False) -> jax.Array:
    """Histogram of mask-selected rows: thin wrapper over the fused-mask
    kernel with the mask folded into a single-leaf leaf_eff."""
    leaf_eff = fold_leaf_mask(jnp.zeros(bins_t.shape[1], jnp.int32), mask)
    return leaf_histogram_masked(bins_t, gh2, leaf_eff, jnp.int32(0),
                                 max_bin=max_bin, row_block=row_block,
                                 interpret=interpret)


# ---------------------------------------------------------------------------
# Fused histogram + best-split gain scan (round 16)
# ---------------------------------------------------------------------------

def _fused_scan_tail(info_ref, stats_ref, parent_ref, fmask_ref, out_ref,
                     pfs_ref, pfl_ref, r_last, max_bin, params, hist_acc):
    """The in-kernel gain-scan epilogue every fused variant shares: on
    the LAST grid step — the feature block's accumulators complete and
    still VMEM-resident — extract the block-diagonal into per-feature
    [B, 3] histograms, run `per_feature_split_rows` (the oracle scan's
    exact jnp ops) for the swept child, subtract from the streamed-in
    parent block and scan the sibling, and emit one [fb, 8] best row
    per child.  The [F, B, 3] tensor is never read back from HBM for
    scanning."""
    r = pl.program_id(1)

    @pl.when(r == r_last)
    def _scan():
        fb = fmask_ref.shape[0]
        acc = out_ref[0]                 # [fb//4, 96, 128] acc dtype
        rows = []
        for m in range(fb // MM_FEATS):
            for f in range(MM_FEATS):
                sub = acc[m, f * (N_COMP * N_HI):(f + 1)
                          * (N_COMP * N_HI),
                          f * N_LO:(f + 1) * N_LO]       # [24, 32]
                rows.append(sub.reshape(N_COMP, N_HI * N_LO))
        h3 = jnp.stack(rows).astype(jnp.float32)         # [fb, 3, 256]
        if hist_acc == "i32":
            inv = stats_ref[SF_INV]
            h3 = h3 * jnp.stack([inv, inv,
                                 jnp.float32(1.0)])[None, :, None]
        # slice to max_bin BEFORE the scan: literally the oracle's
        # [F, max_bin, 3] input, so the suffix sums see identical arrays
        hist = h3[:, :, :max_bin].transpose(0, 2, 1)     # [fb, B, 3]
        fmask = fmask_ref[...] > 0
        pfs_ref[...] = per_feature_split_rows(
            hist, info_ref[IF_CNT_S], stats_ref[SF_SG_S],
            stats_ref[SF_SH_S], fmask, params)
        large = parent_ref[...].astype(jnp.float32) - hist
        pfl_ref[...] = per_feature_split_rows(
            large, info_ref[IF_CNT_L], stats_ref[SF_SG_L],
            stats_ref[SF_SH_L], fmask, params)


def _hist_fused_kernel(hist_acc, max_bin, params, nblocks, info_ref,
                       stats_ref, bins_ref, gh_ref, leaf_ref, parent_ref,
                       fmask_ref, out_ref, pfs_ref, pfl_ref):
    r = pl.program_id(1)
    _accumulate(info_ref[0], bins_ref, gh_ref, leaf_ref, out_ref, r,
                True, hist_acc)
    _fused_scan_tail(info_ref, stats_ref, parent_ref, fmask_ref, out_ref,
                     pfs_ref, pfl_ref, nblocks - 1, max_bin, params,
                     hist_acc)


def _hist_fused_kernel_ranged(hist_acc, max_bin, params, nblocks,
                              info_ref, stats_ref, bins_ref, gh_ref,
                              leaf_ref, parent_ref, fmask_ref, out_ref,
                              pfs_ref, pfl_ref):
    r = pl.program_id(1)
    _accumulate(info_ref[0], bins_ref, gh_ref, leaf_ref, out_ref, r,
                r < info_ref[IF_ACTIVE], hist_acc)
    _fused_scan_tail(info_ref, stats_ref, parent_ref, fmask_ref, out_ref,
                     pfs_ref, pfl_ref, nblocks - 1, max_bin, params,
                     hist_acc)


def _ranged_fused_specs(fb, row_block, max_bin):
    """in/out specs of the ranged fused kernel (info + stats scalar-
    prefetched; index maps clamp to the last active block)."""
    def _rb(r, info_ref):
        return info_ref[1] + jnp.minimum(r, info_ref[IF_ACTIVE] - 1)

    in_specs = [
        pl.BlockSpec((fb, row_block),
                     lambda i, r, s, st: (i, _rb(r, s))),
        pl.BlockSpec((2, row_block),
                     lambda i, r, s, st: (0, _rb(r, s))),
        pl.BlockSpec((row_block,), lambda i, r, s, st: (_rb(r, s),)),
        pl.BlockSpec((fb, max_bin, 3), lambda i, r, s, st: (i, 0, 0)),
        pl.BlockSpec((fb,), lambda i, r, s, st: (i,)),
    ]
    out_specs = (
        pl.BlockSpec((1, fb // MM_FEATS, M_ROWS, N_COLS),
                     lambda i, r, s, st: (i, 0, 0, 0)),
        pl.BlockSpec((fb, PF_COLS), lambda i, r, s, st: (i, 0)),
        pl.BlockSpec((fb, PF_COLS), lambda i, r, s, st: (i, 0)),
    )
    return in_specs, out_specs


def _hist_fused_kernel_blocklist(hist_acc, max_bin, params, grid_blocks,
                                 info_ref, stats_ref, blist_ref, bins_ref,
                                 gh_ref, leaf_ref, parent_ref, fmask_ref,
                                 out_ref, pfs_ref, pfl_ref):
    r = pl.program_id(1)
    _accumulate(info_ref[0], bins_ref, gh_ref, leaf_ref, out_ref, r,
                r < info_ref[IF_ACTIVE], hist_acc)
    _fused_scan_tail(info_ref, stats_ref, parent_ref, fmask_ref, out_ref,
                     pfs_ref, pfl_ref, grid_blocks - 1, max_bin, params,
                     hist_acc)


def _fused_prep(bins_t, parent_hist, feature_mask,
                small_stats, large_stats, inv_scale, max_bin):
    """Shared padding + SMEM packing of the fused wrappers.  Returns
    (bins_t, parent, fmask_f, info_tail, stats, fb, fpad, groups)."""
    f, _ = bins_t.shape
    fb = _feat_block(f)
    fpad = ((f + fb - 1) // fb) * fb
    if fpad != f:
        bins_t = jnp.pad(bins_t, ((0, fpad - f), (0, 0)))
        parent_hist = jnp.pad(parent_hist,
                              ((0, fpad - f), (0, 0), (0, 0)))
        feature_mask = jnp.pad(feature_mask, (0, fpad - f))
    cnt_s, sg_s, sh_s = small_stats
    cnt_l, sg_l, sh_l = large_stats
    info_tail = [jnp.asarray(cnt_s, jnp.int32),
                 jnp.asarray(cnt_l, jnp.int32),
                 jnp.int32(0), jnp.int32(0), jnp.int32(0)]
    inv = (jnp.float32(1.0) if inv_scale is None
           else jnp.asarray(inv_scale, jnp.float32))
    f32 = jnp.float32
    stats = jnp.stack([jnp.asarray(sg_s, f32), jnp.asarray(sh_s, f32),
                       jnp.asarray(sg_l, f32), jnp.asarray(sh_l, f32),
                       inv, f32(0), f32(0), f32(0)])
    fmask_f = feature_mask.astype(jnp.float32)
    return (bins_t, parent_hist.astype(jnp.float32), fmask_f, info_tail,
            stats, fb, fpad, fpad // fb)


def _fused_outs(groups, fb, fpad, hist_acc):
    out_shape = (
        jax.ShapeDtypeStruct((groups, fb // MM_FEATS, M_ROWS, N_COLS),
                             _acc_dtype(hist_acc)),
        jax.ShapeDtypeStruct((fpad, PF_COLS), jnp.float32),
        jax.ShapeDtypeStruct((fpad, PF_COLS), jnp.float32),
    )
    return out_shape


@functools.partial(jax.jit,
                   static_argnames=("max_bin", "params", "hist_acc",
                                    "row_block", "interpret"))
def leaf_histogram_masked_fused(bins_t: jax.Array, gh2: jax.Array,
                                leaf_eff: jax.Array, target_leaf,
                                parent_hist: jax.Array,
                                feature_mask: jax.Array, small_stats,
                                large_stats, inv_scale=None, *,
                                max_bin: int, params: SplitParams,
                                hist_acc: str = "f32",
                                row_block: int = PALLAS_ROW_BLOCK,
                                interpret: bool = False):
    """Fused sweep + gain scan for one split's two children.

    Sweeps the rows with leaf_eff == target_leaf (the SMALL child),
    exactly like leaf_histogram_masked, and on the last grid step also
    scans small AND (parent - small) in-register.  small_stats /
    large_stats are (count i32, sum_g, sum_h) leaf totals; parent_hist
    is the parent's [F, max_bin, 3] f32 histogram (pool state).

    Returns (small_hist [F, max_bin, 3] f32, pf_small [F, 8],
    pf_large [F, 8]) — pf rows finish through
    ops/split.find_best_split_fused.
    """
    f, n = bins_t.shape
    assert n % row_block == 0, (n, row_block)
    assert max_bin <= N_HI * N_LO, max_bin
    (bins_t, parent, fmask_f, info_tail, stats, fb, fpad,
     groups) = _fused_prep(bins_t, parent_hist, feature_mask,
                           small_stats, large_stats, inv_scale,
                           max_bin)
    nblocks = n // row_block
    info = jnp.stack([jnp.asarray(target_leaf, jnp.int32),
                      jnp.int32(0), jnp.int32(nblocks)] + info_tail)

    out, pfs, pfl = pl.pallas_call(
        functools.partial(_hist_fused_kernel, hist_acc, max_bin, params,
                          nblocks),
        grid=(groups, nblocks),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((fb, row_block), lambda i, r: (i, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, row_block), lambda i, r: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_block,), lambda i, r: (r,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((fb, max_bin, 3), lambda i, r: (i, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((fb,), lambda i, r: (i,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((1, fb // MM_FEATS, M_ROWS, N_COLS),
                         lambda i, r: (i, 0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((fb, PF_COLS), lambda i, r: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((fb, PF_COLS), lambda i, r: (i, 0),
                         memory_space=pltpu.VMEM),
        ),
        out_shape=_fused_outs(groups, fb, fpad, hist_acc),
        interpret=interpret,
    )(info, stats, bins_t, gh2, leaf_eff, parent, fmask_f)
    hist = _diag_hist_xla(out, fpad, hist_acc, inv_scale)
    return hist[:f, :max_bin, :], pfs[:f], pfl[:f]


@functools.partial(jax.jit,
                   static_argnames=("max_bin", "params", "hist_acc",
                                    "grid_blocks", "row_block",
                                    "interpret"))
def leaf_histogram_blocklist_fused(bins_t: jax.Array, gh2: jax.Array,
                                   leaf_eff: jax.Array, target_leaf,
                                   block_list: jax.Array, n_active,
                                   parent_hist: jax.Array,
                                   feature_mask: jax.Array, small_stats,
                                   large_stats, inv_scale=None, *,
                                   max_bin: int, params: SplitParams,
                                   hist_acc: str = "f32",
                                   grid_blocks: int = 0,
                                   row_block: int = PALLAS_ROW_BLOCK,
                                   interpret: bool = False):
    """leaf_histogram_blocklist + the fused gain-scan epilogue: the
    ordered-partition fast path keeps its leaf-proportional sweeps AND
    drops the two XLA scan passes.  Same contract as
    leaf_histogram_masked_fused; same block-list correctness rule as
    leaf_histogram_blocklist."""
    f, n = bins_t.shape
    assert n % row_block == 0, (n, row_block)
    assert max_bin <= N_HI * N_LO, max_bin
    (bins_t, parent, fmask_f, info_tail, stats, fb, fpad,
     groups) = _fused_prep(bins_t, parent_hist, feature_mask,
                           small_stats, large_stats, inv_scale,
                           max_bin)
    nblocks = n // row_block
    if grid_blocks <= 0 or grid_blocks > nblocks:
        grid_blocks = nblocks
    info = jnp.stack([jnp.asarray(target_leaf, jnp.int32),
                      jnp.int32(0),
                      jnp.clip(jnp.asarray(n_active, jnp.int32), 1,
                               grid_blocks)] + info_tail)
    blist = jnp.clip(block_list.astype(jnp.int32), 0, nblocks - 1)

    def _rb(r, info_ref, blist_ref):
        return blist_ref[jnp.minimum(r, info_ref[IF_ACTIVE] - 1)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,   # info, stats, blist
        grid=(groups, grid_blocks),
        in_specs=[
            pl.BlockSpec((fb, row_block),
                         lambda i, r, s, st, bl: (i, _rb(r, s, bl))),
            pl.BlockSpec((2, row_block),
                         lambda i, r, s, st, bl: (0, _rb(r, s, bl))),
            pl.BlockSpec((row_block,),
                         lambda i, r, s, st, bl: (_rb(r, s, bl),)),
            pl.BlockSpec((fb, max_bin, 3),
                         lambda i, r, s, st, bl: (i, 0, 0)),
            pl.BlockSpec((fb,), lambda i, r, s, st, bl: (i,)),
        ],
        out_specs=(
            pl.BlockSpec((1, fb // MM_FEATS, M_ROWS, N_COLS),
                         lambda i, r, s, st, bl: (i, 0, 0, 0)),
            pl.BlockSpec((fb, PF_COLS), lambda i, r, s, st, bl: (i, 0)),
            pl.BlockSpec((fb, PF_COLS), lambda i, r, s, st, bl: (i, 0)),
        ),
    )
    out, pfs, pfl = pl.pallas_call(
        functools.partial(_hist_fused_kernel_blocklist, hist_acc,
                          max_bin, params, grid_blocks),
        grid_spec=grid_spec,
        out_shape=_fused_outs(groups, fb, fpad, hist_acc),
        interpret=interpret,
    )(info, stats, blist, bins_t, gh2, leaf_eff, parent, fmask_f)
    hist = _diag_hist_xla(out, fpad, hist_acc, inv_scale)
    return hist[:f, :max_bin, :], pfs[:f], pfl[:f]


@functools.partial(jax.jit,
                   static_argnames=("max_bin", "params", "hist_acc",
                                    "row_block", "interpret"))
def leaf_histogram_ranged_fused(bins_t: jax.Array, gh2: jax.Array,
                                leaf_eff: jax.Array, target_leaf,
                                start_block, n_active,
                                parent_hist: jax.Array,
                                feature_mask: jax.Array, small_stats,
                                large_stats, inv_scale=None, *,
                                max_bin: int, params: SplitParams,
                                hist_acc: str = "f32",
                                row_block: int = PALLAS_ROW_BLOCK,
                                interpret: bool = False):
    """leaf_histogram_ranged + the fused gain-scan epilogue.  Same
    contract as leaf_histogram_masked_fused; same contiguous-range
    correctness rule as leaf_histogram_ranged.

    Like its non-fused twin, this variant is not on the grow_tree
    routing (the ordered-partition mode builds block lists and fuses
    through leaf_histogram_blocklist_fused) — it is the maintained
    contiguous-range API for callers that track leaf extents instead
    of block lists, parity-pinned at the kernel level by
    tests/test_hist_fused.py."""
    f, n = bins_t.shape
    assert n % row_block == 0, (n, row_block)
    assert max_bin <= N_HI * N_LO, max_bin
    (bins_t, parent, fmask_f, info_tail, stats, fb, fpad,
     groups) = _fused_prep(bins_t, parent_hist, feature_mask,
                           small_stats, large_stats, inv_scale,
                           max_bin)
    nblocks = n // row_block
    info = jnp.stack([jnp.asarray(target_leaf, jnp.int32),
                      jnp.clip(jnp.asarray(start_block, jnp.int32), 0,
                               nblocks - 1),
                      jnp.maximum(jnp.asarray(n_active, jnp.int32), 1)]
                     + info_tail)
    in_specs, out_specs = _ranged_fused_specs(fb, row_block, max_bin)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,   # info, stats
        grid=(groups, nblocks),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    out, pfs, pfl = pl.pallas_call(
        functools.partial(_hist_fused_kernel_ranged, hist_acc, max_bin,
                          params, nblocks),
        grid_spec=grid_spec,
        out_shape=_fused_outs(groups, fb, fpad, hist_acc),
        interpret=interpret,
    )(info, stats, bins_t, gh2, leaf_eff, parent, fmask_f)
    hist = _diag_hist_xla(out, fpad, hist_acc, inv_scale)
    return hist[:f, :max_bin, :], pfs[:f], pfl[:f]
