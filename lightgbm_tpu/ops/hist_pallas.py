"""Pallas TPU histogram kernel — the fast path for the #1 hot loop.

The XLA formulation (ops/histogram.py) materializes per-feature one-hot
matrices in HBM (~N*B bytes per feature per split), which dominates at
scale; a straight 256-wide one-hot in VMEM is VPU-bound on the compares.
This kernel uses a radix decomposition bin = hi*32 + lo:

    lhs[c*8+hi, r] = gv[c, r] * (bins_hi[r] == hi)     (VPU: 8+32 compares
    onehot_lo[r, lo] = (bins_lo[r] == lo)               + 32 mults per row)
    part[c*8+hi, lo] = lhs @ onehot_lo                  (MXU)

so hist[c, hi*32+lo] falls out of one [32, blk] x [blk, 32] matmul per
feature per row-block — ~6x fewer VPU ops than the naive one-hot and no
HBM one-hot traffic at all.

Layouts (all chosen for TPU tiling):
  - features processed FEAT_BLOCK=8 at a time
  - kernel output [F, 32, 32]: sublanes = 4 components x 8 hi (component 3
    is an always-zero pad row), lanes = 32 lo values — reshaped to the
    standard [F, B, 3] outside the kernel
  - bins padded to F multiple of 8, N multiple of row_block

Equivalent to DenseBin::ConstructHistogram (reference
src/io/dense_bin.hpp:39-104) with the leaf/bag mask folded into gvals.
Currently supports max_bin <= 256.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

GV_ROWS = 8   # gvals rows: (grad, hess, count, 5 x zero pad)
FEAT_BLOCK = 8
N_HI = 8
N_LO = 32
N_COMP = 4    # grad, hess, count, zero-pad — keeps lhs at 32 sublanes
PALLAS_ROW_BLOCK = 8192   # rows per grid step; N must be a multiple


def make_gvals8(grad: jax.Array, hess: jax.Array, mask: jax.Array
                ) -> jax.Array:
    """[8, N] f32 pre-masked accumulator rows (rows: g*m, h*m, m, 0...)."""
    m = mask.astype(jnp.float32)
    g = grad.astype(jnp.float32) * m
    h = hess.astype(jnp.float32) * m
    z = jnp.zeros_like(m)
    return jnp.stack([g, h, m, z, z, z, z, z])


def leaf_histogram_pallas(bins_t: jax.Array, gvals8: jax.Array, *,
                          max_bin: int, row_block: int = PALLAS_ROW_BLOCK,
                          interpret: bool = False) -> jax.Array:
    """Histogram of pre-masked gvals8 rows (see make_gvals8): a thin wrapper
    over the fused-mask kernel with an always-true mask."""
    n = bins_t.shape[1]
    return leaf_histogram_masked(
        bins_t, gvals8, jnp.zeros(n, jnp.int32), jnp.ones(n, jnp.int32),
        jnp.int32(0), max_bin=max_bin, row_block=row_block,
        interpret=interpret)


# ---------------------------------------------------------------------------
# the kernel: the (leaf_id == target) & bag mask is computed inside, so
# per-split HBM traffic is bins + grad/hess + leaf_id + bag only — no
# [8, N] gvals materialization per split.
# ---------------------------------------------------------------------------

def _hist_masked_kernel(target_ref, bins_ref, gh_ref, leaf_ref, bag_ref,
                        out_ref):
    r = pl.program_id(1)
    gh = gh_ref[:N_COMP, :]                                   # [4, blk]
    blk = gh.shape[1]
    target = target_ref[0]
    mask = ((leaf_ref[:] == target) & (bag_ref[:] != 0)).astype(jnp.float32)
    iota_hi = jax.lax.broadcasted_iota(jnp.int32, (N_HI, blk), 0)
    iota_lo = jax.lax.broadcasted_iota(jnp.int32, (blk, N_LO), 1)
    for k in range(FEAT_BLOCK):
        bins_blk = bins_ref[k, :].astype(jnp.int32)
        hi = bins_blk // N_LO
        lo = bins_blk - hi * N_LO
        masked_hi = ((hi[None, :] == iota_hi).astype(jnp.float32)
                     * mask[None, :])                         # [8, blk]
        onehot_lo = (lo[:, None] == iota_lo).astype(jnp.float32)
        lhs = (gh[:, None, :] * masked_hi[None, :, :]).reshape(
            N_COMP * N_HI, blk)
        part = jnp.dot(lhs, onehot_lo,
                       preferred_element_type=jnp.float32)    # [32, 32]

        @pl.when(r == 0)
        def _init():
            out_ref[k, :, :] = part

        @pl.when(r != 0)
        def _acc():
            out_ref[k, :, :] += part


@functools.partial(jax.jit,
                   static_argnames=("max_bin", "row_block", "interpret"))
def leaf_histogram_masked(bins_t: jax.Array, gh8: jax.Array,
                          leaf_id: jax.Array, bag: jax.Array,
                          target_leaf, *, max_bin: int,
                          row_block: int = PALLAS_ROW_BLOCK,
                          interpret: bool = False) -> jax.Array:
    """Histogram over rows with leaf_id == target_leaf and bag != 0.

    bins_t [F, N] uint8; gh8 [8, N] f32 rows (grad, hess, 1, 0...) — built
    ONCE per tree; leaf_id [N] i32; bag [N] i32 (0/1).
    Returns hist [F, max_bin, 3] f32.
    """
    f, n = bins_t.shape
    assert n % row_block == 0, (n, row_block)
    assert max_bin <= N_HI * N_LO, max_bin
    fpad = ((f + FEAT_BLOCK - 1) // FEAT_BLOCK) * FEAT_BLOCK
    if fpad != f:
        bins_t = jnp.pad(bins_t, ((0, fpad - f), (0, 0)))
    nblocks = n // row_block
    target = jnp.asarray(target_leaf, dtype=jnp.int32).reshape(1)

    out = pl.pallas_call(
        _hist_masked_kernel,
        grid=(fpad // FEAT_BLOCK, nblocks),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((FEAT_BLOCK, row_block), lambda i, r: (i, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((GV_ROWS, row_block), lambda i, r: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_block,), lambda i, r: (r,),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_block,), lambda i, r: (r,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((FEAT_BLOCK, N_COMP * N_HI, N_LO),
                               lambda i, r: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((fpad, N_COMP * N_HI, N_LO),
                                       jnp.float32),
        interpret=interpret,
    )(target, bins_t, gh8, leaf_id, bag)
    hist = out[:f].reshape(f, N_COMP, N_HI * N_LO)[:, :3, :]
    return hist[:, :, :max_bin].transpose(0, 2, 1)


def make_gh8(grad: jax.Array, hess: jax.Array) -> jax.Array:
    """[8, N] f32 (grad, hess, 1, 0...) — per-tree constant rows."""
    g = grad.astype(jnp.float32)
    h = hess.astype(jnp.float32)
    o = jnp.ones_like(g)
    z = jnp.zeros_like(g)
    return jnp.stack([g, h, o, z, z, z, z, z])
