"""Pallas TPU histogram kernel — the fast path for the #1 hot loop.

The XLA formulation (ops/histogram.py) materializes per-feature one-hot
matrices in HBM (~N*B bytes per feature per split), which dominates at
scale.  This kernel uses a radix decomposition bin = hi*32 + lo and packs
MM_FEATS=4 features into ONE block-diagonal MXU matmul (a grid step
covers _feat_block(F) <= MAX_FEAT_BLOCK features, several matmuls):

    lhs[(f, c, hi), r] = gh3[c, r] * (bins_hi[f, r] == hi)   [96, blk]
    rhs[r, (f, lo)]    = (bins_lo[f, r] == lo)               [blk, 128]
    part = lhs @ rhs                                         [96, 128]

so hist[f, hi*32+lo, c] is the f-diagonal of the [4x4 blocks] product.
The off-diagonal (f != f') blocks are wasted FLOPs, but the [96,128]x[blk]
shape keeps the MXU at near-full tile utilization — ~5x faster end-to-end
than one [32, blk] x [blk, 32] matmul per feature, whose 32-wide tiles run
the MXU at 1/16 of peak.

Inputs are kept slim because HBM streaming dominates: bins [F, N] uint8,
gh2 [2, N] f32 (grad, hess; built once per tree), and ONE leaf_eff [N]
int32 with the bagging mask pre-folded (out-of-bag rows get -1, which can
never equal a target leaf).  The (leaf_eff == target) mask is computed
in-kernel, so per-split traffic is bins + gh2 + leaf_eff only — no [N]
per-split gvals materialization.

Equivalent to DenseBin::ConstructHistogram (reference
src/io/dense_bin.hpp:39-104) with the leaf/bag mask folded into the
accumulated values.  Supports max_bin <= 256.
"""

from __future__ import annotations

import functools

from ..utils.compile_cache import enable_compilation_cache

enable_compilation_cache()   # before any jit traces (was a package-import side effect)

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

MAX_FEAT_BLOCK = 16   # features per grid step (gh2/leaf_eff stream from
                      # HBM once per row block per GRID STEP, so wide
                      # feature blocks amortize that traffic; sublane
                      # tiling wants a multiple of 8)
MM_FEATS = 4      # features per block-diagonal matmul
N_HI = 8
N_LO = 32
N_COMP = 3    # grad, hess, count
M_ROWS = MM_FEATS * N_COMP * N_HI   # 96
N_COLS = MM_FEATS * N_LO            # 128
PALLAS_ROW_BLOCK = 8192   # rows per grid step; N must be a multiple —
#                           this is also the alignment of the
#                           bag-compacted sweep window (models/gbdt.py
#                           pads the static in-bag window to it), so the
#                           kernels never see a partial block


def _feat_block(f: int) -> int:
    return min(MAX_FEAT_BLOCK, ((f + 7) // 8) * 8)


def make_gh2(grad: jax.Array, hess: jax.Array) -> jax.Array:
    """[2, N] f32 (grad, hess) — per-tree constant rows."""
    return jnp.stack([grad.astype(jnp.float32), hess.astype(jnp.float32)])


def fold_leaf_mask(leaf_id: jax.Array, mask: jax.Array) -> jax.Array:
    """leaf_eff [N] i32: leaf_id where mask, else -1 (never a target)."""
    return jnp.where(mask, leaf_id.astype(jnp.int32), jnp.int32(-1))


def _hist_kernel(target_ref, bins_ref, gh_ref, leaf_ref, out_ref):
    r = pl.program_id(1)
    feat_block, blk = bins_ref.shape
    mask = (leaf_ref[:] == target_ref[0]).astype(jnp.float32)    # [blk]
    gh3 = jnp.stack([gh_ref[0, :] * mask, gh_ref[1, :] * mask, mask])
    bins = bins_ref[...].astype(jnp.int32)                       # [fb, blk]
    hi = bins >> 5
    lo = bins & 31
    iota_hi = jax.lax.broadcasted_iota(jnp.int32, (N_HI, blk), 0)
    iota_lo = jax.lax.broadcasted_iota(jnp.int32, (N_LO, blk), 0)
    for m in range(feat_block // MM_FEATS):
        lhs_parts = []
        rhs_parts = []
        for f in range(m * MM_FEATS, (m + 1) * MM_FEATS):
            ohi = (hi[f][None, :] == iota_hi).astype(jnp.float32)  # [8, blk]
            lhs_parts.append((gh3[:, None, :] * ohi[None, :, :])
                             .reshape(N_COMP * N_HI, blk))
            rhs_parts.append((lo[f][None, :] == iota_lo)
                             .astype(jnp.float32))               # [32, blk]
        lhs = jnp.concatenate(lhs_parts, axis=0)                 # [96, blk]
        # rhs stays lane-major [128, blk]: contracting BOTH operands on the
        # row (lane) dim avoids the [blk, 32] one-hot transpose relayout
        rhs = jnp.concatenate(rhs_parts, axis=0)                 # [128, blk]
        part = jax.lax.dot_general(
            lhs, rhs, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)                  # [96, 128]

        @pl.when(r == 0)
        def _init():
            out_ref[0, m, :, :] = part

        @pl.when(r != 0)
        def _acc():
            out_ref[0, m, :, :] += part


@functools.partial(jax.jit,
                   static_argnames=("max_bin", "row_block", "interpret"))
def leaf_histogram_masked(bins_t: jax.Array, gh2: jax.Array,
                          leaf_eff: jax.Array, target_leaf, *, max_bin: int,
                          row_block: int = PALLAS_ROW_BLOCK,
                          interpret: bool = False) -> jax.Array:
    """Histogram over rows with leaf_eff == target_leaf.

    bins_t [F, N] uint8; gh2 [2, N] f32 (see make_gh2) — built ONCE per
    tree; leaf_eff [N] i32 with bagging folded in (see fold_leaf_mask).
    Returns hist [F, max_bin, 3] f32 with components (grad, hess, count).
    """
    f, n = bins_t.shape
    assert n % row_block == 0, (n, row_block)
    assert max_bin <= N_HI * N_LO, max_bin
    fb = _feat_block(f)
    fpad = ((f + fb - 1) // fb) * fb
    if fpad != f:
        bins_t = jnp.pad(bins_t, ((0, fpad - f), (0, 0)))
    groups = fpad // fb
    nblocks = n // row_block
    target = jnp.asarray(target_leaf, dtype=jnp.int32).reshape(1)

    out = pl.pallas_call(
        _hist_kernel,
        grid=(groups, nblocks),   # row dim minor: out block stays in VMEM
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((fb, row_block), lambda i, r: (i, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((2, row_block), lambda i, r: (0, r),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_block,), lambda i, r: (r,),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, fb // MM_FEATS, M_ROWS, N_COLS),
                               lambda i, r: (i, 0, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct(
            (groups, fb // MM_FEATS, M_ROWS, N_COLS), jnp.float32),
        interpret=interpret,
    )(target, bins_t, gh2, leaf_eff)
    # rows are (f, c, hi), cols are (f', lo); feature f's histogram is the
    # f == f' diagonal of the 4x4 block structure
    part = out.reshape(-1, MM_FEATS, N_COMP, N_HI, MM_FEATS, N_LO)
    diag = jnp.einsum("gfchfl->gfchl", part)
    hist = diag.transpose(0, 1, 3, 4, 2).reshape(fpad, N_HI * N_LO, N_COMP)
    return hist[:f, :max_bin, :]


def _hist_body(info_ref, bins_ref, gh_ref, leaf_ref, out_ref):
    """Shared body of the ranged/blocklist kernels: info = [target, _,
    n_active] (SMEM).

    The grid's row dimension is the static worst case; steps past
    n_active revisit the last active block (index maps clamp), so the
    pipeline skips their DMA, and pl.when skips their matmuls — the cost
    of an inactive step is grid bookkeeping only.  This is what makes
    sweep time proportional to the leaf's block count instead of N.
    """
    r = pl.program_id(1)
    feat_block, blk = bins_ref.shape
    active = r < info_ref[2]

    def emit(init):
        mask = (leaf_ref[:] == info_ref[0]).astype(jnp.float32)
        gh3 = jnp.stack([gh_ref[0, :] * mask, gh_ref[1, :] * mask, mask])
        bins = bins_ref[...].astype(jnp.int32)
        hi = bins >> 5
        lo = bins & 31
        iota_hi = jax.lax.broadcasted_iota(jnp.int32, (N_HI, blk), 0)
        iota_lo = jax.lax.broadcasted_iota(jnp.int32, (N_LO, blk), 0)
        for m in range(feat_block // MM_FEATS):
            lhs_parts = []
            rhs_parts = []
            for f in range(m * MM_FEATS, (m + 1) * MM_FEATS):
                ohi = (hi[f][None, :] == iota_hi).astype(jnp.float32)
                lhs_parts.append((gh3[:, None, :] * ohi[None, :, :])
                                 .reshape(N_COMP * N_HI, blk))
                rhs_parts.append((lo[f][None, :] == iota_lo)
                                 .astype(jnp.float32))
            lhs = jnp.concatenate(lhs_parts, axis=0)
            rhs = jnp.concatenate(rhs_parts, axis=0)
            part = jax.lax.dot_general(
                lhs, rhs, dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if init:
                out_ref[0, m, :, :] = part
            else:
                out_ref[0, m, :, :] += part

    @pl.when(r == 0)
    def _init():
        emit(True)

    @pl.when((r != 0) & active)
    def _acc():
        emit(False)


def _hist_kernel_ranged(info_ref, bins_ref, gh_ref, leaf_ref, out_ref):
    _hist_body(info_ref, bins_ref, gh_ref, leaf_ref, out_ref)


def _hist_kernel_blocklist(info_ref, blist_ref, bins_ref, gh_ref, leaf_ref,
                           out_ref):
    # blist_ref is consumed by the index maps; the body only needs info
    _hist_body(info_ref, bins_ref, gh_ref, leaf_ref, out_ref)


@functools.partial(jax.jit,
                   static_argnames=("max_bin", "row_block", "interpret"))
def leaf_histogram_ranged(bins_t: jax.Array, gh2: jax.Array,
                          leaf_eff: jax.Array, target_leaf, start_block,
                          n_active, *, max_bin: int,
                          row_block: int = PALLAS_ROW_BLOCK,
                          interpret: bool = False) -> jax.Array:
    """leaf_histogram_masked restricted to row blocks
    [start_block, start_block + n_active) — correct whenever every row
    with leaf_eff == target_leaf lies inside that block range (the
    ordered-partition invariant; rows of OTHER leaves inside the range
    are masked out as usual).  start_block/n_active are traced scalars:
    one compiled kernel serves every leaf range."""
    f, n = bins_t.shape
    assert n % row_block == 0, (n, row_block)
    assert max_bin <= N_HI * N_LO, max_bin
    fb = _feat_block(f)
    fpad = ((f + fb - 1) // fb) * fb
    if fpad != f:
        bins_t = jnp.pad(bins_t, ((0, fpad - f), (0, 0)))
    groups = fpad // fb
    nblocks = n // row_block
    # n_active >= 1 keeps the clamp and the r==0 init well-defined; an
    # EMPTY target leaf stays correct because the in-kernel mask
    # (leaf_eff == target) selects nothing in whatever block is swept
    info = jnp.stack([jnp.asarray(target_leaf, jnp.int32),
                      jnp.clip(jnp.asarray(start_block, jnp.int32), 0,
                               nblocks - 1),
                      jnp.maximum(jnp.asarray(n_active, jnp.int32), 1)])

    def _rb(r, info_ref):
        # clamp to the last active block: inactive steps re-request it,
        # which the pipeline recognizes as "same block, no copy"
        return info_ref[1] + jnp.minimum(r, info_ref[2] - 1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(groups, nblocks),
        in_specs=[
            pl.BlockSpec((fb, row_block), lambda i, r, s: (i, _rb(r, s))),
            pl.BlockSpec((2, row_block), lambda i, r, s: (0, _rb(r, s))),
            pl.BlockSpec((row_block,), lambda i, r, s: (_rb(r, s),)),
        ],
        out_specs=pl.BlockSpec((1, fb // MM_FEATS, M_ROWS, N_COLS),
                               lambda i, r, s: (i, 0, 0, 0)),
    )
    out = pl.pallas_call(
        _hist_kernel_ranged,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (groups, fb // MM_FEATS, M_ROWS, N_COLS), jnp.float32),
        interpret=interpret,
    )(info, bins_t, gh2, leaf_eff)
    part = out.reshape(-1, MM_FEATS, N_COMP, N_HI, MM_FEATS, N_LO)
    diag = jnp.einsum("gfchfl->gfchl", part)
    hist = diag.transpose(0, 1, 3, 4, 2).reshape(fpad, N_HI * N_LO, N_COMP)
    return hist[:f, :max_bin, :]


@functools.partial(jax.jit,
                   static_argnames=("max_bin", "grid_blocks", "row_block",
                                    "interpret"))
def leaf_histogram_blocklist(bins_t: jax.Array, gh2: jax.Array,
                             leaf_eff: jax.Array, target_leaf,
                             block_list: jax.Array, n_active, *,
                             max_bin: int, grid_blocks: int = 0,
                             row_block: int = PALLAS_ROW_BLOCK,
                             interpret: bool = False) -> jax.Array:
    """leaf_histogram_masked restricted to the row blocks named by
    block_list[:n_active] (any order; ascending preserves the full
    sweep's accumulation association, making the result BIT-identical to
    it — skipped blocks contribute exact +0.0f).  Correct whenever every
    row with leaf_eff == target_leaf lies in a listed block; rows of
    other leaves in listed blocks are masked as usual.

    grid_blocks statically bounds the grid (and therefore the per-call
    floor cost); callers dispatch over a ladder of compiled variants and
    pick the smallest with grid_blocks >= n_active.  Steps past n_active
    revisit the last listed block (no DMA) and skip their matmuls.
    """
    f, n = bins_t.shape
    assert n % row_block == 0, (n, row_block)
    assert max_bin <= N_HI * N_LO, max_bin
    fb = _feat_block(f)
    fpad = ((f + fb - 1) // fb) * fb
    if fpad != f:
        bins_t = jnp.pad(bins_t, ((0, fpad - f), (0, 0)))
    groups = fpad // fb
    nblocks = n // row_block
    if grid_blocks <= 0 or grid_blocks > nblocks:
        grid_blocks = nblocks
    info = jnp.stack([jnp.asarray(target_leaf, jnp.int32),
                      jnp.int32(0),
                      jnp.clip(jnp.asarray(n_active, jnp.int32), 1,
                               grid_blocks)])
    blist = jnp.clip(block_list.astype(jnp.int32), 0, nblocks - 1)

    def _rb(r, info_ref, blist_ref):
        return blist_ref[jnp.minimum(r, info_ref[2] - 1)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(groups, grid_blocks),
        in_specs=[
            pl.BlockSpec((fb, row_block),
                         lambda i, r, s, bl: (i, _rb(r, s, bl))),
            pl.BlockSpec((2, row_block),
                         lambda i, r, s, bl: (0, _rb(r, s, bl))),
            pl.BlockSpec((row_block,),
                         lambda i, r, s, bl: (_rb(r, s, bl),)),
        ],
        out_specs=pl.BlockSpec((1, fb // MM_FEATS, M_ROWS, N_COLS),
                               lambda i, r, s, bl: (i, 0, 0, 0)),
    )
    out = pl.pallas_call(
        _hist_kernel_blocklist,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (groups, fb // MM_FEATS, M_ROWS, N_COLS), jnp.float32),
        interpret=interpret,
    )(info, blist, bins_t, gh2, leaf_eff)
    part = out.reshape(-1, MM_FEATS, N_COMP, N_HI, MM_FEATS, N_LO)
    diag = jnp.einsum("gfchfl->gfchl", part)
    hist = diag.transpose(0, 1, 3, 4, 2).reshape(fpad, N_HI * N_LO, N_COMP)
    return hist[:f, :max_bin, :]


def leaf_histogram_pallas(bins_t: jax.Array, gh2: jax.Array,
                          mask: jax.Array, *, max_bin: int,
                          row_block: int = PALLAS_ROW_BLOCK,
                          interpret: bool = False) -> jax.Array:
    """Histogram of mask-selected rows: thin wrapper over the fused-mask
    kernel with the mask folded into a single-leaf leaf_eff."""
    leaf_eff = fold_leaf_mask(jnp.zeros(bins_t.shape[1], jnp.int32), mask)
    return leaf_histogram_masked(bins_t, gh2, leaf_eff, jnp.int32(0),
                                 max_bin=max_bin, row_block=row_block,
                                 interpret=interpret)
