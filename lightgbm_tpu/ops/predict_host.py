"""Host-side exact-compare encodings shared by every predict route.

The ONE home of the order-isomorphic f64 encoding and the rank-encoded
pack builder: the device matmul predictor (ops/predict.py), the batch
predictor (models/gbdt.py) and the serving flat-table engine
(serving/flatforest.py) all build their threshold representations here,
so the three routes compare values against the SAME keys and cannot
drift.  Everything in this module is pure numpy — it is importable from
jax-free lanes (the low-latency serving fast path runs a backend=native
process that must never pull jax), and ops/predict.py re-exports the
names for its historical callers.
"""

from __future__ import annotations

__jax_free__ = True

from typing import List, Tuple

import numpy as np


def split_hi_lo(a: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Order-isomorphic encoding of f64 values as (hi, lo) uint32 pairs.

    The device never needs x64: each double's bit pattern is mapped on
    the HOST to a uint64 whose unsigned order equals the IEEE-754 total
    order (negatives bit-flipped, positives sign-bit-set — the classic
    radix-sortable-float transform), then split into two uint32 words.
    Lexicographic compare of the pairs reproduces the f64 `<=` EXACTLY
    for every finite value, ±1e308 (the parser's inf mapping), and
    subnormals — no precision loss, int ops only on device.  -0.0 is
    normalized to +0.0 first (IEEE `<=` treats them equal); NaN maps to
    the largest key, so `value <= threshold` is false and NaN rows take
    the right child, matching the reference's failed double compare
    (tree.h:179-189)."""
    # one mutable working copy + in-place bit math: the naive
    # np.where chain built ~5 full-size temporaries, which dominated
    # peak memory for wide chunks (sparse prediction)
    a = np.array(a, dtype=np.float64, copy=True)
    nan = np.isnan(a)
    np.copyto(a, 0.0, where=(a == 0.0))     # -0.0 -> +0.0
    neg = np.signbit(a)                     # bit-level sign (incl. -nan)
    bits = a.view(np.uint64)
    bits ^= np.uint64(0x8000000000000000)   # non-negatives: set sign bit
    bits[neg] ^= np.uint64(0x7FFFFFFFFFFFFFFF)  # negatives: full flip
    bits[nan] = np.uint64(0xFFFFFFFFFFFFFFFF)
    lo = bits.astype(np.uint32)             # u64 -> u32 keeps the low word
    bits >>= np.uint64(32)
    hi = bits.astype(np.uint32)
    return hi, lo


def order_key(hi: np.ndarray, lo: np.ndarray) -> np.ndarray:
    """(hi, lo) uint32 pair -> uint64 order key.  The ONE definition both
    the model pack (threshold ranks) and rank_encode (value codes) use —
    the matmul predictor's exactness rests on the two sides agreeing."""
    return (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)


def rank_encode(hi: np.ndarray, lo: np.ndarray, tables: List[np.ndarray],
                dtype: "np.dtype" = np.uint16) -> np.ndarray:
    """Host-side exact rank encoding of raw values against the MODEL's
    per-feature threshold tables (prediction-time binning).

    tables[f] is the sorted array of uint64 order keys (split_hi_lo) of
    every threshold the model compares feature f against.  code(x) =
    searchsorted(table, key(x)) satisfies  x <= thr[i]  <=>  code(x) <=
    rank(thr[i])  EXACTLY in the f64 total order — and the codes are
    tiny integers, so the device upload is uint16 instead of raw keys
    (16x fewer bytes, the remote-tunnel predict bottleneck) and the
    selection matmul needs a single exactly-representable plane.  The
    serving flat-table engine passes dtype=int32 instead: it compares on
    the host, so it never needs the uint16 size cap."""
    key = order_key(hi, lo)
    out = np.zeros(hi.shape, dtype=dtype)
    for f, table in enumerate(tables):
        if len(table):
            out[:, f] = np.searchsorted(table, key[:, f],
                                        side="left").astype(dtype)
    return out


def threshold_rank_tables(trees, sf: np.ndarray, th: np.ndarray,
                          tl: np.ndarray, ftot: int):
    """Per-feature sorted threshold-key tables + per-node order keys.

    The shared first half of every rank-encoded pack: `tables[f]` holds
    the sorted uint64 order keys of all thresholds the model compares
    feature f against, `key` is the [T, M] node threshold keys and
    `real` masks the populated node slots.  matmul_host_arrays (device
    route) and serving/flatforest.compile_flat (host fast path) both
    rank their nodes against THESE tables, which is what makes the two
    routes' compares identical by construction."""
    t_cnt = len(trees)
    m = sf.shape[1]
    key = order_key(th, tl)                   # [T, M] order keys
    real = np.zeros((t_cnt, m), dtype=bool)
    for i in range(t_cnt):
        real[i, :trees[i].num_leaves - 1] = True
    tables = [np.unique(key[real & (sf == f)]) for f in range(ftot)]
    return tables, key, real


def matmul_host_arrays(trees, sf, th, tl, lc, rc, max_l, m, ftot,
                       tree_block):
    """Host-side arrays for the gather-free matmul predictor, shared by
    the batch path (models/gbdt.py _matmul_pack) and the serving forest
    (serving/forest.py) so the two packs cannot drift: one-hot feature
    selection, per-feature threshold rank tables (for rank_encode) +
    node rank codes, and per-tree path matrices.

    trees: the Tree list; sf/th/tl/lc/rc: the [T, M] padded node arrays
    (split_hi_lo threshold words); ftot: model feature width;
    tree_block: scan block multiple the tree count pads to.  Returns
    (tables, sel, thr_code, pos, neg, depth) as numpy arrays, or None
    when the pack declines (wide-feature selection matrix, uint16 code
    overflow) and the descent path should serve instead.
    """
    t_cnt = len(trees)
    # pad the tree count to the scan's block multiple; dummy trees
    # have an all-zero path and depth[0] = 0, so they argmax to leaf
    # 0 and are sliced off by the caller
    t_pad = -(-t_cnt // tree_block) * tree_block
    if ftot * t_pad * m > (1 << 26):
        # wide-feature models would make the one-hot selection
        # matrix hundreds of MB (e.g. 200k sparse features); the
        # descent path handles those instead
        return None
    sel = np.zeros((ftot, t_pad * m), dtype=np.float32)
    for i in range(t_cnt):
        for j in range(trees[i].num_leaves - 1):
            sel[sf[i, j], i * m + j] = 1.0
    tables, key, _ = threshold_rank_tables(trees, sf, th, tl, ftot)
    if max(len(t) for t in tables) >= 65535:
        return None   # uint16 codes overflow; descent path instead
    thr_code = np.zeros(t_pad * m, dtype=np.float32)
    for i in range(t_cnt):
        for j in range(trees[i].num_leaves - 1):
            thr_code[i * m + j] = np.searchsorted(
                tables[sf[i, j]], key[i, j], side="left")
    pos = np.zeros((t_pad, m, max_l), dtype=np.float32)
    neg = np.zeros((t_pad, m, max_l), dtype=np.float32)
    depth = np.full((t_pad, max_l), np.inf, dtype=np.float32)
    depth[t_cnt:, 0] = 0.0
    for i, t in enumerate(trees):
        # DFS from the root: child >= 0 is an internal node, ~child
        # is a leaf (tree.py wire format)
        stack = [(0, [])] if t.num_leaves > 1 else []
        if t.num_leaves == 1:
            depth[i, 0] = 0.0
        while stack:
            node, path = stack.pop()
            for child, sign in ((lc[i, node], 1.0),
                                (rc[i, node], -1.0)):
                cpath = path + [(node, sign)]
                if child < 0:
                    leaf = ~child
                    depth[i, leaf] = len(cpath)
                    for nd, sg in cpath:
                        (pos if sg > 0 else neg)[i, nd, leaf] = 1.0
                else:
                    stack.append((int(child), cpath))
    return tables, sel, thr_code, pos, neg, depth
