"""Vectorized best-split search.

Replaces FeatureHistogram::FindBestThreshold's right-to-left scalar scan
(reference src/treelearner/feature_histogram.hpp:112-170) with suffix sums +
masked argmax over all (feature, threshold) pairs at once — one fused XLA
computation instead of an OpenMP loop over features.

Exact semantic parity notes (all verified against the reference source):
  - right-side hessian starts at kEpsilon = 1e-15 (hpp:119)
  - thresholds scanned are t in [1, B); stored threshold is t-1; split rule
    is `bin <= threshold` goes left (hpp:125,152)
  - the `break` conditions on left stats are monotone in t, so they are
    equivalent to masks
  - gains >= gain_shift + min_gain_to_split are eligible (hpp:143, `<` skips)
  - within a feature, ties keep the LARGER threshold (descending scan with
    strict `>` replacement, hpp:148)
  - across features, ties keep the SMALLER feature index
    (SplitInfo::MaxReducer, src/treelearner/split_info.hpp:98-103)
  - L1/L2 regularized gain and leaf output (hpp:224-245)
"""

from __future__ import annotations

from typing import NamedTuple

from ..utils.compile_cache import enable_compilation_cache

enable_compilation_cache()   # before any jit traces (was a package-import side effect)

import jax
import jax.numpy as jnp

from ..analysis.contracts import contract

K_EPSILON = 1e-15
K_MIN_SCORE = -jnp.inf

# column layout of the per-feature best rows the FUSED Pallas
# histogram+gain kernel emits (ops/hist_pallas.py): everything
# find_best_split_fused needs to finish the cross-feature reduction
# without re-reading the [F, B, 3] histogram tensor.  Counts travel as
# f32 — exact below 2^24 rows, the same bound the f32 histogram count
# component already imposes.
PF_GAIN, PF_T, PF_LG, PF_LH, PF_LCNT, PF_RCNT = range(6)
PF_COLS = 8   # padded to 8 for a uniform [F, 8] row


class SplitParams(NamedTuple):
    """Static split hyper-parameters (baked into the jit)."""
    min_data_in_leaf: int
    min_sum_hessian_in_leaf: float
    lambda_l1: float
    lambda_l2: float
    min_gain_to_split: float


class BestSplit(NamedTuple):
    """Per-leaf best split candidate — SplitInfo as a struct of scalars
    (reference src/treelearner/split_info.hpp:14-54)."""
    gain: jax.Array          # f, kMinScore when invalid
    feature: jax.Array       # i32 inner feature index
    threshold: jax.Array     # i32 bin threshold (left: bin <= threshold)
    left_count: jax.Array    # i32
    right_count: jax.Array   # i32
    left_sum_g: jax.Array
    left_sum_h: jax.Array
    right_sum_g: jax.Array
    right_sum_h: jax.Array
    left_output: jax.Array
    right_output: jax.Array


def leaf_split_gain(sum_g, sum_h, l1: float, l2: float):
    """GetLeafSplitGain (reference feature_histogram.hpp:224-231)."""
    abs_g = jnp.abs(sum_g)
    reg = jnp.maximum(abs_g - l1, 0.0)
    return jnp.where(abs_g > l1, reg * reg / (sum_h + l2), 0.0)


def leaf_output(sum_g, sum_h, l1: float, l2: float):
    """CalculateSplittedLeafOutput (reference feature_histogram.hpp:239-245)."""
    abs_g = jnp.abs(sum_g)
    val = -jnp.sign(sum_g) * (abs_g - l1) / (sum_h + l2)
    return jnp.where(abs_g > l1, val, 0.0)


def _split_scan(hist: jax.Array, leaf_count, sum_g, sum_h,
                feature_mask: jax.Array, params: SplitParams):
    """The suffix-sum threshold scan shared by the serial argmax and the
    voting learner's per-feature vote.  Returns per-(feature, bin) arrays:
    (masked_gains, left_g, left_h, left_cnt, right_g, right_h, right_cnt,
    gain_shift)."""
    l1, l2 = params.lambda_l1, params.lambda_l2
    g = hist[:, :, 0]
    h = hist[:, :, 1]
    c = hist[:, :, 2]

    # suffix sums over bins: right side of a split at t covers bins >= t
    right_g = jnp.cumsum(g[:, ::-1], axis=1)[:, ::-1]
    right_h = jnp.cumsum(h[:, ::-1], axis=1)[:, ::-1] + K_EPSILON
    right_c = jnp.cumsum(c[:, ::-1], axis=1)[:, ::-1]
    right_cnt = jnp.round(right_c).astype(jnp.int32)

    left_g = sum_g - right_g
    left_h = sum_h - right_h
    left_cnt = leaf_count - right_cnt

    gain_shift = leaf_split_gain(sum_g, sum_h, l1, l2)
    min_gain_shift = gain_shift + params.min_gain_to_split

    gains = (leaf_split_gain(left_g, left_h, l1, l2)
             + leaf_split_gain(right_g, right_h, l1, l2))

    valid = ((right_cnt >= params.min_data_in_leaf)
             & (left_cnt >= params.min_data_in_leaf)
             & (right_h >= params.min_sum_hessian_in_leaf)
             & (left_h >= params.min_sum_hessian_in_leaf)
             & (gains >= min_gain_shift))
    # t = 0 is not a split (everything right); mask bin 0
    valid = valid.at[:, 0].set(False)
    valid = valid & feature_mask[:, None]

    masked_gains = jnp.where(valid, gains, K_MIN_SCORE)
    return (masked_gains, left_g, left_h, left_cnt, right_g, right_h,
            right_cnt, gain_shift)


def _per_feature_argmax(masked_gains: jax.Array):
    """Per-feature best threshold with the larger-t tie-break: argmax over
    REVERSED bins (descending scan with strict `>` replacement keeps the
    larger threshold, reference feature_histogram.hpp:148).
    -> (best_gain [F], best_t [F])."""
    b = masked_gains.shape[1]
    rev = masked_gains[:, ::-1]
    best_rev_idx = jnp.argmax(rev, axis=1)
    best_t = b - 1 - best_rev_idx
    best_gain_f = jnp.take_along_axis(masked_gains, best_t[:, None],
                                      axis=1)[:, 0]
    return best_gain_f, best_t


def per_feature_best(hist: jax.Array, leaf_count, sum_g, sum_h,
                     feature_mask: jax.Array, params: SplitParams):
    """(best_gain [F], best_threshold_bin t [F]) per feature — the local
    scoring pass of the voting learner (PV-Tree's local voting step)."""
    masked_gains = _split_scan(hist, leaf_count, sum_g, sum_h,
                               feature_mask, params)[0]
    return _per_feature_argmax(masked_gains)


def per_feature_split_rows(hist: jax.Array, leaf_count, sum_g, sum_h,
                           feature_mask: jax.Array,
                           params: SplitParams) -> jax.Array:
    """[F, PF_COLS] per-feature best rows (PF_* layout): the whole
    threshold scan reduced to one row per feature, so only O(F) scalars
    leave the histogram buffer.  This is the body the fused Pallas
    kernel runs in-register on its VMEM-resident accumulators
    (ops/hist_pallas.py) — the SAME jnp ops as `find_best_split`'s scan,
    so interpret-mode results are bit-identical to the two-op oracle."""
    (masked_gains, left_g, left_h, left_cnt, _rg, _rh, right_cnt,
     _shift) = _split_scan(hist, leaf_count, sum_g, sum_h,
                           feature_mask, params)
    best_gain_f, best_t = _per_feature_argmax(masked_gains)
    tcol = best_t[:, None]
    f32 = jnp.float32
    rows = jnp.stack([
        best_gain_f.astype(f32),
        best_t.astype(f32),
        jnp.take_along_axis(left_g, tcol, axis=1)[:, 0].astype(f32),
        jnp.take_along_axis(left_h, tcol, axis=1)[:, 0].astype(f32),
        jnp.take_along_axis(left_cnt, tcol, axis=1)[:, 0].astype(f32),
        jnp.take_along_axis(right_cnt, tcol, axis=1)[:, 0].astype(f32),
        jnp.zeros_like(best_gain_f, dtype=f32),
        jnp.zeros_like(best_gain_f, dtype=f32),
    ], axis=-1)
    return rows


def find_best_split_fused(pf: jax.Array, sum_g: jax.Array,
                          sum_h: jax.Array,
                          params: SplitParams) -> BestSplit:
    """Finish `find_best_split` from the fused kernel's per-feature best
    rows: a small XLA argmax over features (first max = smaller index,
    the MaxReducer tie-break) plus the scalar re-derivations the oracle
    performs on its winner — identical values, so fused-on trees are
    bit-parity with the two-op oracle."""
    dt = pf.dtype
    l1, l2 = params.lambda_l1, params.lambda_l2
    best_f = jnp.argmax(pf[:, PF_GAIN]).astype(jnp.int32)
    row = pf[best_f]
    gain = row[PF_GAIN]
    t = row[PF_T].astype(jnp.int32)
    bl_g = row[PF_LG]
    bl_h = row[PF_LH]
    bl_c = row[PF_LCNT].astype(jnp.int32)
    br_c = row[PF_RCNT].astype(jnp.int32)
    # right sums re-derived from parent totals, exactly the oracle's
    # bit-parity rule (reference hpp:164-168)
    br_g = sum_g - bl_g
    br_h = sum_h - bl_h
    gain_shift = leaf_split_gain(sum_g, sum_h, l1, l2)
    return BestSplit(
        gain=gain - gain_shift,
        feature=best_f,
        threshold=t - 1,
        left_count=bl_c,
        right_count=br_c,
        left_sum_g=bl_g.astype(dt),
        left_sum_h=bl_h.astype(dt),
        right_sum_g=br_g.astype(dt),
        right_sum_h=br_h.astype(dt),
        left_output=leaf_output(bl_g, bl_h, l1, l2).astype(dt),
        right_output=leaf_output(br_g, br_h, l1, l2).astype(dt),
    )


@contract.parity_oracle("the two-op split scan: hist_fused=off reads "
                        "the materialized [F, B, 3] histogram through "
                        "this XLA pass — the bit-parity oracle the "
                        "fused Pallas histogram+gain kernel is tested "
                        "against (PARITY.md §2.2)")
def find_best_split(hist: jax.Array, leaf_count: jax.Array,
                    sum_g: jax.Array, sum_h: jax.Array,
                    feature_mask: jax.Array, params: SplitParams) -> BestSplit:
    """Best split over one leaf's histograms.

    hist:         [F, B, 3] (grad, hess, count) per (feature, bin)
    leaf_count:   scalar i32 — rows in this leaf (bagged, or global when
                  data-parallel, matching data_parallel_tree_learner.cpp:155-186)
    sum_g/sum_h:  scalar leaf totals
    feature_mask: [F] bool — feature_fraction sample for this tree
    """
    dt = hist.dtype
    l1, l2 = params.lambda_l1, params.lambda_l2

    (masked_gains, left_g, left_h, left_cnt, right_g, right_h, right_cnt,
     gain_shift) = _split_scan(hist, leaf_count, sum_g, sum_h,
                               feature_mask, params)

    best_gain_f, best_t = _per_feature_argmax(masked_gains)

    # across features: first max = smaller feature index
    best_f = jnp.argmax(best_gain_f).astype(jnp.int32)
    t = best_t[best_f].astype(jnp.int32)
    gain = best_gain_f[best_f]

    bl_g = left_g[best_f, t]
    bl_h = left_h[best_f, t]
    br_g = right_g[best_f, t]
    br_h = right_h[best_f, t]
    bl_c = left_cnt[best_f, t]
    br_c = right_cnt[best_f, t]

    # reference reports sums re-derived from parent totals (hpp:164-168):
    # right = parent - left, with left kept from the scan. Our left/right are
    # both scan-derived; recompute right from totals for bit-parity.
    br_g = sum_g - bl_g
    br_h = sum_h - bl_h

    return BestSplit(
        gain=gain - gain_shift,
        feature=best_f,
        threshold=t - 1,
        left_count=bl_c,
        right_count=br_c,
        left_sum_g=bl_g.astype(dt),
        left_sum_h=bl_h.astype(dt),
        right_sum_g=br_g.astype(dt),
        right_sum_h=br_h.astype(dt),
        left_output=leaf_output(bl_g, bl_h, l1, l2).astype(dt),
        right_output=leaf_output(br_g, br_h, l1, l2).astype(dt),
    )
