"""Leaf-wise tree growth as one jitted fixed-trip `lax.scan`.

TPU-native redesign of SerialTreeLearner::Train
(reference src/treelearner/serial_tree_learner.cpp:100-134):

  - The reference's DataPartition (grouped row-index arrays, re-shuffled at
    every split) becomes a flat per-row `leaf_id [N] int32`, updated with one
    vectorized compare per split — no data movement, shard-local under pjit.
  - Per-leaf histogram cache (HistogramPool) becomes a dense
    `hist [L, F, B, 3]` tensor; the parent-minus-smaller-child subtraction
    trick (FeatureHistogram::Subtract, feature_histogram.hpp:97-106) is a
    tensor subtract, halving histogram work exactly as in the reference.
  - The whole `num_leaves - 1` split loop runs on-device inside one
    compiled fixed-trip scan; host sees a single call per tree.

Out-of-bag rows keep following splits via leaf_id (they are masked out of
histograms by bag_mask); this makes the final score update a single
`leaf_value[leaf_id]` gather for ALL rows, which is exactly equivalent to
the reference's two-path update (partition fast path + OOB traversal,
src/boosting/gbdt.cpp:162-167, score_updater.hpp:44-68).

For data-parallel training, `psum_axis` names a mesh axis: local histograms
and root sums are all-reduced over it (the moral equivalent of the
reference's ReduceScatter of histogram buffers,
src/treelearner/data_parallel_tree_learner.cpp:124-154), after which every
shard computes the identical split — the same invariant the reference
relies on (global counts, data_parallel_tree_learner.cpp:226-232).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from ..analysis.contracts import contract
from .histogram import leaf_histogram, make_gvals
from .predict import predict_leaf_binned
from .split import (BestSplit, SplitParams, find_best_split,
                    find_best_split_fused, K_MIN_SCORE, per_feature_best)


class TreeArrays(NamedTuple):
    """Array-based binary tree, mirroring reference include/LightGBM/tree.h:125-152.
    Leaves encoded as ~leaf_idx in child pointers.  Each array carries one
    trailing DUMMY slot (node index L-1 / leaf index L) that inactive scan
    steps write into — real entries are nodes [0, L-2] and leaves [0, L-1];
    the dummy is unreachable from traversal and trimmed on host export."""
    split_feature: jax.Array    # [L] i32 inner (used-feature) index
    threshold_bin: jax.Array    # [L] i32
    split_gain: jax.Array       # [L] f
    left_child: jax.Array       # [L] i32
    right_child: jax.Array      # [L] i32
    leaf_parent: jax.Array      # [L+1] i32
    leaf_value: jax.Array       # [L+1] f
    internal_value: jax.Array   # [L] f
    leaf_depth: jax.Array       # [L+1] i32
    leaf_count: jax.Array       # [L+1] i32
    num_leaves: jax.Array       # scalar i32


class GrowState(NamedTuple):
    tree: TreeArrays
    leaf_id: jax.Array          # [N] i32
    hist: jax.Array             # [K+1, F, B, 3] (last = dummy slot);
    #                             K = max_leaves (dense) or hist_slots (pool)
    leaf_sum_g: jax.Array       # [L+1] (last = dummy slot)
    leaf_sum_h: jax.Array       # [L+1]
    best_f: jax.Array           # [L+1, 8] float best-split fields
    best_i: jax.Array           # [L+1, 4] i32 best-split fields
    # histogram-pool bookkeeping (HistogramPool, reference
    # feature_histogram.hpp:275-398, re-designed as on-device LRU): only
    # carried when hist_slots bounds the pool; zero-size arrays otherwise
    leaf_slot: jax.Array        # [L+1] i32 slot of leaf's hist, -1 evicted
    slot_leaf: jax.Array        # [K+1] i32 leaf occupying slot, -1 free
    slot_used: jax.Array        # [K+1] i32 last-used scan step (LRU key)


# column layout of the packed per-leaf best-split state.  Packing the
# 11 BestSplit fields into two stacked arrays turns the per-split
# bookkeeping (2 leaves updated, 1 read) into 6 row-sized ops instead of
# ~33 scalar gathers/updates — on remote-attached TPUs every extra op in
# the sequential split chain costs launch latency.
BF_GAIN, BF_LG, BF_LH, BF_RG, BF_RH, BF_LOUT, BF_ROUT = range(7)
BI_FEAT, BI_THR, BI_LCNT, BI_RCNT = range(4)


def _pack_best(s: BestSplit, dtype):
    bf = jnp.stack([s.gain.astype(dtype), s.left_sum_g.astype(dtype),
                    s.left_sum_h.astype(dtype), s.right_sum_g.astype(dtype),
                    s.right_sum_h.astype(dtype), s.left_output.astype(dtype),
                    s.right_output.astype(dtype),
                    jnp.zeros((), dtype)])
    bi = jnp.stack([s.feature, s.threshold, s.left_count, s.right_count])
    return bf, bi


def _empty_tree(max_leaves: int, dtype) -> TreeArrays:
    L = max_leaves
    z_i = functools.partial(jnp.zeros, dtype=jnp.int32)
    z_f = functools.partial(jnp.zeros, dtype=dtype)
    return TreeArrays(
        split_feature=z_i(L), threshold_bin=z_i(L), split_gain=z_f(L),
        left_child=z_i(L), right_child=z_i(L),
        leaf_parent=jnp.full(L + 1, -1, dtype=jnp.int32),
        leaf_value=z_f(L + 1), internal_value=z_f(L),
        leaf_depth=jnp.ones(L + 1, dtype=jnp.int32),
        leaf_count=z_i(L + 1),
        num_leaves=jnp.int32(1),
    )


def _empty_best_packed(max_leaves: int, dtype):
    bf = jnp.zeros((max_leaves + 1, 8), dtype=dtype)
    bf = bf.at[:, BF_GAIN].set(K_MIN_SCORE)
    bi = jnp.zeros((max_leaves + 1, 4), dtype=jnp.int32)
    return bf, bi


def _reduce_best_over_features(s: BestSplit, f_offset, feature_axis: str
                               ) -> BestSplit:
    """Combine per-shard best splits into the global best, replicated.

    The TPU equivalent of FeatureParallelTreeLearner's
    Allreduce(SplitInfo::MaxReducer) (reference
    src/treelearner/feature_parallel_tree_learner.cpp:45-78 and
    split_info.hpp:56-104): max gain, ties broken by the SMALLER global
    feature index, so every shard picks the identical winner.
    """
    glob = s._replace(feature=s.feature + f_offset)
    gathered = jax.tree_util.tree_map(
        lambda a: jax.lax.all_gather(a, feature_axis), glob)
    mx = jnp.max(gathered.gain)
    eligible = gathered.gain == mx
    win = jnp.argmin(jnp.where(eligible, gathered.feature,
                               jnp.iinfo(jnp.int32).max))
    return jax.tree_util.tree_map(lambda a: a[win], gathered)


@contract.traced_pure
@contract.parity_oracle("the growth kernel under full-length masked "
                        "bagging: bag_rows<=0 falls through here — the "
                        "bit-parity oracle bag compaction is tested "
                        "against (PARITY.md §2.3)")
@functools.partial(
    jax.jit,
    static_argnames=("max_leaves", "max_bin", "params", "max_depth",
                     "row_chunk", "psum_axis", "feature_axis",
                     "voting_top_k", "hist_impl", "hist_agg", "num_shards",
                     "hist_slots", "compact", "ranged", "fused",
                     "hist_acc"))
def grow_tree(bins_t: jax.Array, grad: jax.Array, hess: jax.Array,
              bag_mask: jax.Array, feature_mask: jax.Array, *,
              max_leaves: int, max_bin: int, params: SplitParams,
              max_depth: int = -1, row_chunk: int = 0,
              psum_axis: Optional[str] = None,
              feature_axis: Optional[str] = None,
              voting_top_k: int = 0, hist_impl: str = "xla",
              hist_agg: str = "psum", num_shards: int = 0,
              hist_slots: int = 0, compact: int = 0, ranged: bool = False,
              fused: bool = False, hist_acc: str = "f32"):
    """Grow one leaf-wise tree. Returns (TreeArrays, leaf_id [N] i32).

    bins_t [F, N] uint8; grad/hess [N]; bag_mask [N] bool;
    feature_mask [F] bool. All per-split control flow is on-device.
    hist_impl: "xla" (portable one-hot matmul) or "pallas" (TPU radix
    kernel, f32, max_bin<=256, N % 8192 == 0).
    fused (pallas, serial only — config.hist_fused): per-split child
    sweeps run the fused histogram+gain kernels, which scan thresholds
    in-register on the VMEM-resident accumulators and emit per-feature
    best rows; find_best_split_fused finishes with an O(F) argmax.
    Bit-parity with fused=False (the retained two-op oracle) in
    interpret mode — the kernel runs the oracle's exact jnp scan on the
    exact accumulator values.
    hist_acc (pallas): "f32" (default, parity), "bf16" (bf16 operands /
    gh2 stream, f32 accumulate), "i32" (overflow-safe fixed-point
    integer accumulation, exact counts) — see hist_pallas.make_gh2_acc.
    psum_axis: mesh axis sharding rows (tree_learner=data).
    hist_slots (>0): bound histogram HBM to hist_slots live [F, B, 3]
    leaf histograms — the reference HistogramPool's role
    (feature_histogram.hpp:275-398) without its host LRU machinery: an
    on-device slot pool inside the scan, least-recently-used eviction,
    and a full recompute of the parent histogram when it was evicted
    (the reference recomputes evicted leaves the same way).  0 keeps the
    dense [max_leaves+1, F, B, 3] tensor (every leaf cached; exactly the
    subtraction-trick arithmetic of the reference's unbounded default,
    histogram_pool_size=-1).
    hist_agg (with psum_axis): "psum" all-reduces the full histogram
    tensor; "scatter" is the owner-computes protocol of the reference
    (ReduceScatter + per-owner FindBestThreshold,
    data_parallel_tree_learner.cpp:124-187): `psum_scatter` gives each
    shard the GLOBAL histograms of F/num_shards features, each shard
    scans only those, and an all-gather of the per-shard best
    candidates + argmax replaces Allreduce(SplitInfo::MaxReducer) —
    halving per-split ICI traffic vs "psum".  Needs static num_shards.
    feature_axis: mesh axis sharding features (tree_learner=feature) —
    bins_t/feature_mask hold this shard's features; rows are replicated;
    tree arrays come out replicated with GLOBAL feature indices.
    voting_top_k (>0, with psum_axis): tree_learner=voting — PV-Tree
    two-round voting (absent from the reference snapshot, SURVEY.md §2.9;
    design per the LightGBM paper): histograms stay shard-local, each
    shard votes its top-k features by local gain, and only the 2k
    vote-winning features' histograms are all-reduced, cutting per-split
    traffic from O(F*B) to O(2k*B).
    """
    f, n = bins_t.shape
    dtype = grad.dtype
    voting = voting_top_k > 0 and psum_axis is not None
    scatter = (hist_agg == "scatter" and psum_axis is not None
               and not voting)
    if scatter:
        assert feature_axis is None, "hist_agg=scatter excludes feature_axis"
        assert num_shards > 0, "hist_agg=scatter needs static num_shards"
        f_chunk = (f + num_shards - 1) // num_shards
        f_pad = f_chunk * num_shards
        my_off = (jax.lax.axis_index(psum_axis) * f_chunk).astype(jnp.int32)
        fmask_pad = jnp.pad(feature_mask, (0, f_pad - f))

    if feature_axis is not None:
        f_offset = (jax.lax.axis_index(feature_axis) * f).astype(jnp.int32)

    def psum(x):
        return jax.lax.psum(x, psum_axis) if psum_axis else x

    def best_of(hist, cnt, sg, sh):
        """find_best_split + cross-shard reduction.  In voting/scatter mode
        `hist` is shard-LOCAL; cnt/sg/sh are always global leaf stats."""
        if scatter:
            histp = jnp.pad(hist, ((0, f_pad - f), (0, 0), (0, 0)))
            mine = jax.lax.psum_scatter(histp, psum_axis,
                                        scatter_dimension=0, tiled=True)
            fm = jax.lax.dynamic_slice_in_dim(fmask_pad, my_off, f_chunk)
            s = find_best_split(mine, cnt, sg, sh, fm, params)
            return _reduce_best_over_features(s, my_off, psum_axis)
        if voting:
            # local scoring pass over local totals
            lsg = jnp.sum(hist[0, :, 0])
            lsh = jnp.sum(hist[0, :, 1])
            lcnt = jnp.round(jnp.sum(hist[0, :, 2])).astype(jnp.int32)
            gains_f, _ = per_feature_best(hist, lcnt, lsg, lsh,
                                          feature_mask, params)
            k = min(voting_top_k, f)
            topv, topi = jax.lax.top_k(gains_f, k)
            votes = jnp.zeros(f, dtype=jnp.float32).at[topi].add(
                jnp.where(topv > K_MIN_SCORE, 1.0, 0.0))
            votes = jax.lax.psum(votes, psum_axis)
            # global top-2k by votes, ties to the smaller feature index
            # (unique integer-valued keys keep top_k deterministic)
            k2 = min(2 * voting_top_k, f)
            key = votes * (f + 1) - jnp.arange(f, dtype=jnp.float32)
            cand = jax.lax.top_k(key, k2)[1].astype(jnp.int32)
            cand_hist = jax.lax.psum(hist[cand], psum_axis)
            s = find_best_split(cand_hist, cnt, sg, sh,
                                feature_mask[cand], params)
            return s._replace(feature=cand[s.feature])
        s = find_best_split(hist, cnt, sg, sh, feature_mask, params)
        if feature_axis is not None:
            s = _reduce_best_over_features(s, f_offset, feature_axis)
        return s

    def feature_go_right(feature, threshold):
        """Per-row `bin > threshold` for a GLOBAL feature index.

        Serial/data-parallel: read the local bin row.  Feature-parallel:
        the OWNER shard evaluates the comparison and broadcasts a packed
        [N/8] u8 bitmask over the feature axis (one shard contributes,
        psum replicates) — the reference's premise that every machine
        holds all rows (feature_parallel_tree_learner.cpp:45-78) means
        only the DECISION must move, and the reference moves 2 SplitInfos
        per split for the same reason.  Shipping the packed decision
        instead of the raw [N] i32 bin row (VERDICT r3 weak #4) cuts the
        per-split feature-axis traffic 32x (~4 MB -> ~128 KB at 1M
        rows)."""
        if feature_axis is None:
            return bins_t[feature].astype(jnp.int32) > threshold
        local = feature - f_offset
        owner = (local >= 0) & (local < f)
        row = jnp.where(owner,
                        bins_t[jnp.clip(local, 0, f - 1)].astype(jnp.int32),
                        0)
        gr = owner & (row > threshold)
        n8 = -(-n // 8) * 8
        bits = jnp.pad(gr, (0, n8 - n)).reshape(-1, 8)
        weights = (jnp.uint8(1) << jnp.arange(8, dtype=jnp.uint8))
        packed = jnp.sum(bits * weights[None, :], axis=1,
                         dtype=jnp.int32).astype(jnp.uint8)
        packed = jax.lax.psum(packed, feature_axis)
        unpacked = (packed[:, None] >> jnp.arange(8, dtype=jnp.uint8)) \
            & jnp.uint8(1)
        return unpacked.reshape(-1)[:n].astype(bool)

    # voting/scatter keep histograms shard-local (cross-shard reduction
    # happens inside best_of); plain psum all-reduces the full tensor
    hist_psum = (lambda x: x) if (voting or scatter) else psum

    ranged_on = (ranged and hist_impl == "pallas"
                 and feature_axis is None)
    # fused histogram+gain path (round 16, config.hist_fused): the
    # per-split children sweep through the *_fused Pallas kernels, which
    # run the best-split scan in-register on the VMEM-resident
    # accumulators and emit per-feature best rows — the two XLA
    # _split_scan passes per split disappear.  Serial-only: under
    # psum/scatter/voting/feature the histogram must cross shards BEFORE
    # the scan, and the small-leaf compaction path gathers its own rows.
    fused_on = (fused and hist_impl == "pallas" and psum_axis is None
                and feature_axis is None and not voting and not scatter
                and compact <= 0)
    if hist_impl == "pallas":
        from .hist_pallas import (PALLAS_ROW_BLOCK, fold_leaf_mask,
                                  leaf_histogram_blocklist,
                                  leaf_histogram_blocklist_fused,
                                  leaf_histogram_masked,
                                  leaf_histogram_masked_fused,
                                  make_gh2_acc)
        gh2, inv_scale = make_gh2_acc(grad, hess, hist_acc)
        # TPU runs the compiled kernel; CPU (tests) uses interpret mode
        interpret = jax.default_backend() == "cpu"
    if ranged_on:
        # Block-list sweeps (VERDICT r2 #1): per split, sweep ONLY the
        # row blocks that contain the target leaf's rows.  The occupancy
        # scan is one cheap [nblocks, B] reduction + a tiny argsort;
        # skipped blocks contribute exact +0.0f in the full sweep, so
        # the result is BIT-identical to it for the same row order.
        # Pays off when rows are leaf-clustered (the ordered-partition
        # mode in models/gbdt.py re-sorts rows by the previous tree's
        # leaves every few trees); never sweeps more than the full grid.
        # Under tree_learner=data (psum_axis set) everything here is
        # shard-LOCAL — blocks, occupancy, block list, re-sorts — except
        # the ladder-rung choice below and the histogram reduction the
        # other impls share (hist_psum).
        nblocks = n // PALLAS_ROW_BLOCK
        # static grid-size ladder: the per-call floor is ~grid_blocks x
        # the per-step bookkeeping, so deep (small) leaves dispatch to a
        # small-grid variant
        ladder = [g for g in (8, 32) if g < nblocks] + [nblocks]

        def _block_plan(leaf_eff, target):
            occ = (leaf_eff == target).reshape(
                nblocks, PALLAS_ROW_BLOCK).any(axis=1)
            n_occ = jnp.sum(occ).astype(jnp.int32)
            # occupied block ids first, ascending (stable argsort of the
            # complement keeps file order => full-sweep association)
            blist = jnp.argsort(jnp.where(occ, 0, 1).astype(jnp.int32),
                                stable=True).astype(jnp.int32)
            # SPMD-uniform rung (VERDICT r3 #2): the rung is picked from
            # the MAX occupancy over shards so every shard dispatches the
            # same compiled branch; each shard still sweeps only its OWN
            # occupied blocks (blist / n_occ stay shard-local)
            n_sel = (jax.lax.pmax(n_occ, psum_axis) if psum_axis
                     else n_occ)
            sel = jnp.int32(len(ladder) - 1)
            for i in range(len(ladder) - 2, -1, -1):
                sel = jnp.where(n_sel <= ladder[i], jnp.int32(i), sel)
            return blist, n_occ, sel

        def hist_leaf(leaf_id, target):
            leaf_eff = fold_leaf_mask(leaf_id, bag_mask)
            blist, n_occ, sel = _block_plan(leaf_eff, target)

            def mk(g):
                def branch(le, bl, na):
                    return leaf_histogram_blocklist(
                        bins_t, gh2, le, target, bl, na, max_bin=max_bin,
                        hist_acc=hist_acc, inv_scale=inv_scale,
                        grid_blocks=g, interpret=interpret).astype(dtype)
                return branch

            return hist_psum(jax.lax.switch(sel, [mk(g) for g in ladder],
                                            leaf_eff, blist, n_occ))

        if fused_on:
            def hist_best(leaf_id, target, parent_hist, s_stats, l_stats):
                leaf_eff = fold_leaf_mask(leaf_id, bag_mask)
                blist, n_occ, sel = _block_plan(leaf_eff, target)

                def mk(g):
                    def branch(le, bl, na):
                        h, pfs, pfl = leaf_histogram_blocklist_fused(
                            bins_t, gh2, le, target, bl, na, parent_hist,
                            feature_mask, s_stats, l_stats, inv_scale,
                            max_bin=max_bin, params=params,
                            hist_acc=hist_acc, grid_blocks=g,
                            interpret=interpret)
                        return h.astype(dtype), pfs, pfl
                    return branch

                return jax.lax.switch(sel, [mk(g) for g in ladder],
                                      leaf_eff, blist, n_occ)
    elif hist_impl == "pallas":
        def hist_leaf(leaf_id, target):
            leaf_eff = fold_leaf_mask(leaf_id, bag_mask)
            return hist_psum(leaf_histogram_masked(
                bins_t, gh2, leaf_eff, target, max_bin=max_bin,
                hist_acc=hist_acc, inv_scale=inv_scale,
                interpret=interpret).astype(dtype))

        if fused_on:
            def hist_best(leaf_id, target, parent_hist, s_stats, l_stats):
                leaf_eff = fold_leaf_mask(leaf_id, bag_mask)
                h, pfs, pfl = leaf_histogram_masked_fused(
                    bins_t, gh2, leaf_eff, target, parent_hist,
                    feature_mask, s_stats, l_stats, inv_scale,
                    max_bin=max_bin, params=params, hist_acc=hist_acc,
                    interpret=interpret)
                return h.astype(dtype), pfs, pfl
    else:
        def hist_leaf(leaf_id, target):
            gv = make_gvals(grad, hess, (leaf_id == target) & bag_mask, dtype)
            return hist_psum(leaf_histogram(bins_t, gv, max_bin=max_bin,
                                            row_chunk=row_chunk))

    # -- compacted small-leaf histograms (serial fast path) ------------
    # Profiling (BASELINE.md): full-row sweeps are ~90% of the fused
    # iteration, and every split sweeps all N rows for the SMALLER child
    # (O(N*num_leaves) row-touches per tree vs the reference's O(N*depth)
    # leaf-row partitions, data_partition.hpp).  Here the smaller child's
    # in-bag rows are compacted (order-preserving cumsum scatter, so
    # accumulation order matches the full sweep's row order) into the
    # smallest of a static capacity ladder [~N/2, /4, /16, /64] and only
    # that buffer is swept — near-leaf-proportional MXU work with static
    # shapes via lax.switch.  The top capacity can never overflow: the
    # smaller-by-bagged-count child has <= floor(bagged_n/2) <= n/2 rows.
    # Serial-only (a shard-local count could exceed a local capacity and
    # branch divergence would break SPMD collective pairing).
    compact_on = (compact > 0 and psum_axis is None
                  and feature_axis is None and not ranged_on)
    if compact_on:
        row_unit = 1
        if hist_impl == "pallas":
            from .hist_pallas import PALLAS_ROW_BLOCK
            row_unit = PALLAS_ROW_BLOCK

        def _round_up(x):
            return max(1, -(-x // row_unit)) * row_unit

        caps = [_round_up(compact)]
        while caps[-1] // 4 >= row_unit and len(caps) < 4:
            caps.append(_round_up(caps[-1] // 4))

        def _compact_idx(mask):
            pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
            slot = jnp.where(mask & (pos < caps[0]), pos, caps[0])
            buf = jnp.zeros(caps[0] + 1, jnp.int32).at[slot].set(
                jnp.arange(n, dtype=jnp.int32))
            return buf[:caps[0]]

        if hist_impl == "pallas":
            def _hist_rows(idx, cnt, cap):
                bins_c = jnp.take(bins_t, idx[:cap], axis=1)
                gh2_c = jnp.take(gh2, idx[:cap], axis=1)
                leaf_c = jnp.where(jnp.arange(cap) < cnt, 0, -1) \
                    .astype(jnp.int32)
                return leaf_histogram_masked(
                    bins_c, gh2_c, leaf_c, jnp.int32(0),
                    max_bin=max_bin, hist_acc=hist_acc,
                    inv_scale=inv_scale,
                    interpret=interpret).astype(dtype)
        else:
            def _hist_rows(idx, cnt, cap):
                bins_c = jnp.take(bins_t, idx[:cap], axis=1)
                gv = make_gvals(jnp.take(grad, idx[:cap]),
                                jnp.take(hess, idx[:cap]),
                                jnp.arange(cap) < cnt, dtype)
                return leaf_histogram(bins_c, gv, max_bin=max_bin,
                                      row_chunk=row_chunk)

        def hist_small(leaf_id, target, cnt):
            mask = (leaf_id == target) & bag_mask
            idx = _compact_idx(mask)
            # smallest capacity that fits cnt (capacities descend)
            sel = jnp.int32(0)
            for b, cap in enumerate(caps[1:], start=1):
                sel = jnp.where(cnt <= cap, jnp.int32(b), sel)
            branches = [functools.partial(_hist_rows, cap=cap)
                        for cap in caps]
            return jax.lax.switch(sel, branches, idx, cnt)
    else:
        def hist_small(leaf_id, target, cnt):
            return hist_leaf(leaf_id, target)

    def depth_gated(gain, depth):
        if max_depth > 0:
            return jnp.where(depth >= max_depth, K_MIN_SCORE, gain)
        return gain

    # ---- root ----
    root_hist = hist_leaf(jnp.zeros(n, dtype=jnp.int32), jnp.int32(0))
    # every row lands in exactly one bin of feature 0, so its histogram sums
    # are the root totals (LeafSplits::Init root sumup, leaf_splits.hpp:36-117);
    # in voting mode the hist is local, so all-reduce the three scalars
    # (the reference's root Allreduce, data_parallel_tree_learner.cpp:94-122)
    root_g = jnp.sum(root_hist[0, :, 0])
    root_h = jnp.sum(root_hist[0, :, 1])
    root_c = jnp.sum(root_hist[0, :, 2])
    if voting or scatter:
        root_g, root_h, root_c = (psum(root_g), psum(root_h), psum(root_c))
    root_cnt = jnp.round(root_c).astype(jnp.int32)

    tree = _empty_tree(max_leaves, dtype)
    tree = tree._replace(leaf_count=tree.leaf_count.at[0].set(root_cnt))
    best_f0, best_i0 = _empty_best_packed(max_leaves, dtype)
    root_best = best_of(root_hist, root_cnt, root_g, root_h)
    root_best = root_best._replace(
        gain=depth_gated(root_best.gain, jnp.int32(1)))
    rbf, rbi = _pack_best(root_best, dtype)
    best_f0 = best_f0.at[0].set(rbf)
    best_i0 = best_i0.at[0].set(rbi)

    pooled = 0 < hist_slots < max_leaves + 1
    K = hist_slots if pooled else max_leaves
    if pooled:
        leaf_slot0 = jnp.full(max_leaves + 1, -1, dtype=jnp.int32).at[0].set(0)
        slot_leaf0 = jnp.full(K + 1, -1, dtype=jnp.int32).at[0].set(0)
        slot_used0 = jnp.full(K + 1, -1, dtype=jnp.int32).at[0].set(0)
    else:   # zero-size placeholders keep the scan-state pytree uniform
        leaf_slot0 = slot_leaf0 = slot_used0 = jnp.zeros(0, dtype=jnp.int32)

    state = GrowState(
        tree=tree,
        leaf_id=jnp.zeros(n, dtype=jnp.int32),
        hist=jnp.zeros((K + 1, f, max_bin, 3), dtype=dtype)
            .at[0].set(root_hist),
        leaf_sum_g=jnp.zeros(max_leaves + 1, dtype=dtype).at[0].set(root_g),
        leaf_sum_h=jnp.zeros(max_leaves + 1, dtype=dtype).at[0].set(root_h),
        best_f=best_f0, best_i=best_i0,
        leaf_slot=leaf_slot0, slot_leaf=slot_leaf0, slot_used=slot_used0,
    )

    # Fixed-trip scan instead of lax.while_loop: a while_loop's per-
    # iteration continuation check serializes against the body's full
    # critical path and costs ~ms/step on remote-attached TPUs, ~8x the
    # body itself.  The scan always runs max_leaves-1 steps; once growth
    # stops (no positive gain / leaf budget reached) every update is
    # redirected to the DUMMY slot (index max_leaves for leaves, the last
    # node slot for nodes) so the real state passes through untouched —
    # preserving the reference's early-stop semantics
    # (serial_tree_learner.cpp:121-129) without a whole-state select.
    def step(st: GrowState, t):
        tree = st.tree
        # argmax over leaves; first max ⇒ smaller leaf index, matching
        # ArrayArgs::ArgMax over best_split_per_leaf_ (serial_tree_learner.cpp:121)
        bl = jnp.argmax(st.best_f[:max_leaves, BF_GAIN]).astype(jnp.int32)
        sf = st.best_f[bl]
        si = st.best_i[bl]
        s_gain = sf[BF_GAIN]
        s_feature = si[BI_FEAT]
        s_threshold = si[BI_THR]
        keep = (tree.num_leaves < max_leaves) & (s_gain > 0.0)

        node = tree.num_leaves - 1
        right = tree.num_leaves           # new leaf index
        # dummy-slot redirection: all writes of an inactive step land in
        # scratch entries that the output never reads
        wl = jnp.where(keep, bl, max_leaves)          # leaf-array writes
        wr = jnp.where(keep, right, max_leaves)
        wn = jnp.where(keep, node, max_leaves - 1)    # node-array writes
        parent = tree.leaf_parent[bl]

        # --- Tree::Split (reference src/io/tree.cpp:42-77) ---
        pidx = jnp.where(keep & (parent >= 0), parent, max_leaves - 1)
        lc = tree.left_child
        lc = lc.at[pidx].set(jnp.where(keep & (parent >= 0)
                                       & (lc[pidx] == ~bl), node, lc[pidx]))
        rc = tree.right_child
        rc = rc.at[pidx].set(jnp.where(keep & (parent >= 0)
                                       & (rc[pidx] == ~bl), node, rc[pidx]))
        lc = lc.at[wn].set(jnp.where(keep, ~bl, lc[wn]))
        rc = rc.at[wn].set(jnp.where(keep, ~right, rc[wn]))

        new_tree = TreeArrays(
            split_feature=tree.split_feature.at[wn].set(
                jnp.where(keep, s_feature, tree.split_feature[wn])),
            threshold_bin=tree.threshold_bin.at[wn].set(
                jnp.where(keep, s_threshold, tree.threshold_bin[wn])),
            split_gain=tree.split_gain.at[wn].set(
                jnp.where(keep, s_gain, tree.split_gain[wn])),
            left_child=lc, right_child=rc,
            leaf_parent=tree.leaf_parent.at[wl].set(node).at[wr].set(node),
            leaf_value=tree.leaf_value.at[wl].set(sf[BF_LOUT])
                                      .at[wr].set(sf[BF_ROUT]),
            internal_value=tree.internal_value.at[wn].set(
                jnp.where(keep, tree.leaf_value[bl],
                          tree.internal_value[wn])),
            leaf_depth=tree.leaf_depth
                .at[wr].set(tree.leaf_depth[bl] + 1)
                .at[wl].add(1),
            leaf_count=tree.leaf_count.at[wl].set(si[BI_LCNT])
                                      .at[wr].set(si[BI_RCNT]),
            num_leaves=tree.num_leaves + keep.astype(jnp.int32),
        )

        # --- partition: one vectorized compare (replaces DataPartition::Split,
        # src/treelearner/data_partition.hpp:84-132) ---
        go_right = (keep & (st.leaf_id == bl)
                    & feature_go_right(s_feature, s_threshold))
        leaf_id = jnp.where(go_right, right, st.leaf_id)

        # --- histograms: smaller child scanned, larger by subtraction ---
        left_is_smaller = si[BI_LCNT] <= si[BI_RCNT]
        small_leaf = jnp.where(left_is_smaller, bl, right)
        small_cnt = jnp.where(left_is_smaller, si[BI_LCNT], si[BI_RCNT])
        if pooled:
            # parent histogram from its pool slot, or a full recompute
            # when it was LRU-evicted (the reference recomputes evicted
            # leaves the same way, feature_histogram.hpp:275-398 +
            # serial_tree_learner.cpp BeforeFindBestSplit)
            parent_slot = st.leaf_slot[bl]
            parent_hist = jax.lax.cond(
                parent_slot >= 0,
                lambda: st.hist[jnp.clip(parent_slot, 0, K - 1)],
                lambda: hist_leaf(st.leaf_id, bl))
        else:
            parent_hist = st.hist[bl]
        if fused_on:
            # fused sweep + in-register gain scan: the kernel consumes
            # the parent block, sweeps the small child, and emits both
            # children's per-feature best rows alongside the histogram
            s_g = jnp.where(left_is_smaller, sf[BF_LG], sf[BF_RG])
            s_h = jnp.where(left_is_smaller, sf[BF_LH], sf[BF_RH])
            l_g = jnp.where(left_is_smaller, sf[BF_RG], sf[BF_LG])
            l_h = jnp.where(left_is_smaller, sf[BF_RH], sf[BF_LH])
            large_cnt = jnp.where(left_is_smaller, si[BI_RCNT],
                                  si[BI_LCNT])
            small_hist, pf_small, pf_large = hist_best(
                leaf_id, small_leaf, parent_hist,
                (small_cnt, s_g, s_h), (large_cnt, l_g, l_h))
        else:
            small_hist = hist_small(leaf_id, small_leaf, small_cnt)
        large_hist = parent_hist - small_hist
        left_hist = jnp.where(left_is_smaller, small_hist, large_hist)
        right_hist = jnp.where(left_is_smaller, large_hist, small_hist)
        if pooled:
            # slot allocation: the left child (which keeps leaf index bl)
            # reuses the parent's slot when cached, else takes the LRU
            # slot; the right child takes the LRU slot among the rest
            slot_l = jnp.where(
                parent_slot >= 0, parent_slot,
                jnp.argmin(st.slot_used[:K]).astype(jnp.int32))
            used_tmp = st.slot_used.at[jnp.clip(slot_l, 0, K - 1)].set(t)
            slot_r = jnp.argmin(used_tmp[:K]).astype(jnp.int32)
            wsl = jnp.where(keep, slot_l, K)      # dummy-slot redirection
            wsr = jnp.where(keep, slot_r, K)
            hist = st.hist.at[wsl].set(left_hist).at[wsr].set(right_hist)
            # drop the evicted occupants' mappings, then map the children
            # (ordering matters: when the parent's slot is reused its
            # occupant IS bl — cleared first, remapped after)
            evict_l = st.slot_leaf[jnp.clip(slot_l, 0, K - 1)]
            evict_r = st.slot_leaf[jnp.clip(slot_r, 0, K - 1)]
            leaf_slot = (
                st.leaf_slot
                .at[jnp.where(keep & (evict_l >= 0), evict_l,
                              max_leaves)].set(-1)
                .at[jnp.where(keep & (evict_r >= 0), evict_r,
                              max_leaves)].set(-1)
                .at[wl].set(jnp.where(keep, slot_l, -1))
                .at[wr].set(jnp.where(keep, slot_r, -1)))
            slot_leaf = st.slot_leaf.at[wsl].set(bl).at[wsr].set(right)
            slot_used = st.slot_used.at[wsl].set(t).at[wsr].set(t)
        else:
            hist = st.hist.at[wl].set(left_hist).at[wr].set(right_hist)
            leaf_slot, slot_leaf, slot_used = (st.leaf_slot, st.slot_leaf,
                                               st.slot_used)

        leaf_sum_g = st.leaf_sum_g.at[wl].set(sf[BF_LG]) \
                                  .at[wr].set(sf[BF_RG])
        leaf_sum_h = st.leaf_sum_h.at[wl].set(sf[BF_LH]) \
                                  .at[wr].set(sf[BF_RH])

        # --- best splits for the two children ---
        child_depth = new_tree.leaf_depth[bl]
        if fused_on:
            # finish from the kernel's per-feature rows: a tiny argmax
            # over [F, 8] instead of two full [F, B, 3] scan passes
            lpf = jnp.where(left_is_smaller, pf_small, pf_large)
            rpf = jnp.where(left_is_smaller, pf_large, pf_small)
            lbest = find_best_split_fused(lpf, sf[BF_LG], sf[BF_LH],
                                          params)
            rbest = find_best_split_fused(rpf, sf[BF_RG], sf[BF_RH],
                                          params)
        else:
            lbest = best_of(left_hist, si[BI_LCNT], sf[BF_LG], sf[BF_LH])
            rbest = best_of(right_hist, si[BI_RCNT], sf[BF_RG],
                            sf[BF_RH])
        lbf, lbi = _pack_best(lbest._replace(
            gain=depth_gated(lbest.gain, child_depth)), dtype)
        rbf, rbi = _pack_best(rbest._replace(
            gain=depth_gated(rbest.gain, child_depth)), dtype)
        best_f = st.best_f.at[wl].set(lbf).at[wr].set(rbf)
        best_i = st.best_i.at[wl].set(lbi).at[wr].set(rbi)

        return GrowState(tree=new_tree, leaf_id=leaf_id, hist=hist,
                         leaf_sum_g=leaf_sum_g, leaf_sum_h=leaf_sum_h,
                         best_f=best_f, best_i=best_i,
                         leaf_slot=leaf_slot, slot_leaf=slot_leaf,
                         slot_used=slot_used), None

    final, _ = jax.lax.scan(step, state,
                            jnp.arange(1, max_leaves, dtype=jnp.int32))
    return final.tree, final.leaf_id


@contract.traced_pure
def grow_tree_bagged(bins_t: jax.Array, grad: jax.Array, hess: jax.Array,
                     bag_mask: jax.Array, feature_mask: jax.Array, *,
                     bag_rows: int = 0, **grow_kw):
    """Bag-compacted grow_tree entry (the fused-path fast path when
    bagging leaves a fixed fraction of rows out of every tree).

    Rows arrive pre-arranged in-bag-first (models/gbdt.py
    _arrange_for_bag): every in-bag row lives in the static window
    [0, bag_rows), so histogram sweeps, the leaf_id partition compares
    and the whole grow scan run over bag_rows rows instead of N.
    `bag_rows` is a PYTHON int (static under jit — graftlint GL011
    guards this), so the window slice shapes are stable across
    re-bagging epochs and the executable never retraces.

    Out-of-bag tail rows no longer ride leaf_id through the scan: their
    leaf assignment comes from one vectorized binned descent over the
    complement — a cheap O(tail * depth) traversal traded for the
    dominant O(N * leaves) histogram cost, exactly the reference's
    two-path score update (partition fast path + OOB traversal,
    src/boosting/gbdt.cpp:162-167).  The returned leaf_id still covers
    ALL rows (window ids from the scan, tail ids from the descent; the
    two agree bit-for-bit with a full-row scan, which routes rows by
    the same compares).

    Under shard_map (psum_axis set) everything added here is
    shard-local — the descent has no collectives — so per-shard bag
    compaction preserves the psum pairing invariants untouched.

    bag_rows <= 0 or >= N falls through to the plain masked full sweep
    (the bit-parity oracle)."""
    n = bins_t.shape[1]
    if bag_rows <= 0 or bag_rows >= n:
        return grow_tree(bins_t, grad, hess, bag_mask, feature_mask,
                         **grow_kw)
    tree, leaf_w = grow_tree(bins_t[:, :bag_rows], grad[:bag_rows],
                             hess[:bag_rows], bag_mask[:bag_rows],
                             feature_mask, **grow_kw)
    oob = predict_leaf_binned(tree.split_feature, tree.threshold_bin,
                              tree.left_child, tree.right_child,
                              bins_t[:, bag_rows:])
    # a 1-leaf stump's all-zero child arrays make the bounded descent
    # return the dummy ~0 = -1; the scan's leaf_id keeps such rows at
    # leaf 0 (whose value drives the score update), so mirror it — the
    # two paths must agree row-for-row with the masked full sweep
    oob = jnp.maximum(oob, 0)
    return tree, jnp.concatenate([leaf_w, oob.astype(leaf_w.dtype)])
