"""Histogram construction — the #1 hot loop of histogram GBDT.

Replaces the reference's per-thread gather-accumulate
(DenseBin::ConstructHistogram, reference src/io/dense_bin.hpp:39-104) with a
TPU-friendly formulation: per-feature one-hot matmuls so the accumulation
runs on the MXU instead of relying on scatter (TPUs have no fast arbitrary
scatter).  Rows outside the target leaf / bag are masked by zeroing their
(grad, hess, count) triple, which preserves the reference's
"only rows of this leaf" semantics over a full sweep.

Layout: bins are stored feature-major [F, N] uint8 (the reference is also
column-major, include/LightGBM/feature.h) so each lax.map step streams one
contiguous feature row.

Row-count generality: N here is whatever window the caller sweeps — the
full padded row count, or the bag-compacted in-bag window (ops/grow.py
grow_tree_bagged), which under bagging is ~bagging_fraction * N.  Nothing
in this module assumes a particular N beyond the shapes it is handed.

A Pallas kernel with VMEM-blocked accumulation is the planned fast path for
large N; this XLA formulation is the portable baseline and the correctness
oracle for it.
"""

from __future__ import annotations

import functools

from ..utils.compile_cache import enable_compilation_cache

enable_compilation_cache()   # before any jit traces (was a package-import side effect)

import jax
import jax.numpy as jnp


@functools.partial(jax.jit, static_argnames=("max_bin", "row_chunk"))
def leaf_histogram(bins_t: jax.Array, gvals: jax.Array, *, max_bin: int,
                   row_chunk: int = 0) -> jax.Array:
    """hist[f, b] = sum over rows r with bins_t[f, r] == b of gvals[r, :].

    bins_t: [F, N] uint8/uint16 binned features
    gvals:  [N, 3] accumulator triples (grad, hess, count-weight), already
            masked (zeroed) for rows outside the active leaf / bag.
    Returns [F, B, 3] in gvals.dtype.
    """
    f, n = bins_t.shape
    dt = gvals.dtype
    ar = jnp.arange(max_bin, dtype=bins_t.dtype)

    if row_chunk and row_chunk < n:
        pad = (-n) % row_chunk
        if pad:
            bins_p = jnp.pad(bins_t, ((0, 0), (0, pad)))
            gv_p = jnp.pad(gvals, ((0, pad), (0, 0)))
        else:
            bins_p, gv_p = bins_t, gvals
        nchunks = bins_p.shape[1] // row_chunk
        bins_c = bins_p.reshape(f, nchunks, row_chunk).transpose(1, 0, 2)
        gv_c = gv_p.reshape(nchunks, row_chunk, 3)

        def chunk_step(acc, inp):
            bc, gc = inp

            def per_feature(bf):
                onehot = (bf[:, None] == ar[None, :]).astype(dt)
                return jnp.einsum("rb,rc->bc", onehot, gc,
                                  preferred_element_type=dt)

            return acc + jax.lax.map(per_feature, bc), None

        init = jnp.zeros((f, max_bin, 3), dtype=dt)
        hist, _ = jax.lax.scan(chunk_step, init, (bins_c, gv_c))
        return hist

    def per_feature(bf):
        onehot = (bf[:, None] == ar[None, :]).astype(dt)
        return jnp.einsum("rb,rc->bc", onehot, gvals,
                          preferred_element_type=dt)

    return jax.lax.map(per_feature, bins_t)


def make_gvals(grad: jax.Array, hess: jax.Array, mask: jax.Array,
               dtype) -> jax.Array:
    """Stack masked (grad, hess, 1) accumulator triples: [N, 3]."""
    m = mask.astype(dtype)
    return jnp.stack([grad.astype(dtype) * m, hess.astype(dtype) * m, m],
                     axis=-1)
