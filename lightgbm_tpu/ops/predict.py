"""Vectorized tree traversal.

Replaces the reference's per-row pointer-chasing (Tree::GetLeaf,
include/LightGBM/tree.h:166-189) with a data-parallel iterate: all rows step
down one level per loop iteration via gathers — the loop is over tree depth,
not over rows, so the work is [N]-wide vector ops that XLA maps onto the VPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@jax.jit
def predict_leaf_binned(split_feature: jax.Array, threshold_bin: jax.Array,
                        left_child: jax.Array, right_child: jax.Array,
                        bins_t: jax.Array) -> jax.Array:
    """Leaf index per row from binned features.

    Mirrors Tree::GetLeaf over BinIterators (tree.h:166-177): node>=0 walks,
    leaves are encoded ~leaf in the child arrays. Returns [N] i32 leaf ids.
    """
    n = bins_t.shape[1]
    node = jnp.zeros(n, dtype=jnp.int32)

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        idx = jnp.maximum(node, 0)
        feat = split_feature[idx]
        thr = threshold_bin[idx]
        val = bins_t[feat, jnp.arange(n)].astype(jnp.int32)
        nxt = jnp.where(val <= thr, left_child[idx], right_child[idx])
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.while_loop(cond, body, node)
    return ~node


@jax.jit
def predict_leaf_raw(split_feature_real: jax.Array, threshold: jax.Array,
                     left_child: jax.Array, right_child: jax.Array,
                     x: jax.Array) -> jax.Array:
    """Leaf index per row from raw feature values (Tree::GetLeaf, tree.h:179-189).

    x: [N, F_total] float; split rule `value <= threshold` goes left.
    """
    n = x.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        idx = jnp.maximum(node, 0)
        feat = split_feature_real[idx]
        thr = threshold[idx]
        val = x[jnp.arange(n), feat]
        nxt = jnp.where(val <= thr, left_child[idx], right_child[idx])
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.while_loop(cond, body, node)
    return ~node
