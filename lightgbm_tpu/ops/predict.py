"""Vectorized tree traversal.

Replaces the reference's per-row pointer-chasing (Tree::GetLeaf,
include/LightGBM/tree.h:166-189) with a data-parallel iterate: all rows step
down one level per loop iteration via gathers — the loop is over tree depth,
not over rows, so the work is [N]-wide vector ops that XLA maps onto the VPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


@jax.jit
def predict_leaf_binned(split_feature: jax.Array, threshold_bin: jax.Array,
                        left_child: jax.Array, right_child: jax.Array,
                        bins_t: jax.Array) -> jax.Array:
    """Leaf index per row from binned features.

    Mirrors Tree::GetLeaf over BinIterators (tree.h:166-177): node>=0 walks,
    leaves are encoded ~leaf in the child arrays. Returns [N] i32 leaf ids.
    """
    n = bins_t.shape[1]
    node = jnp.zeros(n, dtype=jnp.int32)

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        idx = jnp.maximum(node, 0)
        feat = split_feature[idx]
        thr = threshold_bin[idx]
        val = bins_t[feat, jnp.arange(n)].astype(jnp.int32)
        nxt = jnp.where(val <= thr, left_child[idx], right_child[idx])
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.while_loop(cond, body, node)
    return ~node


@jax.jit
def predict_leaf_raw(split_feature_real: jax.Array, threshold: jax.Array,
                     left_child: jax.Array, right_child: jax.Array,
                     x: jax.Array) -> jax.Array:
    """Leaf index per row from raw feature values (Tree::GetLeaf, tree.h:179-189).

    x: [N, F_total] float; split rule `value <= threshold` goes left.
    """
    n = x.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        idx = jnp.maximum(node, 0)
        feat = split_feature_real[idx]
        thr = threshold[idx]
        val = x[jnp.arange(n), feat]
        nxt = jnp.where(val <= thr, left_child[idx], right_child[idx])
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.while_loop(cond, body, node)
    return ~node


def split_hi_lo(a: "np.ndarray"):
    """Order-isomorphic encoding of f64 values as (hi, lo) uint32 pairs.

    The device never needs x64: each double's bit pattern is mapped on
    the HOST to a uint64 whose unsigned order equals the IEEE-754 total
    order (negatives bit-flipped, positives sign-bit-set — the classic
    radix-sortable-float transform), then split into two uint32 words.
    Lexicographic compare of the pairs reproduces the f64 `<=` EXACTLY
    for every finite value, ±1e308 (the parser's inf mapping), and
    subnormals — no precision loss, int ops only on device.  -0.0 is
    normalized to +0.0 first (IEEE `<=` treats them equal); NaN maps to
    the largest key, so `value <= threshold` is false and NaN rows take
    the right child, matching the reference's failed double compare
    (tree.h:179-189)."""
    import numpy as np
    a = np.asarray(a, dtype=np.float64)
    a = np.where(a == 0.0, 0.0, a)          # -0.0 -> +0.0
    bits = a.view(np.uint64)
    neg = bits >> np.uint64(63)
    key = bits ^ np.where(neg.astype(bool),
                          np.uint64(0xFFFFFFFFFFFFFFFF),
                          np.uint64(0x8000000000000000))
    key = np.where(np.isnan(a), np.uint64(0xFFFFFFFFFFFFFFFF), key)
    hi = (key >> np.uint64(32)).astype(np.uint32)
    lo = (key & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    return hi, lo


def _leaf_hi_lo_inner(split_feature_real, thr_hi, thr_lo, left_child,
                      right_child, x_hi, x_lo):
    """One tree's descent for all rows: value <= threshold via exact
    lexicographic uint32-pair compare of split_hi_lo keys."""
    n = x_hi.shape[0]
    rows = jnp.arange(n)
    node = jnp.zeros(n, dtype=jnp.int32)

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        idx = jnp.maximum(node, 0)
        feat = split_feature_real[idx]
        vh = x_hi[rows, feat]
        vl = x_lo[rows, feat]
        th = thr_hi[idx]
        tl = thr_lo[idx]
        left = (vh < th) | ((vh == th) & (vl <= tl))
        nxt = jnp.where(left, left_child[idx], right_child[idx])
        return jnp.where(node >= 0, nxt, node)

    return ~jax.lax.while_loop(cond, body, node)


@jax.jit
def predict_leaf_stacked(split_feature_real: jax.Array, thr_hi: jax.Array,
                         thr_lo: jax.Array, left_child: jax.Array,
                         right_child: jax.Array, x_hi: jax.Array,
                         x_lo: jax.Array) -> jax.Array:
    """Whole-model leaf indices on device.

    The reference predicts row-by-row, tree-by-tree on the host
    (predictor.hpp:35-70 over Tree::GetLeaf, tree.h:179-189); here every
    tree's node arrays are stacked into [T, M] tensors and a lax.scan
    walks the model while all rows descend each tree data-parallel on
    the VPU.  Only the traversal runs on device — score accumulation
    happens on the host in f64 from the returned indices (gbdt.py
    predict_raw), keeping output formatting byte-identical to the
    reference under any backend/x64 configuration.

    split_feature_real/thr_hi/thr_lo/left_child/right_child: [T, M]
    padded node arrays (a 1-leaf stump encodes left_child[0] == ~0 so
    every row lands in leaf 0); x_hi/x_lo: [C, F_total] f32 pair.
    Returns [C, T] i32 leaf indices.
    """

    def per_tree(_, t):
        sf_t, th_t, tl_t, lc_t, rc_t = t
        return None, _leaf_hi_lo_inner(sf_t, th_t, tl_t, lc_t, rc_t,
                                       x_hi, x_lo)

    _, leaves = jax.lax.scan(
        per_tree, None,
        (split_feature_real, thr_hi, thr_lo, left_child, right_child))
    return leaves.T
