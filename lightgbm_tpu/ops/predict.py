"""Vectorized tree traversal.

Replaces the reference's per-row pointer-chasing (Tree::GetLeaf,
include/LightGBM/tree.h:166-189) with a data-parallel iterate: all rows step
down one level per loop iteration via gathers — the loop is over tree depth,
not over rows, so the work is [N]-wide vector ops that XLA maps onto the VPU.
"""

from __future__ import annotations

import functools

from ..utils.compile_cache import enable_compilation_cache

enable_compilation_cache()   # before any jit traces (was a package-import side effect)

import jax
import jax.numpy as jnp

# host-side exact-compare helpers live in predict_host.py (pure numpy,
# importable from jax-free lanes); re-exported here for the historical
# import site every route uses
from .predict_host import (matmul_host_arrays, order_key,  # noqa: F401
                           rank_encode, split_hi_lo,
                           threshold_rank_tables)


@jax.jit
def predict_leaf_binned(split_feature: jax.Array, threshold_bin: jax.Array,
                        left_child: jax.Array, right_child: jax.Array,
                        bins_t: jax.Array) -> jax.Array:
    """Leaf index per row from binned features.

    Mirrors Tree::GetLeaf over BinIterators (tree.h:166-177): node>=0 walks,
    leaves are encoded ~leaf in the child arrays. Returns [N] i32 leaf ids.
    """
    n = bins_t.shape[1]
    node = jnp.zeros(n, dtype=jnp.int32)
    # a well-formed tree reaches its leaf in < num_nodes steps; the bound
    # makes degenerate inputs (an unsplit stump's all-zero child arrays,
    # e.g. an untouched DART-bank row) terminate at node 0 -> ~0 = -1,
    # which gathers the zero-valued dummy leaf slot instead of spinning
    # the while_loop forever
    max_steps = split_feature.shape[0] + 1

    def cond(carry):
        i, node = carry
        return (i < max_steps) & jnp.any(node >= 0)

    def body(carry):
        i, node = carry
        idx = jnp.maximum(node, 0)
        feat = split_feature[idx]
        thr = threshold_bin[idx]
        val = bins_t[feat, jnp.arange(n)].astype(jnp.int32)
        nxt = jnp.where(val <= thr, left_child[idx], right_child[idx])
        return i + 1, jnp.where(node >= 0, nxt, node)

    _, node = jax.lax.while_loop(cond, body, (jnp.int32(0), node))
    return ~node


@jax.jit
def predict_leaf_raw(split_feature_real: jax.Array, threshold: jax.Array,
                     left_child: jax.Array, right_child: jax.Array,
                     x: jax.Array) -> jax.Array:
    """Leaf index per row from raw feature values (Tree::GetLeaf, tree.h:179-189).

    x: [N, F_total] float; split rule `value <= threshold` goes left.
    """
    n = x.shape[0]
    node = jnp.zeros(n, dtype=jnp.int32)

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        idx = jnp.maximum(node, 0)
        feat = split_feature_real[idx]
        thr = threshold[idx]
        val = x[jnp.arange(n), feat]
        nxt = jnp.where(val <= thr, left_child[idx], right_child[idx])
        return jnp.where(node >= 0, nxt, node)

    node = jax.lax.while_loop(cond, body, node)
    return ~node


def _leaf_hi_lo_inner(split_feature_real, thr_hi, thr_lo, left_child,
                      right_child, x_hi, x_lo):
    """One tree's descent for all rows: value <= threshold via exact
    lexicographic uint32-pair compare of split_hi_lo keys."""
    n = x_hi.shape[0]
    rows = jnp.arange(n)
    node = jnp.zeros(n, dtype=jnp.int32)

    def cond(node):
        return jnp.any(node >= 0)

    def body(node):
        idx = jnp.maximum(node, 0)
        feat = split_feature_real[idx]
        vh = x_hi[rows, feat]
        vl = x_lo[rows, feat]
        th = thr_hi[idx]
        tl = thr_lo[idx]
        left = (vh < th) | ((vh == th) & (vl <= tl))
        nxt = jnp.where(left, left_child[idx], right_child[idx])
        return jnp.where(node >= 0, nxt, node)

    return ~jax.lax.while_loop(cond, body, node)


@functools.partial(jax.jit, static_argnames=("tree_block",))
def predict_leaf_matmul(sel: jax.Array, thr_code: jax.Array,
                        path_pos: jax.Array, path_neg: jax.Array,
                        leaf_depth: jax.Array, x_code: jax.Array,
                        *, tree_block: int) -> jax.Array:
    """Gather-free whole-model leaf indices — the TPU-native predictor.

    Pointer-chasing descents (tree.h:179-189) need one random gather per
    level per tree, which serializes on TPU.  Instead the traversal is
    re-expressed as matmuls + an argmax:

      1. node comparisons: the host rank-encodes each value against its
         feature's model-threshold table (rank_encode — exact f64
         order), a one-hot selection matmul routes the codes to nodes,
         and `code <= node_rank` reproduces `value <= threshold`:
         cmp [C, T*M].
      2. leaf resolution: a leaf is reached iff every node on its path
         branched toward it.  With path matrices P± [T, M, L] (+1 node
         sends the leaf left, -1 right), score = cmp @ P+ + (1-cmp) @ P-
         counts satisfied path conditions; score - depth is 0 exactly
         for the reached leaf and <= -1 otherwise, so an argmax over L
         recovers the leaf with no data-dependent memory access.

    Trees process in blocks of `tree_block` via lax.scan to bound the
    [C, tb*M] temporaries.  sel [Ftot, T*M] f32; thr_code [T*M] f32;
    path_pos/neg [T, M, L]; leaf_depth [T, L] (+inf padding slots);
    x_code [C, Ftot] uint16.  Returns [C, T] i32.
    """
    c, ftot = x_code.shape
    t_total = path_pos.shape[0]
    m = path_pos.shape[1]
    nb = t_total // tree_block

    sel_b = sel.reshape(ftot, nb, tree_block * m).transpose(1, 0, 2)
    thr_b = thr_code.reshape(nb, tree_block * m)
    pos_b = path_pos.reshape(nb, tree_block, m, -1)
    neg_b = path_neg.reshape(nb, tree_block, m, -1)
    dep_b = leaf_depth.reshape(nb, tree_block, 1, -1)
    xf = x_code.astype(jnp.float32)              # [C, Ftot], ints < 2^16

    def block(_, args):
        s, th, pp, pn, dp = args
        # HIGHEST precision: codes are integers up to 65535 and the
        # TPU's default bf16 matmul (8 mantissa bits) would corrupt
        # them; the 3-pass f32 mode is exact for one-hot selections
        xsel = jnp.einsum("cf,fm->cm", xf, s,
                          precision=jax.lax.Precision.HIGHEST,
                          preferred_element_type=jnp.float32)
        cmp = (xsel <= th[None]).astype(jnp.float32)         # [C, tb*m]
        cmp = cmp.reshape(c, tree_block, m).transpose(1, 0, 2)
        score = (jnp.einsum("tcm,tml->tcl", cmp, pp,
                            preferred_element_type=jnp.float32)
                 + jnp.einsum("tcm,tml->tcl", 1.0 - cmp, pn,
                              preferred_element_type=jnp.float32))
        leaf = jnp.argmax(score - dp, axis=-1)               # [tb, C]
        # uint8 when it fits: the [C, T] result is the bulk of the
        # device->host traffic (the predict bottleneck over a remote
        # tunnel) and leaves index at most max_leaves <= 256 slots
        out_dt = jnp.uint8 if path_pos.shape[2] <= 256 else jnp.int32
        return None, leaf.astype(out_dt)

    _, leaves = jax.lax.scan(block, None, (sel_b, thr_b, pos_b, neg_b,
                                           dep_b))
    return leaves.reshape(t_total, c).T


@functools.partial(jax.jit, static_argnames=("num_class",))
def accumulate_scores(leaves: jax.Array, leaf_values: jax.Array,
                      *, num_class: int) -> jax.Array:
    """On-device f64 score accumulation in boosting order.

    EXACTLY the host loop of GBDT.predict_raw (`out[i % k] +=
    leaf_values[i, leaves[:, i]]` for i ascending — the reference
    predictor's += tree->Predict, predictor.hpp:35-70): a lax.scan over
    trees performs the same sequence of f64 additions per row, so the
    result is bit-identical to the host path while the device->host
    transfer shrinks from [C, T] leaf indices to [K, C] doubles — the
    remote-tunnel predict bottleneck.  Requires x64 (the CLI predict
    path enables it on accelerators).

    leaves [C, T] int; leaf_values [T, L] f64.  Returns [K, C] f64.
    """
    c = leaves.shape[0]
    t = leaf_values.shape[0]
    # graftlint: disable=GL003 -- f64 IS the contract here: this kernel
    # replicates the host's double score accumulation bit-for-bit and
    # only runs when the CLI predict path enabled x64 (cli.init_predict)
    out = jnp.zeros((num_class, c), dtype=jnp.float64)

    def step(s, inp):
        i, lv_t, leaf_t = inp
        return s.at[i % num_class].add(lv_t[leaf_t]), None

    out, _ = jax.lax.scan(
        step, out,
        (jnp.arange(t, dtype=jnp.int32), leaf_values,
         leaves.T.astype(jnp.int32)))
    return out


@jax.jit
def predict_leaf_stacked(split_feature_real: jax.Array, thr_hi: jax.Array,
                         thr_lo: jax.Array, left_child: jax.Array,
                         right_child: jax.Array, x_hi: jax.Array,
                         x_lo: jax.Array) -> jax.Array:
    """Whole-model leaf indices on device.

    The reference predicts row-by-row, tree-by-tree on the host
    (predictor.hpp:35-70 over Tree::GetLeaf, tree.h:179-189); here every
    tree's node arrays are stacked into [T, M] tensors and a lax.scan
    walks the model while all rows descend each tree data-parallel on
    the VPU.  Only the traversal runs on device — score accumulation
    happens on the host in f64 from the returned indices (gbdt.py
    predict_raw), keeping output formatting byte-identical to the
    reference under any backend/x64 configuration.

    split_feature_real/thr_hi/thr_lo/left_child/right_child: [T, M]
    padded node arrays (a 1-leaf stump encodes left_child[0] == ~0 so
    every row lands in leaf 0); x_hi/x_lo: [C, F_total] f32 pair.
    Returns [C, T] i32 leaf indices.
    """

    def per_tree(_, t):
        sf_t, th_t, tl_t, lc_t, rc_t = t
        return None, _leaf_hi_lo_inner(sf_t, th_t, tl_t, lc_t, rc_t,
                                       x_hi, x_lo)

    _, leaves = jax.lax.scan(
        per_tree, None,
        (split_feature_real, thr_hi, thr_lo, left_child, right_child))
    return leaves.T
