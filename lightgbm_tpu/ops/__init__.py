"""lightgbm_tpu.ops"""
