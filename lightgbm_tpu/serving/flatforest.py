"""Flat quantized node-array engine — the serving fast path's forest.

The low-latency lane answers single-digit-row requests synchronously on
the host, so its per-request cost must be a handful of vector ops, not
a per-tree Python loop.  At warm() the forest compiles ONCE into a
contiguous struct-of-arrays node table:

    feat[n]          int32   split feature id of flat node n
    thr_rank[n]      int32   bin-rank-encoded threshold (see below)
    left[n]/right[n] int32   flat child index; ~leaf_id when the child
                             is a leaf (the models/tree.py wire rule)
    default_left[n]  bool    direction a missing/NaN value takes
                             (derived: always False for this model
                             family — NaN order-keys above every
                             threshold, tree.h:179-189)

Trees concatenate back-to-back (`root[t]` indexes tree t's root; an
unsplit stump stores root[t] = ~0 so every row lands in leaf 0), and
descent is a vectorized numpy loop over [N, T] node cursors — one
gather + compare per tree LEVEL, not per node, exactly the stacked
device kernel's shape but on the host and jax-free.

Thresholds are not stored as f64: each node holds the RANK of its
threshold in its feature's sorted threshold-key table, built by the
SAME pack builder the device matmul route uses
(ops/predict_host.threshold_rank_tables, the shared half of
matmul_host_arrays).  Request values rank-encode against those same
tables (ops/predict_host.rank_encode), and

    code(x) <= rank(thr)   <=>   x <= thr     (exact f64 total order)

so the flat engine's leaf indices are identical to the descent and
matmul routes' BY CONSTRUCTION — one threshold source, three routes,
no drift (tests/test_serving_fastlane.py pins the bytes against both
the batch path and task=predict).
"""

from __future__ import annotations

__jax_free__ = True

from typing import List

import numpy as np

from ..analysis.contracts import contract
from ..models.tree import Tree
from ..ops.predict_host import (rank_encode, split_hi_lo,
                                threshold_rank_tables)


class FlatForest:
    """The compiled flat node table + its rank tables (immutable)."""

    __slots__ = ("feat", "thr_rank", "left", "right", "default_left",
                 "root", "tables", "num_trees", "max_depth")

    def __init__(self, feat: np.ndarray, thr_rank: np.ndarray,
                 left: np.ndarray, right: np.ndarray,
                 default_left: np.ndarray, root: np.ndarray,
                 tables: List[np.ndarray], max_depth: int):
        self.feat = feat
        self.thr_rank = thr_rank
        self.left = left
        self.right = right
        self.default_left = default_left
        self.root = root
        self.tables = tables
        self.num_trees = root.shape[0]
        self.max_depth = max_depth

    def encode(self, x: np.ndarray) -> np.ndarray:
        """[N, F] f64 -> [N, F] int32 rank codes against the model's
        threshold tables (the same encoding the matmul route uploads,
        minus its uint16 size cap — host compares never overflow)."""
        xh, xl = split_hi_lo(x)
        return rank_encode(xh, xl, self.tables, dtype=np.int32)

    def leaves(self, x: np.ndarray) -> np.ndarray:
        """[N, F] f64 rows -> [N, T] int64 leaf indices."""
        return self.leaves_coded(self.encode(x))

    def leaves_coded(self, code: np.ndarray) -> np.ndarray:
        """Vectorized descent over the flat table: all rows x all trees
        step down one level per iteration (<= max_depth iterations)."""
        n = code.shape[0]
        t = self.num_trees
        # node cursor per (row, tree): >= 0 is a flat node index still
        # descending, negative is ~leaf_id done
        node = np.repeat(self.root[None, :], n, axis=0)
        for _ in range(self.max_depth):
            active = node >= 0
            if not active.any():
                break
            idx = np.where(active, node, 0)
            f = self.feat[idx]                               # [N, T]
            v = np.take_along_axis(code, f, axis=1)          # [N, T]
            nxt = np.where(v <= self.thr_rank[idx],
                           self.left[idx], self.right[idx])
            node = np.where(active, nxt, node)
        return (~node).astype(np.int64)

    def nbytes(self) -> int:
        """Resident size of the node table + rank tables (fleet-sizing
        introspection: /healthz reports it per warm model)."""
        n = sum(int(a.nbytes) for a in
                (self.feat, self.thr_rank, self.left, self.right,
                 self.default_left, self.root))
        return n + sum(int(tb.nbytes) for tb in self.tables)


@contract.jax_free
def compile_flat(trees: List[Tree], sf: np.ndarray, thr: np.ndarray,
                 lc: np.ndarray, rc: np.ndarray, ftot: int) -> FlatForest:
    """[T, M] padded node arrays -> the contiguous flat table.

    @contract.jax_free: this compiler runs inside warm() on the serving
    fast path of a backend=native process — graftcheck GC002 verifies
    it can never pull jax into that process.  sf/thr/lc/rc are the
    forest's `_flat_arrays()` (the SAME arrays the device packs build
    from); ftot is the model feature width."""
    th, tl = split_hi_lo(thr)
    tables, key, _ = threshold_rank_tables(trees, sf, th, tl, ftot)
    ni = np.array([tr.num_leaves - 1 for tr in trees], dtype=np.int64)
    off = np.zeros(len(trees) + 1, dtype=np.int64)
    np.cumsum(ni, out=off[1:])
    total = int(off[-1])
    feat = np.zeros(total, dtype=np.int32)
    thr_rank = np.zeros(total, dtype=np.int32)
    left = np.full(total, -1, dtype=np.int32)
    right = np.full(total, -1, dtype=np.int32)
    root = np.full(len(trees), -1, dtype=np.int32)   # stump: ~0 -> leaf 0
    max_depth = 0
    for i in range(len(trees)):
        n = int(ni[i])
        if n == 0:
            continue
        o = int(off[i])
        root[i] = o
        s = slice(o, o + n)
        feat[s] = sf[i, :n]
        for j in range(n):
            thr_rank[o + j] = np.searchsorted(
                tables[sf[i, j]], key[i, j], side="left")
        # rebase internal children to flat indices; leaves stay ~leaf_id
        l = lc[i, :n].astype(np.int32)
        r = rc[i, :n].astype(np.int32)
        left[s] = np.where(l >= 0, l + o, l)
        right[s] = np.where(r >= 0, r + o, r)
        # deepest compare chain bounds the descent loop
        stack = [(0, 1)]
        while stack:
            node, d = stack.pop()
            if d > max_depth:
                max_depth = d
            for child in (int(lc[i, node]), int(rc[i, node])):
                if child >= 0:
                    stack.append((child, d + 1))
    # default direction: the route a NaN value's code takes at each
    # node.  NaN order-keys to the maximum uint64, so its rank lands
    # past every table entry and the compare sends it right — recorded
    # per node so the layout carries the bit explicitly instead of
    # implying it
    nan_code = np.array([len(tables[int(f)]) for f in feat],
                        dtype=np.int64)
    default_left = nan_code <= thr_rank
    return FlatForest(feat, thr_rank, left, right, default_left, root,
                      tables, max_depth)
