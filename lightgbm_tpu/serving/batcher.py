"""Dynamic micro-batching for the serving path.

Concurrent requests coalesce into one forest dispatch under
(max_batch_rows, batch_timeout_ms) — the adaptive-batching scheme of
Clipper (Crankshaw et al., NSDI'17): the first queued request opens a
batching window; the batch dispatches when it reaches max_batch_rows or
when the window expires, and whatever queued while a previous batch was
running rides the next dispatch even at timeout 0.  Per-request results
scatter back bit-identical to what each request would get alone — every
predict kernel here is row-independent, so batch composition can never
change a row's bytes (tests/test_serving_batcher.py pins it).

Requests larger than max_batch_rows split into row segments at submit
and reassemble in order.  Batches group by an opaque `key` (the server
uses (forest, mode)): requests for different modes — or for the
pre-swap forest during a hot reload — never share a dispatch.
"""

from __future__ import annotations

__jax_free__ = True

import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..analysis.contracts import contract


class RowsPayload:
    """A parsed [N, F] float batch segment (JSON requests, or text
    requests once parsed for the JAX engine)."""

    kind = "rows"

    def __init__(self, feats: np.ndarray):
        self.feats = feats

    @property
    def nrows(self) -> int:
        return self.feats.shape[0]

    def split(self, k: int) -> Tuple["RowsPayload", "RowsPayload"]:
        return RowsPayload(self.feats[:k]), RowsPayload(self.feats[k:])


class TextPayload:
    """Raw request lines (header already stripped) for the host
    engine's fused native pass; splits on non-blank-line boundaries so
    each segment is a well-formed chunk."""

    kind = "text"

    def __init__(self, text: bytes, fmt: str, sep: str,
                 nrows: Optional[int] = None):
        self.text = text
        self.fmt = fmt
        self.sep = sep
        self.nrows = (count_rows(text) if nrows is None else nrows)

    def split(self, k: int) -> Tuple["TextPayload", "TextPayload"]:
        cut = _row_offset(self.text, k)
        return (TextPayload(self.text[:cut], self.fmt, self.sep, k),
                TextPayload(self.text[cut:], self.fmt, self.sep,
                            self.nrows - k))


Payload = Union[RowsPayload, TextPayload]


def count_rows(text: bytes) -> int:
    """Non-blank line count — the native scanner's row rule (a line
    needs at least one non-EOL character)."""
    return sum(1 for ln in text.split(b"\n") if ln.strip(b"\r"))


def _row_offset(text: bytes, k: int) -> int:
    """Byte offset just past the k-th non-blank line."""
    pos = 0
    seen = 0
    while seen < k:
        eol = text.find(b"\n", pos)
        end = len(text) if eol < 0 else eol + 1
        if text[pos:end].strip(b"\r\n"):
            seen += 1
        pos = end
        if eol < 0:
            break
    return pos


class BatcherClosed(RuntimeError):
    """submit() after shutdown(): the server is draining."""


class _Item:
    __slots__ = ("key", "payload", "done", "result", "error", "enq_t")

    def __init__(self, key: Any, payload: "Payload"):
        self.key = key
        self.payload = payload
        self.done = threading.Event()
        self.result: Any = None
        self.error: Optional[BaseException] = None
        self.enq_t = time.monotonic()


class MicroBatcher:
    """run_batch(key, [payload, ...]) -> [result, ...] executes one
    coalesced dispatch; on_batch(n_items, n_rows) observes each dispatch
    (metrics hook)."""

    def __init__(self, run_batch: Callable[[object, Sequence], List],
                 max_batch_rows: int, batch_timeout_ms: float,
                 on_batch: Optional[Callable[[int, int], None]] = None):
        if max_batch_rows < 1:
            raise ValueError("max_batch_rows must be >= 1")
        self._run = run_batch
        self.max_batch_rows = int(max_batch_rows)
        self.timeout_s = max(0.0, float(batch_timeout_ms)) / 1000.0
        self._on_batch = on_batch
        self._cv = threading.Condition()
        self._queue: List[_Item] = []
        self._stopped = False
        self._worker = threading.Thread(target=self._loop,
                                        name="serve-batcher", daemon=True)
        self._worker.start()

    # -- client side -----------------------------------------------------
    def submit(self, key: Any, payload: "Payload") -> List[Any]:
        """Enqueue one request (split into <= max_batch_rows segments),
        block until every segment completes, return the per-segment
        results in order."""
        segments: List[Payload] = []
        while payload.nrows > self.max_batch_rows:
            head, payload = payload.split(self.max_batch_rows)
            segments.append(head)
        segments.append(payload)
        items = [_Item(key, p) for p in segments]
        with self._cv:
            if self._stopped:
                raise BatcherClosed("batcher is shut down")
            self._queue.extend(items)
            self._cv.notify_all()
        for it in items:
            it.done.wait()
        for it in items:
            if it.error is not None:
                raise it.error
        return [it.result for it in items]

    def queue_depth(self) -> int:
        """Segments waiting for a dispatch right now (the /metrics
        gauge that makes the lane routing decision observable: a deep
        queue is exactly the state the fast lane exists to bypass)."""
        with self._cv:
            return len(self._queue)

    # -- worker side -----------------------------------------------------
    @contract.locked_by("_cv")
    def _take_batch(self) -> List[_Item]:
        """Called with the lock held (graftcheck GC004 verifies every
        call site); returns the next dispatch (blocks through the
        batching window) or [] at shutdown."""
        while not self._queue:
            if self._stopped:
                return []
            self._cv.wait()
        key = self._queue[0].key
        deadline = self._queue[0].enq_t + self.timeout_s
        while True:
            batch, rows, rest = [], 0, []
            for it in self._queue:
                if (it.key == key and
                        (not batch or
                         rows + it.payload.nrows <= self.max_batch_rows)):
                    batch.append(it)
                    rows += it.payload.nrows
                else:
                    rest.append(it)
            if rows >= self.max_batch_rows or self._stopped:
                self._queue = rest
                return batch
            wait = deadline - time.monotonic()
            if wait <= 0:
                self._queue = rest
                return batch
            self._cv.wait(wait)

    def _loop(self) -> None:
        while True:
            with self._cv:
                batch = self._take_batch()
            if not batch:
                with self._cv:
                    if self._stopped and not self._queue:
                        return
                continue
            try:
                results = self._run(batch[0].key,
                                    [it.payload for it in batch])
                if len(results) != len(batch):
                    raise RuntimeError(
                        "run_batch returned %d results for %d items"
                        % (len(results), len(batch)))
                for it, res in zip(batch, results):
                    # a BaseException element fails ONLY its own item
                    # (e.g. one malformed request inside a coalesced
                    # dispatch must not poison its neighbors)
                    if isinstance(res, BaseException):
                        it.error = res
                    else:
                        it.result = res
            except BaseException as ex:  # propagate to every waiter
                for it in batch:
                    it.error = ex
            finally:
                if self._on_batch is not None:
                    try:
                        self._on_batch(
                            len(batch),
                            sum(it.payload.nrows for it in batch))
                    except Exception:
                        pass
                for it in batch:
                    it.done.set()

    # -- lifecycle -------------------------------------------------------
    def shutdown(self, timeout: float = 30.0) -> None:
        """Graceful drain: refuse new submits, finish everything queued,
        stop the worker."""
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        self._worker.join(timeout)
