"""SO_REUSEPORT multi-process serving front-end.

The single ServingServer is a stdlib HTTP loop behind the GIL: one
process tops out near one core no matter how many handler threads it
spawns.  Production RPS wants processes.  This module runs N worker
processes (`serve_workers`), each the EXISTING ServingServer with its
own warm forest/fleet, all bound to ONE listen port with SO_REUSEPORT —
the kernel load-balances accepted connections across the workers, so no
userspace proxy hop and no shared accept lock.

Workers are plain subprocesses running `python -m
lightgbm_tpu.serving.frontend <cfg.json> <idx> <port>` — a fresh
interpreter per worker (no forked JAX runtime state; each worker warms
its own device forest), independent of how the supervisor itself was
started (CLI, pytest, embedding).

Supervisor duties:
  - pick/reserve the port (serve_port=0 resolves once, workers inherit)
  - spawn workers and detect death + respawn (the `frontend.spawn`
    faultpoint makes spawn failures chaos-testable; a crash loop backs
    off instead of spinning hot)
  - fan SIGTERM/SIGINT out to every worker and wait for each one's
    graceful drain, so no in-flight request is dropped at shutdown

Each worker tags its /healthz and /metrics with its (index, pid) —
repeated scrapes land on different workers (SO_REUSEPORT picks per
connection), so a prober sees the whole fleet's liveness.
"""

from __future__ import annotations

__jax_free__ = True

import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional

from ..config import Config
from ..resilience.backoff import Backoff
from ..resilience.faults import faultpoint
from ..utils import log

RESPAWN_BACKOFF_S = 0.5
RESPAWN_BACKOFF_MAX_S = 30.0
#: one curve for both crash-loop flavors (pre-ready strikes and
#: post-ready fast deaths) — the shared resilience/backoff helper, so
#: the respawn throttle cannot drift from the connect/deploy retries
_RESPAWN_CURVE = Backoff(base_s=RESPAWN_BACKOFF_S,
                         cap_s=RESPAWN_BACKOFF_MAX_S)
#: consecutive never-became-ready deaths per slot before the supervisor
#: gives up — but ONLY while NO worker has ever signaled readiness (a
#: broken model/config at startup should exit with the diagnostic, like
#: the single-process server does; once the fleet has been healthy,
#: respawns retry forever).  "Ready" is an explicit event handshake —
#: the worker touches its per-slot ready file once its server is
#: listening — NOT a wall-clock age check: under heavy host contention
#: a crash-looping worker can take arbitrarily long to start Python and
#: die, and a time-based classifier misread that as stability (the
#: pre-round-16 flake in test_frontend_startup_crash_loop_gives_up).
STARTUP_CRASH_LIMIT = 3

#: a worker that dies within this long of its spawn DESPITE having
#: completed the readiness handshake throttles its slot's respawns
#: (exponential, same ceiling as the unready path).  Wall clock here
#: paces sleeps ONLY — it never classifies stability or counts toward
#: the give-up, so the contention flake the handshake fixed cannot
#: come back through it (worst case: a healthy respawn waits a bit).
POST_READY_FAST_S = 2.0

#: repo/package parent directory — prepended to the workers' PYTHONPATH
#: so `python -m lightgbm_tpu.serving.frontend` resolves even when the
#: supervisor itself ran from a source checkout without installation
_PKG_PARENT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _worker_main(cfg: Config, idx: int, port: int,
                 ready_path: Optional[str] = None) -> None:
    """Body of one front-end worker process (fresh interpreter, so this
    re-applies the per-process setup the CLI would have done — log
    level, fault schedule, device platform)."""
    log.set_level_from_verbosity(cfg.verbose)
    if cfg.faults:
        from ..resilience.faults import configure
        configure(cfg.faults)
    if cfg.serve_backend != "native" and cfg.device_type == "cpu":
        # mirror cli.Application._apply_device_type: must run before
        # any JAX backend initializes in this fresh process
        import jax
        # graftlint: disable=GL007 -- _worker_main IS a process entry
        # point (spawned fresh): it re-applies the CLI's device_type in
        # its own interpreter before any backend initializes, exactly
        # like cli.Application._apply_device_type does for task=serve
        jax.config.update("jax_platforms", "cpu")
    from .server import ServingServer, run_until_signal
    cfg = dataclasses.replace(cfg, serve_port=port)
    server = ServingServer(cfg, reuse_port=True, worker_index=idx)
    log.info("serve worker %d (pid %d) listening on port %d"
             % (idx, os.getpid(), port))
    if ready_path:
        # readiness handshake: the model parsed, the forest warmed and
        # the socket is listening — only now does the supervisor count
        # this slot as stable (see STARTUP_CRASH_LIMIT).  A marker
        # file, not a pipe: survives supervisor embedding styles and
        # costs one stat per monitor sweep.
        with open(ready_path, "w") as rf:
            rf.write(str(os.getpid()))
    run_until_signal(server)


def worker_entry(argv: List[str]) -> int:
    """`python -m lightgbm_tpu.serving.frontend <cfg.json> <idx>
    <port> [ready_file]` — the subprocess entry the supervisor
    spawns."""
    if len(argv) not in (3, 4):
        log.warning("usage: python -m lightgbm_tpu.serving.frontend "
                    "<cfg.json> <worker_idx> <port> [ready_file]")
        return 2
    with open(argv[0]) as f:
        cfg = Config(**json.load(f))
    _worker_main(cfg, int(argv[1]), int(argv[2]),
                 argv[3] if len(argv) == 4 else None)
    return 0


class Frontend:
    """Supervisor for N SO_REUSEPORT ServingServer worker processes."""

    def __init__(self, cfg: Config):
        if cfg.serve_workers < 2:
            raise ValueError("Frontend wants serve_workers >= 2; use "
                             "ServingServer for a single process")
        if not hasattr(socket, "SO_REUSEPORT"):
            log.fatal("serve_workers > 1 needs SO_REUSEPORT, which "
                      "this platform does not provide")
        self.cfg = cfg
        self.num_workers = int(cfg.serve_workers)
        # supervision runs on the main thread; the lock makes the
        # worker-table/drain-flag stores safe against embedding callers
        # (and keeps the serving lock discipline uniform, GL006)
        self._lock = threading.Lock()
        self._workers: List[Optional[subprocess.Popen]] = \
            [None] * self.num_workers
        self._spawned_at: List[float] = [0.0] * self.num_workers
        self._fast_deaths: List[int] = [0] * self.num_workers
        self._ever_stable = False
        self._draining = False
        self._reserve: Optional[socket.socket] = None
        self._cfg_path: Optional[str] = None
        self._ready_dir: Optional[str] = None
        self.port = cfg.serve_port

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        """Reserve the port, persist the worker config, spawn every
        worker."""
        # bound-but-not-listening + SO_REUSEPORT reserves the port for
        # the workers without joining the kernel's accept distribution
        # (only LISTENING sockets receive connections)
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        s.bind((self.cfg.serve_host, self.cfg.serve_port))
        fd, cfg_path = tempfile.mkstemp(prefix="lgbm_serve_cfg_",
                                        suffix=".json")
        with os.fdopen(fd, "w") as f:
            json.dump(dataclasses.asdict(self.cfg), f)
        ready_dir = tempfile.mkdtemp(prefix="lgbm_serve_ready_")
        with self._lock:
            self._reserve = s
            self.port = s.getsockname()[1]
            self._cfg_path = cfg_path
            self._ready_dir = ready_dir
        for idx in range(self.num_workers):
            self._spawn(idx)
        log.info("Front-end: %d workers on http://%s:%d (pids %s), "
                 "low-latency lane %s"
                 % (self.num_workers, self.cfg.serve_host, self.port,
                    ",".join(str(p.pid) for p in self._workers
                             if p is not None),
                    self.cfg.serve_low_latency))

    def _ready_path(self, idx: int) -> str:
        assert self._ready_dir is not None
        return os.path.join(self._ready_dir, "worker_%d.ready" % idx)

    def _is_ready(self, idx: int) -> bool:
        """Has this slot's CURRENT worker completed the readiness
        handshake (server listening, marker file written)?"""
        return (self._ready_dir is not None
                and os.path.exists(self._ready_path(idx)))

    def _spawn(self, idx: int) -> None:
        # the spawn seam is chaos-testable: a schedule can fail the
        # Nth (re)spawn to prove the supervisor survives and retries
        faultpoint("frontend.spawn")
        assert self._cfg_path is not None
        # clear the slot's previous handshake: readiness must come from
        # THIS worker, not a dead predecessor's stale marker
        try:
            os.unlink(self._ready_path(idx))
        except OSError:
            pass
        env = dict(os.environ)
        env["PYTHONPATH"] = (_PKG_PARENT + os.pathsep
                             + env.get("PYTHONPATH", ""))
        proc = subprocess.Popen(
            [sys.executable, "-m", "lightgbm_tpu.serving.frontend",
             self._cfg_path, str(idx), str(self.port),
             self._ready_path(idx)],
            env=env)
        with self._lock:
            self._workers[idx] = proc
            self._spawned_at[idx] = time.monotonic()

    def worker_pids(self) -> List[int]:
        return [p.pid for p in self._workers if p is not None]

    # -- supervision -----------------------------------------------------
    def _monitor_once(self, timeout: float = 1.0) -> None:
        """Poll the workers; respawn what died (unless draining).  A
        worker that died WITHOUT completing its readiness handshake is
        crash-looping — back off EXPONENTIALLY so a broken model/config
        does not spin the host at 100% respawning, and if the fleet has
        NEVER been ready (no worker ever wrote its ready marker) give
        up after STARTUP_CRASH_LIMIT strikes per slot: a typo'd
        input_model should exit with the worker's diagnostic, exactly
        like the single-process server does.  Readiness is the event
        handshake from _worker_main, never a wall-clock age — a slow
        host cannot promote a crash-looper to 'stable', nor demote a
        healthy-but-slow startup to a strike."""
        died = False
        for idx, proc in enumerate(list(self._workers)):
            if proc is None or self._draining:
                continue
            ready = self._is_ready(idx)
            code = proc.poll()
            if code is None:
                if ready:
                    with self._lock:
                        self._ever_stable = True
                        # the post-ready throttle counter clears only
                        # once the worker has SURVIVED the fast window
                        # — an alive sweep landing between a 0.2 s
                        # handshake and a 1.5 s crash must not reset
                        # the escalation (pacing only, like the rest
                        # of the wall-clock use here)
                        if (time.monotonic() - self._spawned_at[idx]
                                >= POST_READY_FAST_S):
                            self._fast_deaths[idx] = 0
                continue
            died = True
            # re-sample AFTER poll observed the death: a worker that
            # wrote its marker and exited between the two calls above
            # must not be misread as a pre-ready strike (the marker
            # state is final once the process is dead)
            ready = ready or self._is_ready(idx)
            fast = not ready   # died before ever serving = a strike
            throttle = 0
            if ready:
                # the worker completed its handshake before dying — the
                # fleet WAS healthy (credit it even when the death fell
                # between two sweeps), so this death never counts toward
                # the startup give-up.  It still THROTTLES: a worker
                # that keeps crashing moments after becoming ready
                # would otherwise respawn at full interpreter-spawn
                # speed forever — back its slot off exponentially
                # (pacing only; see POST_READY_FAST_S).
                fast_post = (time.monotonic() - self._spawned_at[idx]
                             < POST_READY_FAST_S)
                with self._lock:
                    self._ever_stable = True
                    if fast_post:
                        self._fast_deaths[idx] += 1
                        throttle = self._fast_deaths[idx]
                    else:
                        self._fast_deaths[idx] = 0
            log.warning("serve worker %d (pid %s) died (exit %s)%s — "
                        "respawning"
                        % (idx, proc.pid, code,
                           " before its readiness handshake (crash-"
                           "loop backoff)" if fast else ""))
            if fast:
                with self._lock:
                    self._fast_deaths[idx] += 1
                    throttle = self._fast_deaths[idx]
                    hopeless = not self._ever_stable and all(
                        n >= STARTUP_CRASH_LIMIT
                        for n in self._fast_deaths)
                if hopeless:
                    log.fatal(
                        "every serve worker crash-looped %d times at "
                        "startup (see the worker diagnostics above) — "
                        "giving up instead of respawning forever"
                        % STARTUP_CRASH_LIMIT)
            if throttle:
                # one backoff curve for both crash-loop flavors
                # (pre-ready strikes and post-ready fast deaths)
                time.sleep(_RESPAWN_CURVE.delay(throttle))
            try:
                self._spawn(idx)
            except Exception as ex:
                # an injected (or real) spawn failure: keep the rest of
                # the fleet serving, retry this slot on the next sweep
                with self._lock:
                    self._workers[idx] = None
                log.warning("serve worker %d respawn failed (%s: %s); "
                            "retrying" % (idx, type(ex).__name__, ex))
        if not died:
            time.sleep(timeout)

    def _sweep_empty_slots(self) -> None:
        if self._draining:
            return
        for idx, proc in enumerate(self._workers):
            if proc is None:
                try:
                    self._spawn(idx)
                except Exception as ex:
                    log.warning("serve worker %d respawn failed "
                                "(%s: %s); retrying"
                                % (idx, type(ex).__name__, ex))

    def shutdown(self, drain_timeout: float = 30.0) -> None:
        """SIGTERM fan-out + graceful join: every worker drains its
        in-flight requests (ServingServer.shutdown inside the worker);
        stragglers past the timeout are killed."""
        with self._lock:
            self._draining = True
        for proc in self._workers:
            if proc is not None and proc.poll() is None:
                try:
                    proc.terminate()   # SIGTERM: worker drains
                except OSError:
                    pass
        deadline = time.monotonic() + drain_timeout
        for proc in self._workers:
            if proc is None:
                continue
            try:
                proc.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                log.warning("serve worker pid %s did not drain in %gs; "
                            "killing" % (proc.pid, drain_timeout))
                proc.kill()
                try:
                    proc.wait(5.0)
                except subprocess.TimeoutExpired:
                    pass
        if self._reserve is not None:
            self._reserve.close()
            with self._lock:
                self._reserve = None
        if self._cfg_path is not None:
            try:
                os.unlink(self._cfg_path)
            except OSError:
                pass
            with self._lock:
                self._cfg_path = None
        if self._ready_dir is not None:
            import shutil
            shutil.rmtree(self._ready_dir, ignore_errors=True)
            with self._lock:
                self._ready_dir = None

    def run_forever(self) -> None:
        """Supervise until SIGTERM/SIGINT, then fan out the drain."""
        stop = threading.Event()

        def _on_signal(signum: int, frame: Any) -> None:
            log.info("Signal %d: draining %d workers..."
                     % (signum, self.num_workers))
            stop.set()

        prev: Dict[int, Any] = {}
        for sig in (signal.SIGTERM, signal.SIGINT):
            prev[sig] = signal.signal(sig, _on_signal)
        try:
            while not stop.is_set():
                self._monitor_once(timeout=0.5)
                self._sweep_empty_slots()
        finally:
            for sig, h in prev.items():
                signal.signal(sig, h)
            self.shutdown()
            log.info("Front-end drained, exiting")


def frontend_forever(cfg: Config) -> None:
    """CLI entry (task=serve with serve_workers > 1)."""
    fe = Frontend(cfg)
    fe.start()
    fe.run_forever()


if __name__ == "__main__":   # pragma: no cover - subprocess entry
    sys.exit(worker_entry(sys.argv[1:]))
