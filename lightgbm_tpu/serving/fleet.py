"""Multi-model fleet: N hot models behind an LRU + age warm pool.

One server process can hold many models warm at once — the per-tenant
shape production serving actually runs: the default model answers
`/predict`, `/predict?model=<path>` routes to any REGISTERED model
(loading + warming it on first use), and the warm pool bounds how many
forests stay resident two ways: LRU capacity (`serve_fleet_max_models`)
and idle age (`serve_fleet_evict_age_s` — a warm model untouched that
long drops at the next pool access).  Registered models past either
bound re-warm on demand; the default model is pinned and never evicted.

Cold loads warm LAZILY (forest.warm(lazy=True)): the flat table and
host packs build immediately — the low-latency lane serves the very
first hit — while device bucket executables compile on the first routed
batch (the jit cache keys on shapes, so same-shaped fleet models reuse
already-compiled executables).  That keeps a cold hit to parse + pack
cost, which is what lets the pool scale toward thousands of per-tenant
models instead of 4.

Batches can never coalesce across models: the batcher keys on the
ServingForest itself, whose __eq__/__hash__ compare the EXPLICIT
identity (content sha, per-process instance number) — a reload
mid-flight yields a new instance, so in-flight rows finish on the old
forest and new rows batch on the new one (tests/test_serving_fleet.py
pins it).

Eviction is GC-safe: forests are immutable after warm(), and in-flight
batches hold their forest through the batch key, so an evicted forest
finishes its dispatches before it is collected.
"""

from __future__ import annotations

__jax_free__ = True

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from ..config import Config
from ..utils import log
from .forest import ServingForest, load_forest


class UnknownModelError(KeyError):
    """/predict?model= named a path that was never registered."""


class ModelFleet:
    """LRU warm pool of ServingForests, keyed by model path.

    The default model (cfg.input_model / the preloaded forest) is
    pinned; extra models register via cfg.serve_models, /reload, or
    register().  All pool mutation happens under `_lock`; the slow
    parse+warm of a miss runs under `_load_lock` OUTSIDE the pool lock,
    so hits keep serving while a cold model warms.
    """

    def __init__(self, cfg: Config, default_forest: ServingForest):
        self.cfg = cfg
        self.max_models = int(cfg.serve_fleet_max_models)
        self.evict_age_s = float(cfg.serve_fleet_evict_age_s)
        self._lock = threading.Lock()        # pool + registry state
        self._load_lock = threading.Lock()   # serializes cold loads
        default_path = default_forest.source
        self._default_path = default_path
        # last pool access per path (monotonic), for age eviction
        self._last_used: Dict[str, float] = {
            default_path: time.monotonic()}
        # path -> warm forest, in LRU order (last = most recent)
        self._pool: "OrderedDict[str, ServingForest]" = OrderedDict()
        self._pool[default_path] = default_forest
        # registered paths (the allowed /predict?model= set); values are
        # unused — an OrderedDict keeps registration order for listings
        self._registered: "OrderedDict[str, bool]" = OrderedDict()
        self._registered[default_path] = True
        for path in (cfg.serve_models or "").split(","):
            path = path.strip()
            if path:
                self._registered[path] = True

    # -- lookup ----------------------------------------------------------
    @property
    def default_path(self) -> str:
        with self._lock:
            return self._default_path

    def default(self) -> ServingForest:
        with self._lock:
            forest = self._pool[self._default_path]
            self._pool.move_to_end(self._default_path)
            self._last_used[self._default_path] = time.monotonic()
            return forest

    def contains(self, forest: ServingForest) -> bool:
        """Is this exact forest instance currently pooled?  (The
        circuit breaker only counts failures of live forests.)"""
        with self._lock:
            return any(f is forest for f in self._pool.values())

    def get(self, path: Optional[str] = None) -> ServingForest:
        """The warm forest for `path` (default model when None).
        Unregistered paths raise UnknownModelError — serving must not
        read arbitrary files off a query parameter."""
        if path is None or path == "":
            return self.default()
        with self._lock:
            if path not in self._registered:
                raise UnknownModelError(path)
            self._evict_stale()
            forest = self._pool.get(path)
            if forest is not None:
                self._pool.move_to_end(path)
                self._last_used[path] = time.monotonic()
                return forest
        return self._load(path)

    # -- mutation --------------------------------------------------------
    def register(self, path: str) -> None:
        """Allow `path` for /predict?model= (no load yet)."""
        with self._lock:
            self._registered[path] = True

    def reload(self, path: str, make_default: bool = False,
               loader: Any = None, register: bool = False) -> ServingForest:
        """Parse + warm a FRESH forest for `path` off to the side, then
        swap it into the pool atomically (in-flight batches keep keying
        on the old instance).  make_default also repoints the default
        model — the single-model /reload semantics.  register=True is
        the deploy agent's challenger PUSH: the path enters the
        registry and warms WITHOUT becoming default (shadow traffic via
        /predict?model= first; promotion is a later make_default call).
        Both are operator-initiated BODY forms over HTTP — the in-place
        query form (make_default=False, register=False) only refreshes
        an ALREADY-registered entry: a typo'd /reload?model= is a 400,
        not a silent allow-list expansion.  Any failure propagates
        BEFORE the swap, so the old forest keeps serving."""
        if not make_default and not register:
            with self._lock:
                if path not in self._registered:
                    raise UnknownModelError(path)
        fresh = (loader or self._load_fresh)(path)
        with self._lock:
            self._registered[path] = True
            self._pool[path] = fresh
            self._pool.move_to_end(path)
            self._last_used[path] = time.monotonic()
            if make_default:
                self._default_path = path
            self._evict_stale()
            self._evict_over_capacity()
        return fresh

    def _load(self, path: str) -> ServingForest:
        """Cold-miss load: serialized so N concurrent first requests
        for one model parse it once."""
        with self._load_lock:
            with self._lock:
                forest = self._pool.get(path)
                if forest is not None:
                    self._pool.move_to_end(path)
                    return forest
            fresh = self._load_fresh(path)
            with self._lock:
                self._pool[path] = fresh
                self._pool.move_to_end(path)
                self._last_used[path] = time.monotonic()
                self._evict_stale()
                self._evict_over_capacity()
            return fresh

    def _load_fresh(self, path: str) -> ServingForest:
        cfg = self.cfg
        forest = load_forest(path,
                             num_model_predict=cfg.num_model_predict,
                             backend=cfg.serve_backend,
                             matmul=cfg.serve_matmul,
                             matmul_min_rows=cfg.serve_matmul_min_rows)
        # lazy warm: flat table + host packs NOW (the fast lane serves
        # the first hit), device buckets on first routed batch — the
        # cold-hit cost stays bounded at thousand-model fleet scale.
        # Operator paths that want eager buckets (startup preload,
        # /reload) call warm() again themselves.
        forest.warm(cfg.serve_max_batch_rows, lazy=True)
        log.info("Fleet: lazily warmed %s (%d trees, sha %s)"
                 % (path, forest.num_models, forest.content_sha[:12]))
        return forest

    def _evict_over_capacity(self) -> None:
        """Called with _lock held: drop least-recently-used non-default
        forests past max_models.  Their model paths STAY registered —
        the next request re-warms them (LRU warm pool, not an allow-list
        change)."""
        while len(self._pool) > self.max_models:
            victim = next((p for p in self._pool
                           if p != self._default_path), None)
            if victim is None:
                return
            evicted = self._pool.pop(victim)
            self._last_used.pop(victim, None)
            log.info("Fleet: evicted %s (sha %s) from the warm pool"
                     % (victim, evicted.content_sha[:12]))

    def _evict_stale(self) -> None:
        """Called with _lock held: age eviction — non-default forests
        idle past serve_fleet_evict_age_s drop from the pool (still
        registered; the next hit lazily re-warms).  At per-tenant scale
        LRU capacity alone keeps dead tenants resident for hours; age
        is the bound that actually frees their node tables."""
        if self.evict_age_s <= 0:
            return
        now = time.monotonic()
        stale = [p for p in self._pool
                 if p != self._default_path
                 and now - self._last_used.get(p, now) > self.evict_age_s]
        for victim in stale:
            evicted = self._pool.pop(victim)
            self._last_used.pop(victim, None)
            log.info("Fleet: evicted %s (sha %s) — idle past %.3gs"
                     % (victim, evicted.content_sha[:12],
                        self.evict_age_s))

    # -- introspection ---------------------------------------------------
    def warm_models(self) -> List[ServingForest]:
        with self._lock:
            return list(self._pool.values())

    def registered_paths(self) -> List[str]:
        with self._lock:
            return list(self._registered)

    def info(self) -> List[Dict[str, Any]]:
        """Per-model listing for /healthz and /metrics: every registered
        model, warm ones with their full forest info."""
        with self._lock:
            default = self._default_path
            entries = [(p, self._pool.get(p)) for p in self._registered]
        out: List[Dict[str, Any]] = []
        for path, forest in entries:
            if forest is None:
                out.append({"source": path, "warm": False,
                            "default": path == default})
            else:
                doc = forest.info()
                doc["warm"] = True
                doc["default"] = path == default
                out.append(doc)
        return out
